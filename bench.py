"""Benchmark: fused single-chip Llama-3-8B decode throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md: its deployment of record is
Llama-3-8B layer-split across a Titan X Pascal + M1 Max over an ngrok tunnel,
tokens/sec measured at runtime but never published — master.rs:57-65). With
no published baseline to divide by, ``vs_baseline`` reports the fraction of
the *HBM-bandwidth roofline* for this chip and model (ideal decode tok/s =
HBM bytes/s / model bytes; the closer to 1.0 the better). That makes the
number comparable across rounds and meaningful in absolute terms.

Knobs (env):
  CAKE_BENCH_PRESET  8b (default) | small | tiny  — model size
  CAKE_BENCH_STEPS   timed decode steps (default 128)
  CAKE_BENCH_SEQ     KV capacity (default 512)
  CAKE_BENCH_QUANT   int8 | int4 — quantize linear weights (per-channel
                     symmetric; int4 is packed two-per-byte)
  CAKE_BENCH_MULTISTEP  fused decode steps per dispatch (default 16; 1 =
                        one program per token like the reference's loop).
                        Measured on v5e (small preset): 1 -> 16% of the HBM
                        roofline, 8 -> 59%, 16 -> 70%, 64 -> 78%.
  CAKE_BENCH_OBS=1   decode tok/s with observability off vs on (tracer +
                     flight recorder) through the generator hot path;
                     emits the overhead percentage (`make perf-smoke`
                     bounds the disabled-path micro-cost), plus a second
                     row repeating the off/on comparison through the
                     HTTP serve plane where tracing mints per-request
                     spans (reqtrace) — target within 3% of untraced.
  CAKE_BENCH_SERVE=1 end-to-end HTTP serving: loadgen clients against the
                     --mode serve plane (cake_tpu/serve) over the same
                     engine — aggregate tok/s through the socket plus
                     TTFT p50/p95, next to the in-process serving rows
                     (CAKE_BENCH_BATCH sets the client count).
  CAKE_BENCH_CONSTRAIN=1 grammar-constrained HTTP serving
                     (cake_tpu/constrain): loadgen --workload json
                     requests (response_format json_schema, responses
                     asserted json.loads-parseable) vs the same server
                     unconstrained — constrained tok/s with
                     vs_baseline = constrained/unconstrained.
  CAKE_BENCH_GATEWAY=1 routing-gateway overhead (cake_tpu/gateway): the
                     same loadgen workload against one serve replica
                     directly vs through a gateway fronting it —
                     gateway tok/s with vs_baseline = gateway/direct
                     plus the TTFT p50 the extra hop adds.
  CAKE_BENCH_KVPOOL=1 paged-KV churn (cake_tpu/kvpool): churn tok/s on
                     the paged layout vs the slot layout vs the paged
                     steady batch, legs interleaved A/B/A/B —
                     vs_baseline = churn_paged/steady_paged (ROADMAP's
                     within-25% churn target).
  CAKE_BENCH_DISAGG=1 disaggregated prefill/decode tiers
                     (cake_tpu/disagg): the mixed-prefill workload
                     against a tiered fleet (1 prefill + 1 decode, KV
                     pages over the transfer channel) vs 2 mixed
                     replicas, legs interleaved A/B/A/B — decode-tier
                     TPOT p95 with vs_baseline = tiered/mixed (< 1.0 =
                     the tier split wins), TTFT p95 split by prompt
                     bucket.
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _sync(x) -> None:
    """Synchronize by fetching a scalar of every leaf to the host.
    block_until_ready can return early through remote-device tunnels; an
    actual device->host read of the dependency chain cannot. All leaves are
    fetched so async allocation failures surface here (inside the caller's
    try), and the slice happens on-device so only one element transfers."""
    for leaf in jax.tree.leaves(x):
        np.asarray(leaf.ravel()[:1])

# the chip spec tables live in ONE place (cake_tpu/utils/chips.py) so
# bench.py and the measurement tools can never disagree on a roofline
# denominator; the local names are kept for this file's call sites
from cake_tpu.utils.chips import (  # noqa: E402
    HBM_GBPS as _HBM_GBPS,
    HBM_GIB as _HBM_GIB,
    PEAK_TFLOPS as _PEAK_TFLOPS,
    device_spec as _device_spec,
    hbm_gbps as _hbm_gbps,
)


def _mtag(preset: str) -> str:
    """Metric model tag: family_preset ("llama_8b" by default; a
    CAKE_BENCH_FAMILY run tags its own family so family rows can never be
    mistaken for the llama numbers of record)."""
    fam = os.environ.get("CAKE_BENCH_FAMILY", "llama")
    return f"{fam}_{preset}"


def _wtag(quant: str, kv_quant: str | None) -> str:
    """Metric tag for the weight/KV dtype combination."""
    tag = quant if quant in ("int8", "int4") else "bf16"
    return tag + "_kv8" if kv_quant else tag


def _matmul_flops(params, config, t: int) -> float:
    """Matmul FLOPs of a T-token prompt pass: 2 * matmul-params * T. The
    embed table is a lookup, not a matmul, so it is excluded; attention
    FLOPs are also excluded — conservative for MFU-style ratios."""
    n = sum(x.size for x in jax.tree.leaves(params))
    return 2.0 * (n - config.vocab_size * config.hidden_size) * t


def _kv_quant() -> str | None:
    """CAKE_BENCH_KV=int8: run with the quantized KV cache (half the cache
    HBM -> roughly double the servable batch x window on a fixed budget).
    Honored by EVERY bench path (single-stream, batched, prefill,
    speculative) — the HBM preflight prices it, so the paths must actually
    allocate it."""
    kv = os.environ.get("CAKE_BENCH_KV", "") or None
    if kv not in (None, "int8"):
        sys.exit(f"error: CAKE_BENCH_KV must be 'int8', got {kv!r}")
    return kv


def _config(preset: str):
    """CAKE_BENCH_FAMILY=mistral|qwen2|gemma swaps the 8b rung's
    architecture for that family's 7B-class geometry (random weights —
    tok/s only): mistral prices the sliding-window mask + windowed flash
    plane on-chip; qwen2 the biased-GQA 3584/28-layer geometry; gemma the
    MHA/head_dim-256/GeGLU/tied-head shape (its 256k-vocab embed stays
    bf16, so the int8 rung is the one that fits a v5e). Default family:
    llama."""
    from cake_tpu.models.config import (LlamaConfig, gemma_7b, llama3_8b,
                                        mistral_7b, qwen2_7b, tiny)

    seq = int(os.environ.get("CAKE_BENCH_SEQ", "512"))
    fam = os.environ.get("CAKE_BENCH_FAMILY", "llama")
    if fam != "llama" and preset != "8b":
        # the fallback rungs are llama geometry — benching them under a
        # family tag would mislabel the row
        sys.exit(f"error: CAKE_BENCH_FAMILY={fam} requires the 8b rung "
                 "(the fallback presets are llama geometry)")
    if preset == "8b":
        if fam == "mistral":
            return mistral_7b(max_seq_len=seq)
        if fam == "qwen2":
            return qwen2_7b(max_seq_len=seq)
        if fam == "gemma":
            return gemma_7b(max_seq_len=seq)
        if fam != "llama":
            sys.exit(f"error: CAKE_BENCH_FAMILY must be llama|mistral|"
                     f"qwen2|gemma, got {fam!r}")
        return llama3_8b(max_seq_len=seq)
    if preset == "small":
        return LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=16, num_attention_heads=32,
            num_key_value_heads=8, max_seq_len=seq,
        )
    return tiny(max_seq_len=seq, dtype="bfloat16")


def _param_bytes(params) -> int:
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))


def _ledger_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_results.jsonl")


def _tpu_ledger(max_rows: int = 16) -> list[dict]:
    """Freshest TPU-stamped row per metric from the measurement ledger
    (bench_results.jsonl), newest first. CPU rows are excluded — the
    ledger's purpose here is to carry the on-chip record through a wedged
    grant window, not to restate the fallback."""
    best: dict[str, dict] = {}
    try:
        with open(_ledger_path()) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("platform") != "tpu":
                    continue
                # append-order == time-order: later lines overwrite earlier
                best[rec.get("metric", "")] = rec
    except OSError:
        return []
    rows = sorted(best.values(), key=lambda r: r.get("stamp", ""),
                  reverse=True)
    return rows[:max_rows]


def _pick_headline(ledger: list[dict]) -> dict:
    """The ledger row to headline: the metric of record (master.rs:57-65
    analogue) is the plain single-stream decode row, and int8 is the tier
    that fits one v5e — prefer it, then any single-stream decode row, then
    whatever is freshest (the ledger is already newest-first and ``min``
    is stable)."""
    def rank(r):
        m = r.get("metric", "")
        if not (m.startswith("decode_tokens_per_sec")
                and m.endswith("_1chip")):
            return 2
        return 0 if "_int8_" in m else 1

    return min(ledger, key=rank)


def _emit(row: dict, dev, baseline: str | None = None, **extra) -> None:
    """Print the benchmark row (the driver contract: ONE JSON line on
    stdout per invocation, flushed the moment the row lands) and append it
    to bench_results.jsonl with device + timestamp, so a later wedge or
    crash in the same session cannot erase the evidence that a row was
    measured on-chip. The jsonl is a deliberately TRACKED measurement
    ledger (like KERNELS_TPU.json): on-chip rows are committed as round
    evidence, which is why it is not in .gitignore.

    ``baseline`` names what ``vs_baseline`` divides by, so every row is
    self-describing without BASELINE.md in hand (r4 verdict item 8);
    ``extra`` carries metric-family companions (tokens_per_dispatch,
    acceptance, p95_ms, busy_s ...) into both the stdout line and the
    ledger record.

    When this process is running on CPU — i.e. the live probe fell back
    because the tunnel grant was wedged — the emitted line additionally
    carries the freshest TPU-stamped ledger rows under ``ledger``, with a
    ``ledger_headline`` pointing at the single-stream record. Four rounds
    running, the driver's capture hit a wedged window and BENCH_rNN.json
    recorded only the CPU fallback while the on-chip record sat in the
    ledger; this makes the driver artifact wedge-proof (r4 verdict item 1):
    honest provenance (the live row is clearly the CPU fallback; ledger
    rows carry their own device + stamp), no lost evidence."""
    if baseline is not None:
        row = dict(row, baseline=baseline)
    if extra:
        row = dict(row, **extra)
    out = row
    if dev.platform == "cpu":
        ledger = _tpu_ledger()
        if ledger:
            headline = _pick_headline(ledger)
            out = dict(
                row,
                ledger_note=(
                    "live row ran on CPU fallback (accelerator probe "
                    "failed); 'ledger' holds the freshest TPU-stamped "
                    "rows previously measured by this repo's bench, one "
                    "per metric, device+UTC stamp included"
                ),
                ledger_headline=headline,
                ledger=ledger,
            )
    print(json.dumps(out), flush=True)
    try:
        rec = dict(row, device=getattr(dev, "device_kind", "cpu"),
                   platform=dev.platform,
                   stamp=time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
        snap = _metrics_snapshot()
        if snap:
            rec["metrics"] = snap
        with open(_ledger_path(), "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass


def _metrics_snapshot() -> dict:
    """Non-empty obs-registry series for the ledger record, so a bench row
    carries dispatch/admission percentiles and wire bytes alongside the
    single throughput number. The snapshot is the process-cumulative
    registry at emit time: one bench phase runs per process (main()
    dispatches exactly one _run_* path; step-downs re-exec fresh), so the
    only extra samples are that phase's own warm-up/compile dispatches.
    Zero-valued instruments created at import are dropped."""
    from cake_tpu.obs import metrics as obs_metrics

    out = {}
    for name, inst in obs_metrics.registry().snapshot().items():
        kind = inst.get("type")
        if kind == "histogram" and inst.get("count"):
            out[name] = inst
        elif kind in ("counter", "gauge") and inst.get("value"):
            out[name] = inst
    return out


def _device_init_probe(timeout_s: float) -> bool:
    """Check device init completes in a THROWAWAY subprocess. A wedged
    remote chip hangs inside PJRT client init without returning to the
    interpreter (so in-process alarms can't fire); probing in a subprocess
    with a hard timeout lets the parent fall back to CPU instead of hanging
    the driver forever."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax, numpy as np, jax.numpy as jnp; "
             "np.asarray(jnp.ones((8, 8)).sum())"],
            timeout=timeout_s, capture_output=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _device_init_probe_retried() -> bool:
    """Probe for a usable accelerator under a hard WALL-CLOCK budget.

    History: r2's 3x45s probe budget was too small for transient wedges and
    cost the round's record; r3 raised it to 10x60s — but the r3 wedge
    lasted 8+ hours, so all 10 probes burned ~19 minutes of driver time and
    the record still fell back to CPU. Evidence now says wedges are bimodal:
    either the first probe succeeds in seconds (healthy chip) or the grant
    stays wedged for hours (no probe count helps). So the budget is a
    deadline, not a count: keep probing until CAKE_BENCH_PROBE_BUDGET
    seconds (default 360) elapse, then degrade to CPU fast. A healthy chip
    still passes on the first ~15s probe; a wedged one costs 6 minutes
    instead of 19 (CAKE_BENCH_PROBE_WAIT / CAKE_BENCH_PROBE_TIMEOUT tune
    the per-probe cadence)."""
    wait_s = float(os.environ.get("CAKE_BENCH_PROBE_WAIT", "45"))
    timeout_s = float(os.environ.get("CAKE_BENCH_PROBE_TIMEOUT", "60"))
    if "CAKE_BENCH_PROBE_BUDGET" not in os.environ and \
            "CAKE_BENCH_PROBES" in os.environ:
        # r2/r3 contract compatibility: a count-based knob maps onto the
        # wall-clock budget it used to imply (N probes hanging their full
        # timeout plus the waits between them).
        n = int(os.environ["CAKE_BENCH_PROBES"])
        budget_s = n * timeout_s + max(0, n - 1) * wait_s
    else:
        budget_s = float(os.environ.get("CAKE_BENCH_PROBE_BUDGET", "360"))
    if budget_s <= 0:
        # CAKE_BENCH_PROBES=0 / CAKE_BENCH_PROBE_BUDGET=0: bypass the
        # accelerator without launching even one probe (a probe against a
        # wedged grant can re-wedge it).
        return False
    deadline = time.monotonic() + budget_s
    attempt = 0
    while True:
        attempt += 1
        if _device_init_probe(timeout_s):
            return True
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            sys.stderr.write(
                f"device init: {attempt} probes failed within the "
                f"{budget_s:.0f}s budget\n"
            )
            return False
        sys.stderr.write(
            f"device init probe {attempt} failed; retrying in "
            f"{min(wait_s, remaining):.0f}s ({remaining:.0f}s of probe "
            f"budget left)\n"
        )
        time.sleep(min(wait_s, remaining))


def _reexec(cpu: bool = False, **env_overrides) -> None:
    """Replace this process with a fresh bench run. With ``cpu=True``,
    PYTHONPATH is pinned to the repo root so the axon sitecustomize (which
    force-registers the TPU plugin in every python process) is dropped;
    accelerator re-runs keep the environment intact."""
    env = dict(os.environ, **env_overrides)
    if cpu:
        env.update(JAX_PLATFORMS="cpu", CAKE_BENCH_NO_FALLBACK="1")
        env["PYTHONPATH"] = os.path.dirname(os.path.abspath(__file__))
        # the CPU fallback runs the tiny preset, which is llama geometry —
        # a surviving family knob would hit the family-requires-8b guard
        # and turn the fallback into an error exit
        env.pop("CAKE_BENCH_FAMILY", None)
    os.execve(sys.executable, [sys.executable, __file__], env)


def _run_prefill(config, params, preset, quant, dev) -> int:
    """Prefill (TTFT-side) throughput: tokens/s of one warm prompt pass at
    T = CAKE_BENCH_SEQ/2 against a CAKE_BENCH_SEQ KV window. This is where
    the Pallas flash kernel carries the long-context story (132x over
    XLA-materialized scores at T=2048/S=8192 on v5e — KERNELS_TPU.json);
    the reference hard-caps context at 4096 and materializes full score
    matrices (attention.rs:59-80)."""
    from cake_tpu.ops.kvcache import init_cache
    from cake_tpu.runtime.generator import prefill_fn

    kv_quant = _kv_quant()
    t = config.max_seq_len // 2
    prefill = jax.jit(partial(prefill_fn, config=config),
                      donate_argnames=("cache",))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, config.vocab_size, (1, t)),
        jnp.int32,
    )
    last = jnp.asarray([t - 1], jnp.int32)

    cache = init_cache(config, batch=1, max_seq=config.max_seq_len,
                       quant=kv_quant)
    t0 = time.perf_counter()
    logits, cache = prefill(params, tokens, cache, last)
    _sync(logits)
    ttft_cold = time.perf_counter() - t0  # includes compile

    # Each iteration's cache is allocated and synced OUTSIDE its timed
    # window (prefill donates the cache, so a fresh one is needed per
    # iteration). Timed per-iteration — NOT by pre-allocating all iters
    # caches at once, which at 8B/16K-window would be ~17 GB of cache and
    # OOM the chip before the bench starts.
    iters = 8
    dts = []
    for _ in range(iters):
        cache = init_cache(config, batch=1, max_seq=config.max_seq_len,
                           quant=kv_quant)
        _sync(cache)
        t0 = time.perf_counter()
        logits, cache = prefill(params, tokens, cache, last)
        _sync(logits)
        dts.append(time.perf_counter() - t0)
    dt = sum(dts) / iters

    wtag = _wtag(quant, kv_quant)
    # vs_baseline: fraction of the chip's bf16 peak the prompt pass sustains
    flops = _matmul_flops(params, config, t)
    peak = _device_spec(dev, _PEAK_TFLOPS, 197.0) * 1e12
    _emit({
        "metric": f"prefill_tokens_per_sec_{_mtag(preset)}_{wtag}_1chip_t{t}",
        "value": round(t / dt, 3),
        "unit": "tokens/s",
        "vs_baseline": round(flops / dt / peak, 4),
    }, dev, baseline=f"mfu_vs_bf16_peak_{peak / 1e12:.0f}tflops")
    sys.stderr.write(
        f"device={dev.device_kind} T={t} window={config.max_seq_len} "
        f"warm_prefill={dt * 1e3:.1f}ms ttft_cold={ttft_cold:.2f}s "
        f"mfu~{flops / dt / peak:.2f}\n"
    )
    return 0


def _run_batched(config, params, preset, quant, settings, dev,
                 batch, steps, multistep) -> int:
    """Multi-stream aggregate decode throughput (CAKE_BENCH_BATCH=N).

    Drives the serving stack itself — the per-row mesh decode program
    (parallel/pipeline per_row mode on a 1-device mesh), N streams at their
    own positions with per-stream keys. Weight reads amortize over the
    batch, so aggregate tok/s can exceed the single-stream weights-bound
    roofline (``vs_baseline > 1``) — the axis the single-request reference
    has no answer to (SURVEY.md §0: no batching of concurrent requests).
    """
    from cake_tpu.parallel.mesh import (
        MeshPlan,
        init_cache_on_mesh,
        shard_params,
    )
    from cake_tpu.parallel.pipeline import (
        build_sharded_decode,
        build_sharded_prefill,
    )

    kv_quant = _kv_quant()
    plan = MeshPlan.build(config, devices=jax.devices()[:1])
    params = shard_params(params, plan.mesh)
    cache = init_cache_on_mesh(config, plan.mesh, batch=batch,
                               max_seq=config.max_seq_len, quant=kv_quant)
    prefill = build_sharded_prefill(config, plan, params_like=params,
                                    kv_quant=kv_quant)
    decode = build_sharded_decode(config, settings, plan, params_like=params,
                                  steps=multistep, per_row=True,
                                  kv_quant=kv_quant)

    prompt_len = 8
    tokens = jnp.tile(
        jnp.asarray([[1, 5, 9, 14, 3, 8, 2, 4]], jnp.int32), (batch, 1)
    )
    t_pf0 = time.perf_counter()
    logits, cache = prefill(
        params, tokens, cache,
        jnp.full((batch,), prompt_len - 1, jnp.int32),
    )
    _sync(logits)
    ttft_s = time.perf_counter() - t_pf0

    base = jax.random.PRNGKey(settings.seed)
    keys = jnp.stack([jax.random.fold_in(base, i) for i in range(batch)])
    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    pos = jnp.full((batch,), prompt_len, jnp.int32)
    history = jnp.full((batch, settings.repeat_last_n), -1, jnp.int32)
    hist_slot = jnp.zeros((batch,), jnp.int32)

    per = max(1, multistep)
    max_dispatches = (config.max_seq_len - prompt_len) // per - 3
    if max_dispatches < 1:
        sys.exit(
            f"error: CAKE_BENCH_SEQ={config.max_seq_len} too small for "
            f"CAKE_BENCH_MULTISTEP={multistep}"
        )
    dispatches = max(1, min(steps // per, max_dispatches))

    index = jnp.ones((batch,), jnp.int32)  # per-stream token indices

    def step_once(tok, cache, history, hist_slot, pos, index):
        toks, cache, history, hist_slot = decode(
            params, tok, cache, pos, keys, history, hist_slot, index,
        )
        # per_row decode returns [B] for steps==1, [steps, B] otherwise
        last = toks if per == 1 else toks[-1]
        return (last.astype(jnp.int32), cache, history, hist_slot,
                pos + per, index + per)

    for _ in range(3):  # compile + warm-up
        tok, cache, history, hist_slot, pos, index = step_once(
            tok, cache, history, hist_slot, pos, index
        )
    _sync(tok)
    t0 = time.perf_counter()
    for _ in range(dispatches):
        tok, cache, history, hist_slot, pos, index = step_once(
            tok, cache, history, hist_slot, pos, index
        )
    _sync(tok)
    dt = time.perf_counter() - t0

    agg_tok_s = dispatches * per * batch / dt
    model_gb = _param_bytes(params) / 1e9
    roofline = _hbm_gbps(dev) / model_gb  # single-stream weights-bound ideal
    wtag = _wtag(quant, kv_quant)
    _emit({
        "metric": f"decode_tokens_per_sec_{_mtag(preset)}_{wtag}_1chip_b{batch}",
        "value": round(agg_tok_s, 3),
        "unit": "tokens/s",
        "vs_baseline": round(agg_tok_s / roofline, 4),
    }, dev,
        baseline=f"single_stream_hbm_roofline_{roofline:.1f}tok/s",
        per_stream_tok_s=round(agg_tok_s / batch, 3))
    sys.stderr.write(
        f"device={dev.device_kind} params={model_gb:.2f}GB batch={batch} "
        f"single-stream roofline={roofline:.1f}tok/s "
        f"per-stream {agg_tok_s / batch:.1f}tok/s ttft_cold={ttft_s:.2f}s "
        f"timed_tokens={dispatches * per * batch} multistep={per}\n"
    )
    return 0


def _run_ttft(config, params, preset, quant, dev) -> int:
    """CAKE_BENCH_TTFT=1: p50/p95 time-to-first-token at CAKE_BENCH_SEQ/2
    prompt length — warm prefill + first-token sample per trial, the
    latency metric BASELINE.json names alongside tok/s (the reference
    never measures TTFT at all; its master only logs steady-state
    tokens/sec, master.rs:57-65)."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.generator import LlamaGenerator

    kv_quant = _kv_quant()
    trials = int(os.environ.get("CAKE_BENCH_TTFT_TRIALS", "16"))
    t = config.max_seq_len // 2
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    gen = LlamaGenerator(config, params, settings=settings,
                         kv_quant=kv_quant)
    rng = np.random.default_rng(0)
    prompt0 = rng.integers(1, config.vocab_size, t).tolist()
    gen.set_prompt(prompt0)
    gen.next_token(0)  # compile + warm
    lat = []
    for i in range(trials):
        prompt = rng.integers(1, config.vocab_size, t).tolist()
        gen.set_prompt(prompt)
        t0 = time.perf_counter()
        tok = gen.next_token(0)
        lat.append(time.perf_counter() - t0)
        assert tok.id >= 0
    lat.sort()
    p50 = lat[len(lat) // 2]
    p95 = lat[min(len(lat) - 1, int(len(lat) * 0.95))]
    wtag = _wtag(quant, kv_quant)
    # vs_baseline: how close the warm prompt pass runs to the chip's peak
    flops = _matmul_flops(params, config, t)
    peak = _device_spec(dev, _PEAK_TFLOPS, 197.0) * 1e12
    _emit({
        "metric": f"ttft_p50_ms_{_mtag(preset)}_{wtag}_1chip_t{t}",
        "value": round(p50 * 1e3, 2),
        "unit": "ms",
        "vs_baseline": round(flops / p50 / peak, 4),
    }, dev,
        baseline=f"prefill_mfu_at_p50_vs_bf16_peak_{peak / 1e12:.0f}tflops",
        p95_ms=round(p95 * 1e3, 2), prompt_tokens=t)
    sys.stderr.write(
        f"device={dev.device_kind} T={t} trials={trials} "
        f"p50={p50 * 1e3:.1f}ms p95={p95 * 1e3:.1f}ms\n"
    )
    return 0


def _run_obs_overhead(config, params, preset, quant, dev, steps) -> int:
    """CAKE_BENCH_OBS=1: decode tokens/sec with the observability planes
    OFF vs ON (tracer + flight recorder enabled, in-memory only) through
    the LlamaGenerator hot path — the single-stream loop that calls
    span()/record()/histogram per token. The figure of merit is the
    overhead percentage; the obs satellite contract is that OFF costs an
    attribute check per call site (`make perf-smoke` bounds that
    micro-cost; this row prices the enabled planes). A second row does
    the same off/on comparison through the HTTP serve plane, where the
    tracer additionally carries the per-request span set (serve.queue →
    session.emit, cake_tpu/obs/reqtrace); the design target is traced
    serve tok/s within 3% of untraced."""
    from cake_tpu.obs import flight, trace
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.generator import LlamaGenerator

    kv_quant = _kv_quant()
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    n = max(8, min(4 * steps, config.max_seq_len - 16))
    prompt = [1, 5, 9, 14, 3, 8, 2, 4]

    def run(label: str) -> float:
        gen = LlamaGenerator(config, params, settings=settings,
                             kv_quant=kv_quant)
        gen.set_prompt(prompt)
        # warm BOTH programs before the clock: next_token(0) compiles only
        # prefill, next_token(1) the decode step — a timed first decode
        # would put one ~600 ms XLA compile inside a ~120 ms measurement
        # window and swamp the obs delta being measured
        gen.next_token(0)
        gen.next_token(1)
        t0 = time.perf_counter()
        for i in range(2, n):
            gen.next_token(i)
        dt = time.perf_counter() - t0
        sys.stderr.write(f"obs={label}: {(n - 2) / dt:.1f} tok/s\n")
        return (n - 2) / dt

    def obs_leg(enabled: bool) -> float:
        if not enabled:
            return run("off")
        trace.tracer().start()
        flight.recorder().enable()
        try:
            return run("on")
        finally:
            trace.tracer().stop()
            flight.recorder().disable()
            flight.recorder().clear()
            trace.tracer().clear()

    # warm leg (pays the compiles), then ABBA: host throughput drifts
    # monotonically over a CPU bench, and a single off-then-on pair books
    # that drift as obs overhead — off-on-on-off cancels a linear drift
    obs_leg(False)
    obs_legs = [obs_leg(e) for e in (False, True, True, False)]
    off = (obs_legs[0] + obs_legs[3]) / 2
    on = (obs_legs[1] + obs_legs[2]) / 2
    overhead_pct = (off / on - 1.0) * 100.0
    wtag = _wtag(quant, kv_quant)
    _emit({
        "metric": f"decode_obs_overhead_pct_{_mtag(preset)}_{wtag}_1chip",
        "value": round(overhead_pct, 2),
        "unit": "%",
        "vs_baseline": round(on / off, 4),
    }, dev, baseline=f"obs_off_{off:.1f}tok/s",
        obs_off_tok_s=round(off, 2), obs_on_tok_s=round(on, 2),
        legs_tok_s=[round(x, 2) for x in obs_legs], timed_tokens=n - 2)

    # -- prof leg: step-phase profiler OFF vs ON (default coarse sampling)
    # through the BatchGenerator step loop — the engine that carries the
    # phase stamps. A/B/A/B interleaved: two off and two on windows
    # alternating over ONE engine (the profiler is a process singleton, so
    # re-pointing the stride needs no rebuild and no recompile), averaging
    # out drift that a single off-then-on pair would book as overhead.
    import dataclasses as _dc

    from cake_tpu.obs import prof as _prof
    from cake_tpu.runtime.batch_generator import BatchGenerator

    clients = 2
    # longer timed window than the trace legs: the prof delta is small, so
    # a ~70 ms window would drown it in scheduler noise
    k = max(64, min(4 * steps, config.max_seq_len - 48))
    cfg_prof = _dc.replace(config, eos_token_id=-1)  # streams never EOS
    pgen = BatchGenerator(cfg_prof, params, settings=settings,
                          kv_quant=kv_quant)
    # prime like the scheduler: a live batch of retired slots, so the
    # legs' enqueues ride continuous admission
    pgen.set_prompts([[1]] * clients)
    for s in pgen.streams:
        s.done = True
    sample0 = _prof.profiler().sample_every
    sample_on = sample0 if sample0 > 0 else 64

    def prof_leg(sample: int, sid0: int) -> float:
        _prof.profiler().set_sample(sample)
        for j in range(clients):
            pgen.enqueue(prompt, sid0 + j)
        for _ in range(4):  # admit + warm (first leg pays the compiles)
            pgen.step()
        t0 = time.perf_counter()
        for _ in range(k):
            pgen.step()
        dt = time.perf_counter() - t0
        # retire the slots the same way the priming idiom does, so the
        # next leg's enqueues admit into them fresh
        for s in pgen.streams:
            s.done = True
        pgen.step()
        return (k * clients) / dt

    try:
        prof_leg(0, sid0=990)  # warm: pays admission + decode compiles
        legs = []
        # ABBA order: host throughput decays monotonically over a CPU
        # bench (turbo/thermal), and off-on-off-on would book that decay
        # as profiler overhead; off-on-on-off cancels a linear drift
        for i, sample in enumerate((0, sample_on, sample_on, 0)):
            tok_s = prof_leg(sample, sid0=1000 + 10 * i)
            legs.append(round(tok_s, 2))
            sys.stderr.write(
                f"prof sample={sample}: {tok_s:.1f} tok/s\n")
    finally:
        _prof.profiler().set_sample(sample0)
    prof_off = (legs[0] + legs[3]) / 2
    prof_on = (legs[1] + legs[2]) / 2
    prof_pct = (prof_off / prof_on - 1.0) * 100.0
    _emit({
        "metric": f"decode_prof_overhead_pct_{_mtag(preset)}_{wtag}_1chip",
        "value": round(prof_pct, 2),
        "unit": "%",
        "vs_baseline": round(prof_on / prof_off, 4),
    }, dev, baseline=f"prof_off_{prof_off:.1f}tok/s",
        legs_tok_s=legs, sample_every=sample_on,
        timed_steps=k, clients=clients)

    # -- serve leg: the same off/on comparison through the HTTP plane,
    # where tracing also mints per-request spans (reqtrace) on every
    # queue/admit/prefill/emit transition rather than per-token records
    from cake_tpu.runtime.batch_generator import BatchGenerator
    from cake_tpu.serve.api import start_api_server
    from cake_tpu.serve.scheduler import Scheduler
    from cake_tpu.tools import loadgen

    clients = 2
    max_tokens = max(4, min(steps, config.max_seq_len - 16))
    gen = BatchGenerator(config, params, settings=settings,
                         kv_quant=kv_quant)
    sched = Scheduler(gen, queue_depth=4 * clients)
    sched.start(max_concurrent=clients, warm_prompt_len=8)
    srv = start_api_server(sched)
    url = f"http://127.0.0.1:{srv.port}"

    def serve_run(label: str, seed: int) -> float:
        # 4 requests/client: a longer window than the SERVE row's 2 —
        # the figure of merit here is a small DELTA, not the absolute
        stats = loadgen.run_load(
            url, 4 * clients, concurrency=clients, max_tokens=max_tokens,
            prompt_lens=[8], vocab=config.vocab_size - 1, seed=seed)
        if stats["completed"] != 4 * clients or stats["errors"]:
            raise RuntimeError(f"serve obs leg ({label}) failed: {stats}")
        sys.stderr.write(f"serve obs={label}: {stats['tok_s']:.1f} tok/s\n")
        return stats["tok_s"]

    try:
        # warm pass: first requests pay decode/admission compiles
        loadgen.run_load(url, clients, concurrency=clients, max_tokens=4,
                         prompt_lens=[8], vocab=config.vocab_size - 1,
                         seed=1)
        serve_off = serve_run("off", seed=2)
        trace.tracer().start()
        flight.recorder().enable()
        try:
            serve_on = serve_run("on", seed=3)
        finally:
            trace.tracer().stop()
            flight.recorder().disable()
            flight.recorder().clear()
            trace.tracer().clear()
    finally:
        srv.close()
        sched.close()
    serve_pct = (serve_off / serve_on - 1.0) * 100.0
    _emit({
        "metric": f"serve_trace_overhead_pct_{_mtag(preset)}_{wtag}_1chip",
        "value": round(serve_pct, 2),
        "unit": "%",
        "vs_baseline": round(serve_on / serve_off, 4),
    }, dev, baseline=f"trace_off_{serve_off:.1f}tok/s",
        serve_off_tok_s=round(serve_off, 2),
        serve_on_tok_s=round(serve_on, 2),
        clients=clients, max_tokens=max_tokens)
    return 0


def _run_serve_http(config, params, preset, quant, dev, batch,
                    steps) -> int:
    """CAKE_BENCH_SERVE=1: END-TO-END HTTP serving — the full network
    plane (cake_tpu/serve: HTTP accept, JSON/SSE framing, scheduler
    fan-out) over the same BatchGenerator the in-process serving rows
    measure. The figure of merit is aggregate tok/s THROUGH the socket
    plus TTFT p50/p95 as a loadgen client sees them; the gap to the
    in-process CAKE_BENCH_BATCH/CHURN rows is the serving plane's own
    overhead. Closed loop at CAKE_BENCH_BATCH concurrency (default floors
    at 2), 2 requests per client, CAKE_BENCH_STEPS tokens per request."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.batch_generator import BatchGenerator
    from cake_tpu.serve.api import start_api_server
    from cake_tpu.serve.scheduler import Scheduler
    from cake_tpu.tools import loadgen

    kv_quant = _kv_quant()
    batch = max(2, batch)
    max_tokens = max(4, min(steps, config.max_seq_len - 16))
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    gen = BatchGenerator(config, params, settings=settings,
                         kv_quant=kv_quant)
    sched = Scheduler(gen, queue_depth=4 * batch)
    sched.start(max_concurrent=batch, warm_prompt_len=8)
    srv = start_api_server(sched)
    url = f"http://127.0.0.1:{srv.port}"
    try:
        # warm pass: first requests pay decode/admission compiles
        loadgen.run_load(url, batch, concurrency=batch, max_tokens=4,
                         prompt_lens=[8], vocab=config.vocab_size - 1,
                         seed=1)
        stats = loadgen.run_load(
            url, 2 * batch, concurrency=batch, max_tokens=max_tokens,
            prompt_lens=[8], vocab=config.vocab_size - 1, seed=2)
    finally:
        srv.close()
        sched.close()
    if stats["completed"] != 2 * batch or stats["errors"]:
        sys.stderr.write(f"serve bench failed: {stats}\n")
        return 1
    model_gb = _param_bytes(params) / 1e9
    roofline = _hbm_gbps(dev) / model_gb
    wtag = _wtag(quant, kv_quant)
    _emit({
        "metric": (f"serve_http_tokens_per_sec_{_mtag(preset)}_{wtag}_"
                   f"1chip_c{batch}"),
        "value": stats["tok_s"],
        "unit": "tokens/s",
        "vs_baseline": round(stats["tok_s"] / roofline, 4),
    }, dev,
        baseline=f"single_stream_hbm_roofline_{roofline:.1f}tok/s",
        ttft_p50_ms=stats["ttft_ms"]["p50"],
        ttft_p95_ms=stats["ttft_ms"]["p95"],
        tpot_p50_ms=stats["tpot_ms"]["p50"],
        requests=stats["requests"], max_tokens=max_tokens)
    sys.stderr.write(
        f"device={dev.device_kind} clients={batch} "
        f"requests={stats['requests']} http_tok_s={stats['tok_s']} "
        f"ttft_p50={stats['ttft_ms']['p50']}ms "
        f"ttft_p95={stats['ttft_ms']['p95']}ms\n"
    )
    return 0


def _run_gateway_http(config, params, preset, quant, dev, batch,
                      steps) -> int:
    """CAKE_BENCH_GATEWAY=1: the routing gateway's own overhead — the
    same loadgen workload against one serve replica directly, then
    through a gateway (cake_tpu/gateway) fronting it. The figure of
    merit is gateway tok/s with vs_baseline = gateway/direct (the proxy
    hop, routing decision, and health bookkeeping are the whole gap; the
    design target is within 10% on the smoke config), plus the TTFT p50
    delta the extra hop adds."""
    from cake_tpu.gateway.api import start_gateway
    from cake_tpu.gateway.health import Backend, HealthMonitor
    from cake_tpu.gateway.policy import make_policy
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.batch_generator import BatchGenerator
    from cake_tpu.serve.api import start_api_server
    from cake_tpu.serve.scheduler import Scheduler
    from cake_tpu.tools import loadgen

    kv_quant = _kv_quant()
    batch = max(2, batch)
    max_tokens = max(4, min(steps, config.max_seq_len - 16))
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    gen = BatchGenerator(config, params, settings=settings,
                         kv_quant=kv_quant)
    sched = Scheduler(gen, queue_depth=4 * batch)
    sched.start(max_concurrent=batch, warm_prompt_len=8)
    srv = start_api_server(sched)
    direct_url = f"http://127.0.0.1:{srv.port}"
    monitor = HealthMonitor(
        [Backend("b0", f"127.0.0.1:{srv.port}")], probe_interval=0.5)
    monitor.start()
    gw = start_gateway(monitor, make_policy("p2c"))
    gw_url = f"http://127.0.0.1:{gw.port}"
    directs, via_gws = [], []
    try:
        # warm BOTH paths (compiles + the gateway's connect machinery),
        # then interleave the measured legs A/B/A/B — sequential legs
        # against the shared engine bias whichever runs later (EMA and
        # warmup drift exceed the ms-scale overhead being measured)
        loadgen.run_load(direct_url, batch, concurrency=batch,
                         max_tokens=4, prompt_lens=[8],
                         vocab=config.vocab_size - 1, seed=1)
        loadgen.run_load(gw_url, batch, concurrency=batch,
                         max_tokens=4, prompt_lens=[8],
                         vocab=config.vocab_size - 1, seed=1)
        for rep in range(2):
            directs.append(loadgen.run_load(
                direct_url, 2 * batch, concurrency=batch,
                max_tokens=max_tokens, prompt_lens=[8],
                vocab=config.vocab_size - 1, seed=2 + rep))
            via_gws.append(loadgen.run_load(
                gw_url, 2 * batch, concurrency=batch,
                max_tokens=max_tokens, prompt_lens=[8],
                vocab=config.vocab_size - 1, seed=2 + rep))
    finally:
        gw.close()
        monitor.stop()
        srv.close()
        sched.close()

    def _agg(legs):
        tokens = sum(s["tokens"] for s in legs)
        wall = sum(s["wall_s"] for s in legs)
        return {
            "tok_s": round(tokens / wall, 2) if wall else 0.0,
            "ttft_p50_ms": round(
                sum(s["ttft_ms"]["p50"] for s in legs) / len(legs), 1),
            "completed": sum(s["completed"] for s in legs),
            "errors": sum(s["errors"] for s in legs),
            "requests": sum(s["requests"] for s in legs),
        }

    direct, via_gw = _agg(directs), _agg(via_gws)
    if (direct["errors"] or via_gw["errors"]
            or direct["completed"] != 4 * batch
            or via_gw["completed"] != 4 * batch):
        sys.stderr.write(f"gateway bench failed: direct={direct} "
                         f"gateway={via_gw}\n")
        return 1
    ratio = via_gw["tok_s"] / direct["tok_s"] if direct["tok_s"] else 0.0
    wtag = _wtag(quant, kv_quant)
    _emit({
        "metric": (f"gateway_http_tokens_per_sec_{_mtag(preset)}_{wtag}_"
                   f"1chip_c{batch}"),
        "value": via_gw["tok_s"],
        "unit": "tokens/s",
        "vs_baseline": round(ratio, 4),
    }, dev,
        baseline=f"direct_http_{direct['tok_s']:.1f}tok/s",
        ttft_p50_ms=via_gw["ttft_p50_ms"],
        ttft_p50_direct_ms=direct["ttft_p50_ms"],
        ttft_added_p50_ms=round(via_gw["ttft_p50_ms"]
                                - direct["ttft_p50_ms"], 1),
        requests=via_gw["requests"], max_tokens=max_tokens,
        interleaved_reps=2)
    sys.stderr.write(
        f"device={dev.device_kind} clients={batch} "
        f"gateway_tok_s={via_gw['tok_s']} direct_tok_s={direct['tok_s']} "
        f"ratio={ratio:.3f} ttft_p50 {direct['ttft_p50_ms']} -> "
        f"{via_gw['ttft_p50_ms']} ms\n"
    )
    return 0


def _run_slo(config, params, preset, quant, dev, batch, steps) -> int:
    """CAKE_BENCH_SLO=1: class-aware scheduling (ISSUE 20) vs FIFO under
    the mixed-class flood — an interactive trickle (every 4th request)
    inside a batch flood against ONE paged serve stack, A/B/A/B'd by
    swapping the scheduler's policy between legs (same warmed engine,
    same compiled programs — the policy is the only variable). The
    figure of merit is interactive TTFT p95: under FIFO it is hostage
    to however many batch requests queued first; under "slo" the
    arrivals jump the queue and preempt batch victims to host-RAM
    spill. The row FAILS unless slo beats fifo."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.batch_generator import BatchGenerator
    from cake_tpu.serve.api import start_api_server
    from cake_tpu.serve.scheduler import Scheduler
    from cake_tpu.tools import loadgen

    kv_quant = _kv_quant()
    batch = max(2, batch)
    max_tokens = max(4, min(steps, config.max_seq_len - 16))
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    gen = BatchGenerator(config, params, settings=settings,
                        kv_quant=kv_quant, kv_layout="paged",
                        kv_page_size=16)
    n = 12 * batch  # per leg; every 4th request is interactive
    sched = Scheduler(gen, queue_depth=2 * n, sched_policy="slo")
    sched.start(max_concurrent=batch, warm_prompt_len=8)
    srv = start_api_server(sched)
    url = f"http://127.0.0.1:{srv.port}"
    # arrivals must decisively outpace service so the admission queue
    # builds — a drained queue has nothing for the policy to reorder,
    # and FIFO only loses when interactive arrivals find a deep queue.
    # A near-burst guarantees depth regardless of how fast this host
    # decodes the tiny model.
    rate = 100.0 * batch
    ttfts = {"fifo": [], "slo": []}
    counts = {"fifo": 0, "slo": 0}
    try:
        # warm pass: first requests pay decode/admission compiles
        loadgen.run_load(url, batch, concurrency=batch, max_tokens=4,
                         prompt_lens=[8], vocab=config.vocab_size - 1,
                         seed=1)
        for rep in range(2):  # interleaved A/B/A/B on one warmed stack
            for policy in ("fifo", "slo"):
                sched.set_policy(policy)
                leg = loadgen.run_load(
                    url, n, max_tokens=max_tokens, prompt_lens=[8],
                    vocab=config.vocab_size - 1, rate=rate,
                    seed=3 + rep, workload="mixed-class")
                if leg["errors"] or leg["completed"] != n:
                    sys.stderr.write(f"slo bench leg failed "
                                     f"({policy}): {leg}\n")
                    return 1
                counts[policy] += leg["completed"]
                ttfts[policy] += [
                    r["ttft_s"] * 1e3
                    for i, r in enumerate(leg["results"])
                    if i % 4 == 0 and r and r.get("ttft_s") is not None]
        st = sched.stats()
    finally:
        srv.close()
        sched.close()
    fifo_p95 = round(loadgen._percentile(ttfts["fifo"], 0.95), 1)
    slo_p95 = round(loadgen._percentile(ttfts["slo"], 0.95), 1)
    ratio = slo_p95 / fifo_p95 if fifo_p95 else 0.0
    wtag = _wtag(quant, kv_quant)
    _emit({
        "metric": (f"slo_interactive_ttft_p95_{_mtag(preset)}_{wtag}_"
                   f"1chip_c{batch}"),
        "value": slo_p95,
        "unit": "ms",
        "vs_baseline": round(ratio, 4),
    }, dev,
        baseline=f"fifo_interactive_ttft_p95_{fifo_p95:.1f}ms",
        interactive_n=len(ttfts["slo"]),
        requests=counts["fifo"] + counts["slo"],
        preemptions=st.get("preemptions", 0),
        max_tokens=max_tokens, interleaved_reps=2)
    sys.stderr.write(
        f"device={dev.device_kind} clients={batch} "
        f"interactive ttft_p95 fifo={fifo_p95}ms slo={slo_p95}ms "
        f"ratio={ratio:.3f} preemptions={st.get('preemptions', 0)}\n"
    )
    if slo_p95 >= fifo_p95:
        sys.stderr.write(
            "slo bench FAILED: class-aware interactive TTFT p95 "
            f"({slo_p95}ms) must beat the FIFO baseline "
            f"({fifo_p95}ms)\n")
        return 1
    return 0


def _run_disagg(config, params, preset, quant, dev, batch, steps) -> int:
    """CAKE_BENCH_DISAGG=1: the disaggregated prefill/decode tiers
    (cake_tpu/disagg) under the interference regime they exist for — the
    mixed-prefill workload (bimodal prompt lengths, Poisson arrivals)
    against a TIERED fleet (1 prefill + 1 decode replica, KV pages
    shipped over the transfer channel) vs 2 MIXED replicas, both behind
    a routing gateway, legs interleaved A/B/A/B. The figure of merit is
    the decode-tier TPOT p95 (long neighbors' prefill dispatches no
    longer interleave with anyone's decode) with vs_baseline =
    tiered/mixed (< 1.0 = the tier split pays for its transfer hop);
    TTFT p95 rides along split by prompt bucket."""
    from cake_tpu.disagg import TransferServer
    from cake_tpu.gateway.api import start_gateway
    from cake_tpu.gateway.health import Backend, HealthMonitor
    from cake_tpu.gateway.policy import make_policy
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.batch_generator import BatchGenerator
    from cake_tpu.serve.api import start_api_server
    from cake_tpu.serve.scheduler import Scheduler
    from cake_tpu.tools import loadgen

    kv_quant = _kv_quant()
    batch = max(2, batch)
    max_tokens = max(4, min(steps, 32))
    # the bimodal mix: chatty short prompts next to long-document ones
    # (the long bucket is capped so prompt + decode fits the window)
    short_len = 8
    long_len = max(short_len * 2,
                   min(512, config.max_seq_len - max_tokens - 8))
    n_req = 4 * batch
    rate = max(2.0, 1.5 * batch)
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)

    def _stack(role):
        gen = BatchGenerator(config, params, settings=settings,
                             kv_quant=kv_quant, kv_layout="paged")
        sched = Scheduler(gen, queue_depth=4 * batch, role=role)
        sched.start(max_concurrent=batch, warm_prompt_len=8)
        return start_api_server(sched), sched

    def _fleet(roles, tag):
        stacks = [_stack(r) for r in roles]
        xfers = []
        for _, sched in stacks:
            if sched.role == "decode":
                ts = TransferServer(sched).start()
                sched.transfer_port = ts.port
                xfers.append(ts)
        monitor = HealthMonitor(
            [Backend(f"{tag}{i}", f"127.0.0.1:{srv.port}")
             for i, (srv, _) in enumerate(stacks)],
            probe_interval=0.5).start()
        gw = start_gateway(monitor, make_policy("p2c"))
        deadline = time.monotonic() + 15.0
        want = {r for r in roles if r != "mixed"}
        while time.monotonic() < deadline and want:
            if want <= {b.role for b in monitor.routable()}:
                break
            time.sleep(0.05)

        def close():
            gw.close()
            monitor.stop()
            for ts in xfers:
                ts.stop()
            for srv, sched in stacks:
                srv.close()
                sched.close()

        return f"http://127.0.0.1:{gw.port}", close

    def _leg(url, seed):
        return loadgen.run_load(
            url, n_req, concurrency=batch, max_tokens=max_tokens,
            prompt_lens=[short_len, long_len],
            vocab=config.vocab_size - 1, rate=rate, seed=seed,
            workload="mixed-prefill")

    tiered_url, tiered_close = _fleet(["prefill", "decode"], "dt")
    mixed_url, mixed_close = _fleet(["mixed", "mixed"], "dm")
    tiered_legs, mixed_legs = [], []
    try:
        # warm both fleets (compiles, transfer channel, gateway probes),
        # then interleave the measured legs A/B/A/B
        _leg(tiered_url, 1)
        _leg(mixed_url, 1)
        for rep in range(2):
            tiered_legs.append(_leg(tiered_url, 2 + rep))
            mixed_legs.append(_leg(mixed_url, 2 + rep))
    finally:
        tiered_close()
        mixed_close()

    def _agg(legs):
        gaps = [g for s in legs for r in s["results"]
                if r for g in r.get("gaps_s", ())]
        ttfts = [r["ttft_s"] for s in legs for r in s["results"]
                 if r and r.get("ttft_s") is not None]
        gaps.sort()
        ttfts.sort()

        def pct(xs, q):
            return round(
                xs[min(len(xs) - 1, int(q * (len(xs) - 1) + 0.5))] * 1e3,
                2) if xs else 0.0

        by_len = {}
        for s in legs:
            for ln, st in s.get("ttft_ms_by_prompt_len", {}).items():
                by_len.setdefault(ln, []).append(st["p95"])
        return {
            "tpot_p95_ms": pct(gaps, 0.95),
            "tpot_p50_ms": pct(gaps, 0.5),
            "ttft_p95_ms": pct(ttfts, 0.95),
            "ttft_p95_by_len": {ln: round(max(v), 1)
                                for ln, v in sorted(by_len.items())},
            "completed": sum(s["completed"] for s in legs),
            "errors": sum(s["errors"] for s in legs),
            "tok_s": round(sum(s["tokens"] for s in legs)
                           / max(1e-9, sum(s["wall_s"] for s in legs)),
                           2),
        }

    tiered, mixed = _agg(tiered_legs), _agg(mixed_legs)
    if (tiered["errors"] or mixed["errors"]
            or tiered["completed"] != 2 * n_req
            or mixed["completed"] != 2 * n_req):
        sys.stderr.write(f"disagg bench failed: tiered={tiered} "
                         f"mixed={mixed}\n")
        return 1
    ratio = (tiered["tpot_p95_ms"] / mixed["tpot_p95_ms"]
             if mixed["tpot_p95_ms"] else 0.0)
    wtag = _wtag(quant, kv_quant)
    _emit({
        "metric": (f"disagg_decode_tpot_p95_ms_{_mtag(preset)}_{wtag}_"
                   f"1chip_c{batch}"),
        "value": tiered["tpot_p95_ms"],
        "unit": "ms",
        "vs_baseline": round(ratio, 4),
    }, dev,
        baseline=f"mixed_fleet_{mixed['tpot_p95_ms']}ms",
        tiered=tiered, mixed=mixed,
        prompt_lens=[short_len, long_len], max_tokens=max_tokens,
        requests_per_leg=n_req, rate_rps=rate, interleaved_reps=2)
    sys.stderr.write(
        f"device={dev.device_kind} clients={batch} "
        f"prompts={short_len}/{long_len} "
        f"tiered tpot_p95={tiered['tpot_p95_ms']}ms "
        f"ttft_p95={tiered['ttft_p95_ms']}ms | "
        f"mixed tpot_p95={mixed['tpot_p95_ms']}ms "
        f"ttft_p95={mixed['ttft_p95_ms']}ms | ratio={ratio:.3f}\n"
    )
    return 0


class _AsciiTok:
    """Printable-ASCII toy tokenizer for the constrained-serving row: id
    -> one printable char (mod 95), so grammar compilation has real vocab
    strings without shipping a tokenizer.json in the bench image."""

    def decode(self, ids):
        return "".join(chr(32 + (i % 95)) for i in ids)

    def encode(self, text):
        return [ord(c) - 32 for c in text]


def _run_serve_constrain(config, params, preset, quant, dev, batch,
                         steps) -> int:
    """CAKE_BENCH_CONSTRAIN=1: grammar-constrained HTTP serving
    (cake_tpu/constrain) vs the same server unconstrained. The
    constrained leg runs loadgen's --workload json mode — every request
    carries a response_format json_schema and every response must
    json.loads-parse — and the figure of merit is constrained tok/s with
    vs_baseline = constrained/unconstrained (the mask gather + host-side
    DFA advance + forced single-step dispatch are the whole gap; the
    design target is within 10% on the smoke config)."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.batch_generator import BatchGenerator
    from cake_tpu.serve.api import start_api_server
    from cake_tpu.serve.scheduler import Scheduler
    from cake_tpu.tools import loadgen

    kv_quant = _kv_quant()
    batch = max(2, batch)
    max_tokens = max(32, min(steps * 2, config.max_seq_len - 16))
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    gen = BatchGenerator(config, params, settings=settings,
                         kv_quant=kv_quant, tokenizer=_AsciiTok())
    sched = Scheduler(gen, queue_depth=4 * batch)
    sched.start(max_concurrent=batch, warm_prompt_len=8,
                warm_constrain=True)
    srv = start_api_server(sched)
    url = f"http://127.0.0.1:{srv.port}"
    try:
        # warm BOTH legs: plain decode/admission compiles, then the
        # masked-program compile (each leg must measure steady state)
        loadgen.run_load(url, batch, concurrency=batch, max_tokens=4,
                         prompt_lens=[8], vocab=config.vocab_size - 1,
                         seed=1)
        loadgen.run_load(url, batch, concurrency=batch, max_tokens=4,
                         prompt_lens=[8], vocab=config.vocab_size - 1,
                         seed=1, workload="json")
        plain = loadgen.run_load(
            url, 2 * batch, concurrency=batch, max_tokens=max_tokens,
            prompt_lens=[8], vocab=config.vocab_size - 1, seed=2)
        constrained = loadgen.run_load(
            url, 2 * batch, concurrency=batch, max_tokens=max_tokens,
            prompt_lens=[8], vocab=config.vocab_size - 1, seed=3,
            workload="json")
    finally:
        srv.close()
        sched.close()
    if (constrained["errors"] or constrained["json_invalid"]
            or plain["errors"]):
        sys.stderr.write(f"constrain bench failed: plain={plain} "
                         f"constrained={constrained}\n")
        return 1
    wtag = _wtag(quant, kv_quant)
    ratio = (constrained["tok_s"] / plain["tok_s"]
             if plain["tok_s"] else 0.0)
    _emit({
        "metric": (f"serve_constrained_tokens_per_sec_{_mtag(preset)}_"
                   f"{wtag}_1chip_c{batch}"),
        "value": constrained["tok_s"],
        "unit": "tokens/s",
        "vs_baseline": round(ratio, 4),
    }, dev,
        baseline=f"unconstrained_http_{plain['tok_s']:.1f}tok/s",
        json_valid=constrained["completed"] - constrained["json_invalid"],
        requests=constrained["requests"],
        ttft_p50_ms=constrained["ttft_ms"]["p50"])
    sys.stderr.write(
        f"device={dev.device_kind} clients={batch} "
        f"constrained_tok_s={constrained['tok_s']} "
        f"unconstrained_tok_s={plain['tok_s']} ratio={ratio:.3f} "
        f"json_valid={constrained['completed']}/"
        f"{constrained['requests']}\n"
    )
    return 0


def _admit_chunk(config) -> int:
    """Largest divisor of the window <= 512 (admit_chunk must divide
    max_seq) — shared by both churn rows so the admission-chunk policy
    cannot diverge between them."""
    return max(c for c in range(1, min(512, config.max_seq_len) + 1)
               if config.max_seq_len % c == 0)


def _churn_drive(gen, base, batch, steps, stream_len, admits,
                 next_sid, e0, churn=True) -> int:
    """The ONE churn-driving loop both churn rows share (`_run_churn`
    and `_run_kvpool`): retire each stream at ``stream_len`` tokens and
    enqueue a replacement through the chunked admission path
    (``churn=False``: plain steady stepping), until the token quota is
    met or everything drains. Returns the number of admissions made."""
    admitted = 0
    for _ in range(steps * 4):
        gen.step()
        if churn:
            for s in gen.streams:
                if (s.active and not s.done
                        and len(s.generated) >= stream_len):
                    s.done = True
                    if admitted < admits:
                        gen.enqueue(list(base), next_sid)
                        next_sid += 1
                        admitted += 1
        live = any(s.active and not s.done for s in gen.streams)
        if not live and gen.pending_admissions() == 0:
            break
        if gen.stats()["tokens_emitted"] - e0 >= steps * batch:
            break
    return admitted


def _run_churn(config, params, preset, quant, dev, batch, steps,
               multistep) -> int:
    """CAKE_BENCH_CHURN=1: serving under arrival churn. Streams that reach
    CAKE_BENCH_STREAM_LEN tokens retire and a queued arrival takes the slot
    via the chunked admission path (enqueue) — the continuous-batching
    regime. The figure of merit is aggregate tok/s with churn vs the
    fixed-batch row (CAKE_BENCH_BATCH alone): admission overhead shows up
    directly as the gap."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.batch_generator import BatchGenerator

    kv_quant = _kv_quant()
    stream_len = int(os.environ.get("CAKE_BENCH_STREAM_LEN", "64"))
    admits = int(os.environ.get("CAKE_BENCH_ADMITS", str(batch)))
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    admit_chunk = _admit_chunk(config)
    # Adaptive decode blocks (CAKE_BENCH_BLOCK_MAX, default 4x the base
    # block): the fused block doubles while no arrival waits and snaps
    # back on churn — the diagnosed lever for the r4 churn row's ~1.5 s
    # dispatch wall vs ~190 ms device math (BASELINE.md). 0 disables.
    block_max = int(os.environ.get("CAKE_BENCH_BLOCK_MAX",
                                   str(4 * multistep)))
    # CAKE_BENCH_LOOKAHEAD=1: double-buffer the block dispatches (the
    # device computes block N+1 while block N's rows ride the tunnel to
    # the host) — the second r5 churn lever, orthogonal to block growth
    lookahead = os.environ.get("CAKE_BENCH_LOOKAHEAD") == "1"
    gen = BatchGenerator(config, params, settings=settings,
                         block_size=multistep, block_size_max=block_max,
                         lookahead=lookahead, kv_quant=kv_quant,
                         admit_chunk=admit_chunk)
    base = [5, 9, 2, 4, 8, 1, 3, 7]
    gen.set_prompts([list(base) for _ in range(batch)])
    for _ in range(3):  # compile + warm-up
        gen.step()
    # compile the admission-prefill program and the adaptive block ladder
    # outside the timed window
    gen.warm_admission(len(base))
    gen.warm_blocks()
    t0 = time.perf_counter()
    e0 = gen.stats()["tokens_emitted"]
    b0 = gen.stats()["busy_s"]  # exclude warm-up/compile busy time
    admitted = _churn_drive(gen, base, batch, steps, stream_len, admits,
                            next_sid=batch, e0=e0)
    # measurement boundary: tokens the device already computed (buffered
    # rows + any in-flight lookahead block) are emitted and counted — the
    # final sync pays their wall-clock either way, so dropping them would
    # under-report the lookahead arm
    gen.drain()
    _sync(gen._last_tokens)
    dt = time.perf_counter() - t0
    emitted = gen.stats()["tokens_emitted"] - e0
    agg = emitted / dt
    model_gb = _param_bytes(params) / 1e9
    roofline = _hbm_gbps(dev) / model_gb
    wtag = _wtag(quant, kv_quant)
    st = gen.stats()
    _emit({
        "metric": (f"decode_tokens_per_sec_{_mtag(preset)}_{wtag}_1chip_"
                   f"b{batch}_churn"),
        "value": round(agg, 3),
        "unit": "tokens/s",
        "vs_baseline": round(agg / roofline, 4),
    }, dev,
        baseline=f"single_stream_hbm_roofline_{roofline:.1f}tok/s",
        tokens_per_dispatch=st["tokens_per_dispatch"],
        busy_s=round(st["busy_s"] - b0, 3), wall_s=round(dt, 3))
    sys.stderr.write(
        f"device={dev.device_kind} batch={batch} stream_len={stream_len} "
        f"admitted={admitted} dispatches={st['decode_dispatches']}d+"
        f"{st['admit_dispatches']}a tokens/dispatch="
        f"{st['tokens_per_dispatch']} busy_s={st['busy_s'] - b0:.3f} "
        f"timed_s={dt:.3f}\n"
    )
    return 0


def _run_kvpool(config, params, preset, quant, dev, batch, steps,
                multistep) -> int:
    """CAKE_BENCH_KVPOOL=1: churn throughput, paged vs slot KV layout
    (cake_tpu/kvpool), plus the paged layout's own steady-batch row on
    the same config. Three legs per rep — steady/paged, churn/paged,
    churn/slot — INTERLEAVED across two reps (A/B/A/B) so warmup and
    EMA drift can't flatter one layout (the gateway row's lesson: a
    sequential comparison measured ordering bias bigger than the effect).
    Figures of merit: churn_paged/steady_paged (ROADMAP's within-25%
    target — admission/retirement as page-table edits instead of cache
    splices) and churn_paged/churn_slot."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.batch_generator import BatchGenerator

    kv_quant = _kv_quant()
    stream_len = int(os.environ.get("CAKE_BENCH_STREAM_LEN", "64"))
    admits = int(os.environ.get("CAKE_BENCH_ADMITS", str(batch)))
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    admit_chunk = _admit_chunk(config)
    block_max = int(os.environ.get("CAKE_BENCH_BLOCK_MAX",
                                   str(4 * multistep)))
    base = [5, 9, 2, 4, 8, 1, 3, 7]

    def build(layout):
        gen = BatchGenerator(config, params, settings=settings,
                             block_size=multistep, block_size_max=block_max,
                             kv_quant=kv_quant, admit_chunk=admit_chunk,
                             kv_layout=layout)
        gen.set_prompts([list(base) for _ in range(batch)])
        for _ in range(3):
            gen.step()
        gen.warm_admission(len(base))
        gen.warm_blocks()
        return gen

    def leg(layout, churn):
        gen = build(layout)
        t0 = time.perf_counter()
        e0 = gen.stats()["tokens_emitted"]
        _churn_drive(gen, base, batch, steps, stream_len, admits,
                     next_sid=batch, e0=e0, churn=churn)
        gen.drain()
        _sync(gen._last_tokens)
        dt = time.perf_counter() - t0
        return (gen.stats()["tokens_emitted"] - e0) / dt

    acc = {"steady_paged": [], "churn_paged": [], "churn_slot": []}
    for _ in range(2):  # interleaved reps: no leg owns the warm tail
        acc["steady_paged"].append(leg("paged", churn=False))
        acc["churn_paged"].append(leg("paged", churn=True))
        acc["churn_slot"].append(leg("slot", churn=True))
    mean = {k: sum(v) / len(v) for k, v in acc.items()}
    ratio_steady = (mean["churn_paged"] / mean["steady_paged"]
                    if mean["steady_paged"] else 0.0)
    ratio_slot = (mean["churn_paged"] / mean["churn_slot"]
                  if mean["churn_slot"] else 0.0)
    wtag = _wtag(quant, kv_quant)
    _emit({
        "metric": (f"decode_tokens_per_sec_{_mtag(preset)}_{wtag}_1chip_"
                   f"b{batch}_churn_paged"),
        "value": round(mean["churn_paged"], 3),
        "unit": "tokens/s",
        "vs_baseline": round(ratio_steady, 4),
    }, dev,
        baseline=f"steady_paged_{mean['steady_paged']:.1f}tok/s",
        churn_slot_tok_s=round(mean["churn_slot"], 3),
        ratio_paged_vs_slot=round(ratio_slot, 4),
        ratio_churn_vs_steady=round(ratio_steady, 4))
    sys.stderr.write(
        f"device={dev.device_kind} batch={batch} "
        f"steady_paged={mean['steady_paged']:.1f} "
        f"churn_paged={mean['churn_paged']:.1f} "
        f"churn_slot={mean['churn_slot']:.1f} tok/s "
        f"churn/steady={ratio_steady:.3f} paged/slot={ratio_slot:.3f}\n"
    )
    return 0


def _run_spec_serving(config, params, preset, quant, dev, batch, steps,
                      k) -> int:
    """CAKE_BENCH_SPEC=K with CAKE_BENCH_BATCH=N: batched serving
    speculation — every live stream's K n-gram proposals verified in ONE
    per-row dispatch (runtime/batch_generator spec_k plane). The figure of
    merit is aggregate tok/s on self-repeating streams plus
    tokens-per-dispatch; contrast with the plain CAKE_BENCH_BATCH row to
    see the dispatch amortization."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.batch_generator import BatchGenerator

    kv_quant = _kv_quant()
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    gen = BatchGenerator(config, params, settings=settings, spec_k=k,
                         kv_quant=kv_quant)
    base = [5, 9, 2, 5, 9, 2, 5, 9]
    gen.set_prompts([[(t + i) % (config.vocab_size - 1) + 1 for t in base]
                     for i in range(batch)])
    for _ in range(4):  # compile (verify program) + warm
        gen.step()
    t0 = time.perf_counter()
    e0 = gen.stats()["tokens_emitted"]
    for _ in range(steps * 4):
        gen.step()
        if gen.stats()["tokens_emitted"] - e0 >= steps * batch:
            break
    _sync(gen._last_tokens)
    dt = time.perf_counter() - t0
    emitted = gen.stats()["tokens_emitted"] - e0
    agg = emitted / dt
    model_gb = _param_bytes(params) / 1e9
    roofline = _hbm_gbps(dev) / model_gb
    wtag = _wtag(quant, kv_quant)
    st = gen.stats()
    _emit({
        "metric": (f"decode_tokens_per_sec_{_mtag(preset)}_{wtag}_1chip_"
                   f"b{batch}_spec{k}"),
        "value": round(agg, 3),
        "unit": "tokens/s",
        "vs_baseline": round(agg / roofline, 4),
    }, dev,
        baseline=f"single_stream_hbm_roofline_{roofline:.1f}tok/s",
        tokens_per_dispatch=st["tokens_per_dispatch"])
    sys.stderr.write(
        f"device={dev.device_kind} batch={batch} spec_k={k} "
        f"spec_dispatches={st['spec_dispatches']} "
        f"tokens/dispatch={st['tokens_per_dispatch']} "
        f"(self-repeating streams: favorable-regime acceptance)\n"
    )
    return 0


def _run_spec_corpus(config, params, preset, quant, dev, steps) -> int:
    """CAKE_BENCH_SPEC=K + CAKE_BENCH_SPEC_CORPUS=1: teacher-forced replay
    of the embedded REAL-text corpus (cake_tpu/utils/corpus.py) through the
    fused speculation machinery — the honest companion to the synthetic
    self-repeating row (r4 verdict item 6). Acceptance is decided by
    whether the n-gram proposals match the corpus's actual next tokens
    (real prose/code repetition statistics); every round still pays the
    true [1, K+1] verification forward, so tok/s carries the real
    dispatch + FLOP cost. The replay is capped at ONE corpus pass (a
    wrapped stream degenerates to the synthetic best case — see
    corpus.py). Row fields: tokens_per_round (the figure of merit),
    acceptance (mean accepted proposals / K)."""
    from cake_tpu.ops.kvcache import init_cache
    from cake_tpu.runtime.generator import prefill_fn
    from cake_tpu.runtime.speculative import spec_replay_fn
    from cake_tpu.utils.corpus import corpus_tokens

    k = int(os.environ.get("CAKE_BENCH_SPEC", "8"))
    rounds = int(os.environ.get("CAKE_BENCH_SPEC_ROUNDS", "8"))
    kv_quant = _kv_quant()
    if kv_quant:
        sys.exit("error: CAKE_BENCH_SPEC_CORPUS does not take CAKE_BENCH_KV "
                 "(the replay path uses the plain single-chip cache)")
    toks = corpus_tokens(config.vocab_size)  # ONE pass, no wrap
    window = min(config.max_seq_len, len(toks))
    prompt_len = min(64, window // 4)
    corpus_dev = jnp.asarray(toks[:window])

    cache = init_cache(config, batch=1, max_seq=config.max_seq_len)
    prefill = jax.jit(partial(prefill_fn, config=config),
                      donate_argnames=("cache",))
    logits, cache = prefill(
        params, corpus_dev[None, :prompt_len], cache,
        jnp.asarray([prompt_len - 1], jnp.int32),
    )
    _sync(logits)

    replay = jax.jit(
        partial(spec_replay_fn, config=config, k=k, n_max=3, rounds=rounds),
        donate_argnames=("cache",),
    )
    # corpus[0..prompt_len-1] is in the cache; the stream's next known
    # token corpus[prompt_len] feeds the first verify at that position
    # (its KV is written by that round's fed[0], like live speculation)
    pos = jnp.int32(prompt_len)
    acc = jnp.float32(0.0)
    counts, pos, cache, acc = replay(params, corpus_dev, pos, cache, acc)
    _sync(counts)  # compile + warm (positions advanced: replay continues)

    t0 = time.perf_counter()
    dispatches = 0
    all_counts = [np.asarray(counts)]
    pos_h = int(pos)
    headroom = rounds * (k + 1) + 1
    while pos_h + headroom < window and dispatches < steps:
        counts, pos, cache, acc = replay(params, corpus_dev, pos, cache, acc)
        pos_h = int(pos)  # the one sync per chain (by design)
        all_counts.append(np.asarray(counts))
        dispatches += 1
    _sync(acc)
    dt = time.perf_counter() - t0
    if dispatches == 0:
        sys.exit("error: corpus/window too short for one timed replay "
                 f"chain (window {window}, need {headroom} headroom)")

    counts_np = np.concatenate(all_counts[1:])  # timed rounds only
    emitted = int(counts_np.sum())
    tok_s = emitted / dt
    per_round = counts_np.mean()
    acceptance = (counts_np - 1).mean() / k
    model_gb = _param_bytes(params) / 1e9
    roofline = _hbm_gbps(dev) / model_gb
    wtag = _wtag(quant, kv_quant)
    _emit({
        "metric": (f"decode_tokens_per_sec_{_mtag(preset)}_{wtag}_1chip_"
                   f"spec{k}_corpus"),
        "value": round(tok_s, 3),
        "unit": "tokens/s",
        "vs_baseline": round(tok_s / roofline, 4),
    }, dev,
        baseline=f"single_stream_hbm_roofline_{roofline:.1f}tok/s",
        mode="teacher_forced_corpus_replay_bytes",
        tokens_per_round=round(float(per_round), 2),
        # per DISPATCH = per host sync (one replay chain of `rounds`
        # verifies), matching _run_speculative's definition
        tokens_per_dispatch=round(emitted / dispatches, 2),
        acceptance=round(float(acceptance), 4),
        rounds_per_dispatch=rounds)
    sys.stderr.write(
        f"device={dev.device_kind} spec_k={k} rounds={rounds} "
        f"corpus_window={window} dispatches={dispatches} "
        f"tokens/round={per_round:.2f} acceptance={acceptance:.3f} "
        f"(teacher-forced byte-level corpus replay — real-text n-gram "
        f"statistics, true verify cost)\n"
    )
    return 0


def _run_speculative(config, params, preset, quant, dev, steps) -> int:
    """CAKE_BENCH_SPEC=K: greedy decode with n-gram speculation on a
    self-repeating stream (the favorable regime — repetitive/structured
    text; acceptance is printed so the row is honest about it). The win is
    structural: tokens-per-dispatch > 1 amortizes the per-token HBM weight
    sweep that bounds plain decode."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.speculative import SpeculativeGenerator

    k = int(os.environ.get("CAKE_BENCH_SPEC", "8"))
    # Fused rounds per host sync (default 8). The w3 on-chip row measured
    # ~94 ms of math against ~170 ms of tunnel sync RTT per dispatch —
    # more rounds amortize the RTT further (the knob exists to measure
    # that curve; on a local chip RTT is ~1 ms and 8 is already enough).
    rounds = int(os.environ.get("CAKE_BENCH_SPEC_ROUNDS", "8"))
    kv_quant = _kv_quant()
    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.0)
    gen = SpeculativeGenerator(config, params, settings=settings,
                               spec_k=k, spec_rounds=rounds,
                               kv_quant=kv_quant)
    prompt = [5, 9, 2, 5, 9, 2, 5, 9]
    gen.set_prompt(prompt)
    gen.next_token(0)  # prefill + compile
    warm = 8
    for i in range(1, warm):
        gen.next_token(i)
    d0, e0, r0 = gen.dispatches, gen.emitted, gen.rounds
    t0 = time.perf_counter()
    n = 0
    while gen.emitted - e0 < steps and gen._pos < config.max_seq_len - k - 1:
        gen.next_token(warm + n)
        n += 1
    _sync(gen._history)
    dt = time.perf_counter() - t0
    timed = gen.emitted - e0
    tok_s = timed / dt
    accept = timed / max(1, gen.dispatches - d0)
    per_round = timed / max(1, gen.rounds - r0)
    model_gb = _param_bytes(params) / 1e9
    roofline = _hbm_gbps(dev) / model_gb
    wtag = _wtag(quant, kv_quant)
    rounds_per_dispatch = (gen.rounds - r0) / max(1, gen.dispatches - d0)
    _emit({
        "metric": f"decode_tokens_per_sec_{_mtag(preset)}_{wtag}_1chip_spec{k}",
        "value": round(tok_s, 3),
        "unit": "tokens/s",
        "vs_baseline": round(tok_s / roofline, 4),
    }, dev,
        baseline=f"single_stream_hbm_roofline_{roofline:.1f}tok/s",
        tokens_per_dispatch=round(accept, 2),
        tokens_per_round=round(per_round, 2),
        rounds_per_dispatch=round(rounds_per_dispatch, 2))
    sys.stderr.write(
        f"device={dev.device_kind} params={model_gb:.2f}GB spec_k={k} "
        f"rounds/dispatch={rounds_per_dispatch:.2f} "
        f"tokens/round={per_round:.2f} "
        f"tokens/dispatch={accept:.2f} timed_tokens={timed} "
        f"(self-repeating stream: favorable-regime acceptance)\n"
    )
    return 0


def main() -> int:
    preset = os.environ.get("CAKE_BENCH_PRESET", "8b")
    if (os.environ.get("CAKE_BENCH_NO_FALLBACK") != "1"
            and os.environ.get("CAKE_BENCH_PROBED") != "1"
            and os.environ.get("JAX_PLATFORMS", "") != "cpu"
            and not _device_init_probe_retried()):
        sys.stderr.write("device init hung or failed; re-running on CPU\n")
        _reexec(cpu=True, CAKE_BENCH_PRESET="tiny")
    if preset not in ("8b", "small", "tiny"):
        sys.stderr.write(f"unknown CAKE_BENCH_PRESET={preset!r}, using tiny\n")
        preset = "tiny"
    steps = int(os.environ.get("CAKE_BENCH_STEPS", "128"))

    from cake_tpu.models.llama import init_params
    from cake_tpu.ops.kvcache import init_cache
    from cake_tpu.ops.sampling import SamplerSettings, init_history
    from cake_tpu.runtime.generator import (
        decode_scan_fn,
        decode_step_fn,
        prefill_fn,
    )

    dev = jax.devices()[0]
    key = jax.random.PRNGKey(0)

    # OOM fallback ladder: if the requested rung does not fit this chip's
    # HBM, step down and say so (blocked inside the try so async allocation
    # failures are actually caught here, not at first use). 8B bf16 is
    # 14.96 GiB of weights against ~14.5 GiB usable v5e HBM (measured:
    # the runtime reserves ~1.5 GiB of the 16), so the rung below it is
    # 8B int8 — the same model at half the bytes, matching the reference's
    # quantized deployment tier (BASELINE.md config 5).
    quant = os.environ.get("CAKE_BENCH_QUANT", "")
    if quant not in ("", "int8", "int4"):
        sys.exit(
            f"error: CAKE_BENCH_QUANT must be 'int8' or 'int4', got {quant!r}"
        )
    rung = (preset, quant)
    default_ladder = [("8b", ""), ("8b", "int8"), ("small", ""), ("tiny", "")]
    if os.environ.get("CAKE_BENCH_FAMILY", "llama") != "llama":
        # family geometries exist only at the 8b rung (the fallback
        # presets are llama shapes); stepping into them would error out
        # of _config instead of degrading — cap the ladder at the int8
        # rung and let the no-rung-fits path fall to CPU (which drops the
        # family knob in _reexec)
        default_ladder = default_ladder[:2]
    on_default = rung == ("8b", "") or (
        # a step-down re-exec from the default ladder stays on it (marker
        # env set by _reexec below) — otherwise the int8 rung would leak
        # int8 into the small/tiny fallbacks
        os.environ.get("CAKE_BENCH_LADDER") == "default"
        and rung in default_ladder
    )
    if on_default:
        ladder = default_ladder
    else:
        # an explicit preset/quant choice steps down presets only, keeping
        # the requested weight dtype — never silently benchmark a dtype the
        # user did not ask for
        presets = ["8b", "small", "tiny"]
        ladder = [(p, quant) for p in presets[presets.index(preset):]]
    # HBM preflight: gate EVERY rung — including the last — behind the
    # budget arithmetic before anything reaches the compiler. The r3 wedge
    # followed an OOM-failed compile, and a killed/failed compile can wedge
    # the remote grant for hours, so an OOM-able config must never compile
    # at all. The estimate is params+KV (utils/memory.hbm_budget) times a
    # margin for XLA temporaries (fusion scratch, f32 logits, donation
    # double-buffering — the r3 OOM row showed the raw estimate running
    # ~1.5 GiB light), against capacity minus the measured ~9% runtime
    # reserve. If no rung fits, fall to CPU WITHOUT attempting a compile.
    # The try/except ladder below remains the backstop for when the
    # estimate is still wrong.
    if dev.platform != "cpu":
        from cake_tpu.utils.memory import hbm_budget

        usable = _device_spec(dev, _HBM_GIB, 16.0) * 0.91 * 2**30
        margin = float(os.environ.get("CAKE_BENCH_HBM_MARGIN", "1.10"))
        bench_batch = max(1, int(os.environ.get("CAKE_BENCH_BATCH", "1")))
        if os.environ.get("CAKE_BENCH_CHURN") == "1":
            # price what _run_churn will actually allocate (it floors the
            # batch at 2 so there is churn to measure)
            bench_batch = max(2, bench_batch)
        idx = ladder.index(rung)
        while idx < len(ladder):
            p_, q_ = ladder[idx]
            est = margin * hbm_budget(
                _config(p_), batch=bench_batch, quant=q_ or None,
                cache_bytes_per_el=1 if os.environ.get("CAKE_BENCH_KV")
                else 2,
            )["total"]
            if est <= usable:
                break
            sys.stderr.write(
                f"preset={p_}{'+' + q_ if q_ else ''} needs "
                f"~{est / 2**30:.1f} GiB (x{margin:.2f} temp margin) > "
                f"~{usable / 2**30:.1f} GiB usable on {dev.device_kind}; "
                f"skipping to the next rung\n"
            )
            idx += 1
        if idx == len(ladder):
            if os.environ.get("CAKE_BENCH_NO_FALLBACK") != "1":
                sys.stderr.write(
                    "no ladder rung fits this chip's HBM; re-running on "
                    "CPU without attempting a compile\n"
                )
                _reexec(cpu=True, CAKE_BENCH_PRESET="tiny")
            sys.stderr.write("no ladder rung fits this device\n")
            return 1
        rung = ladder[idx]
        preset, quant = rung
    params = config = None
    cfg = _config(preset)
    # A freshly released chip can still hold the previous process's memory
    # for a few seconds (remote runtime); retry before stepping down so a
    # transient RESOURCE_EXHAUSTED doesn't shrink the model.
    for attempt in range(3):
        try:
            if quant == "int8":
                # generate-and-quantize per layer: peak HBM stays near the
                # int8 total instead of bf16 + int8 (llama.init_params_int8)
                from cake_tpu.models.llama import init_params_int8

                candidate = init_params_int8(cfg, key)
            elif quant == "int4":
                from cake_tpu.models.llama import init_params_int4

                candidate = init_params_int4(cfg, key)
            else:
                candidate = init_params(cfg, key)
            _sync(candidate)
            params, config = candidate, cfg
            break
        except Exception as e:
            sys.stderr.write(
                f"init at preset={preset}{'+' + quant if quant else ''} "
                f"failed ({e}); attempt {attempt + 1}/3\n"
            )
            candidate = None
            # only a transient grant-release is worth waiting out, and
            # never after the last attempt (we step down immediately)
            if "RESOURCE_EXHAUSTED" not in str(e) or attempt == 2:
                break
            time.sleep(15 * (attempt + 1))
    if params is None and ladder.index(rung) + 1 < len(ladder):
        # Step down ONE rung in a FRESH process: a failed multi-GB
        # allocation can poison this client (subsequent small allocations
        # keep failing in-process even though a fresh process succeeds).
        nxt_preset, nxt_quant = ladder[ladder.index(rung) + 1]
        sys.stderr.write(
            f"stepping down to preset={nxt_preset}"
            f"{'+' + nxt_quant if nxt_quant else ''} in a fresh process\n"
        )
        _reexec(CAKE_BENCH_PRESET=nxt_preset, CAKE_BENCH_QUANT=nxt_quant,
                CAKE_BENCH_PROBED="1",
                CAKE_BENCH_LADDER="default" if on_default else "")
    if params is None:
        # Accelerator unusable (e.g. a wedged remote grant holding HBM):
        # fall back to CPU so the driver still gets a benchmark line, unless
        # we are already on CPU.
        if dev.platform != "cpu" and os.environ.get("CAKE_BENCH_NO_FALLBACK") != "1":
            sys.stderr.write("no preset fits; re-running on CPU fallback\n")
            _reexec(cpu=True, CAKE_BENCH_PRESET="tiny")
        sys.stderr.write("no preset fits this device\n")
        return 1

    settings = SamplerSettings(temperature=0.0, repeat_penalty=1.1)
    multistep = int(os.environ.get("CAKE_BENCH_MULTISTEP", "16"))
    batch = int(os.environ.get("CAKE_BENCH_BATCH", "1"))
    if os.environ.get("CAKE_BENCH_PREFILL") == "1":
        return _run_prefill(config, params, preset, quant, dev)
    if os.environ.get("CAKE_BENCH_TTFT") == "1":
        return _run_ttft(config, params, preset, quant, dev)
    if os.environ.get("CAKE_BENCH_OBS") == "1":
        return _run_obs_overhead(config, params, preset, quant, dev, steps)
    if os.environ.get("CAKE_BENCH_SERVE") == "1":
        return _run_serve_http(config, params, preset, quant, dev, batch,
                               steps)
    if os.environ.get("CAKE_BENCH_CONSTRAIN") == "1":
        return _run_serve_constrain(config, params, preset, quant, dev,
                                    batch, steps)
    if os.environ.get("CAKE_BENCH_GATEWAY") == "1":
        return _run_gateway_http(config, params, preset, quant, dev,
                                 batch, steps)
    if os.environ.get("CAKE_BENCH_DISAGG") == "1":
        return _run_disagg(config, params, preset, quant, dev,
                           max(2, batch), steps)
    if os.environ.get("CAKE_BENCH_SLO") == "1":
        return _run_slo(config, params, preset, quant, dev,
                        max(2, batch), steps)
    if os.environ.get("CAKE_BENCH_SPEC"):
        k = int(os.environ["CAKE_BENCH_SPEC"])
        if os.environ.get("CAKE_BENCH_SPEC_CORPUS") == "1":
            return _run_spec_corpus(config, params, preset, quant, dev,
                                    steps)
        if batch > 1:
            return _run_spec_serving(config, params, preset, quant, dev,
                                     batch, steps, k)
        return _run_speculative(config, params, preset, quant, dev, steps)
    if os.environ.get("CAKE_BENCH_KVPOOL") == "1":
        return _run_kvpool(config, params, preset, quant, dev,
                           max(2, batch), steps, multistep)
    if os.environ.get("CAKE_BENCH_CHURN") == "1":
        return _run_churn(config, params, preset, quant, dev,
                          max(2, batch), steps, multistep)
    if batch > 1:
        return _run_batched(config, params, preset, quant, settings, dev,
                            batch, steps, multistep)
    kv_quant = _kv_quant()
    cache = init_cache(config, batch=1, max_seq=config.max_seq_len,
                       quant=kv_quant)
    history, hist_slot = init_history(settings.repeat_last_n)

    if multistep > 1:
        decode = jax.jit(
            partial(decode_scan_fn, config=config, settings=settings,
                    steps=multistep),
            donate_argnames=("cache",),
        )
    else:
        decode = jax.jit(
            partial(decode_step_fn, config=config, settings=settings),
            donate_argnames=("cache",),
        )

    # prefill a short prompt so decode runs from a warm cache
    prompt = jnp.asarray([[1, 5, 9, 14, 3, 8, 2, 4]], jnp.int32)
    prefill = jax.jit(partial(prefill_fn, config=config), donate_argnames=("cache",))
    t_pf0 = time.perf_counter()
    logits, cache = prefill(params, prompt, cache, jnp.asarray([7], jnp.int32))
    _sync(logits)
    ttft_s = time.perf_counter() - t_pf0  # includes compile (cold TTFT)

    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:1]
    pos = 8

    def step_once(tok, cache, history, hist_slot, pos):
        out = decode(
            params, tok.reshape(1), cache, jnp.int32(pos), key, history,
            hist_slot,
        )
        if multistep > 1:
            toks, cache, history, hist_slot = out
            return toks[-1], cache, history, hist_slot, pos + multistep
        tok, cache, history, hist_slot = out
        return tok, cache, history, hist_slot, pos + 1

    # never overrun the KV window: prompt(8) + 3 warm-up dispatches + timed
    # dispatches must fit max_seq (dynamic_update_slice would clamp silently
    # and the timed loop would rewrite the last slot at wrong positions).
    # Checked BEFORE warm-up so an invalid combination fails fast instead of
    # burning compiles on clamped writes.
    per = max(1, multistep)
    max_dispatches = (config.max_seq_len - 8) // per - 3
    if max_dispatches < 1:
        sys.exit(
            f"error: CAKE_BENCH_SEQ={config.max_seq_len} too small for "
            f"CAKE_BENCH_MULTISTEP={multistep}"
        )
    dispatches = max(1, min(steps // per, max_dispatches))

    # warm-up (compile + 2 dispatches)
    for _ in range(3):
        tok, cache, history, hist_slot, pos = step_once(
            tok, cache, history, hist_slot, pos
        )
    _sync(tok)

    t0 = time.perf_counter()
    for _ in range(dispatches):
        tok, cache, history, hist_slot, pos = step_once(
            tok, cache, history, hist_slot, pos
        )
    _sync(tok)
    dt = time.perf_counter() - t0

    timed_tokens = dispatches * per
    toks_per_s = timed_tokens / dt
    model_gb = _param_bytes(params) / 1e9
    roofline = _hbm_gbps(dev) / model_gb  # ideal decode tok/s (weights-bound)

    wtag = _wtag(quant, kv_quant)
    _emit({
        "metric": f"decode_tokens_per_sec_{_mtag(preset)}_{wtag}_1chip",
        "value": round(toks_per_s, 3),
        "unit": "tokens/s",
        "vs_baseline": round(toks_per_s / roofline, 4),
    }, dev, baseline=f"single_stream_hbm_roofline_{roofline:.1f}tok/s")
    sys.stderr.write(
        f"device={dev.device_kind} params={model_gb:.2f}GB "
        f"roofline={roofline:.1f}tok/s ttft_cold={ttft_s:.2f}s "
        f"timed_tokens={timed_tokens} multistep={per}\n"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
