#!/bin/bash
# r5 wait-then-measure queue. Probes the tunnel grant every 20 min; on the
# first healthy probe it lands the round's row ladder, safest rows first
# (the r3/r4 record: a wedge usually follows a crashed/OOM compile, so the
# known-good acquisition paths run before anything compile-heavy, and the
# kernel sweeps — which crashed the r4w2 grant — run last). Every row
# appends to bench_results.jsonl the moment it lands, so a mid-ladder
# wedge cannot erase earlier evidence.
set -u
LOG=${LOG:-/tmp/bench_queue5.log}
cd /root/repo

probe() {
  timeout -k 10 240 python -c \
    "import jax; d = jax.devices()[0]; assert d.platform == 'tpu', d; print('healthy:', d.device_kind)" \
    >>"$LOG" 2>&1
}

run_row() {
  echo "=== $(date -u +%FT%TZ) row: $* ===" >>"$LOG"
  env "$@" CAKE_BENCH_PROBE_BUDGET=120 python -u bench.py >>"$LOG" 2>&1
  echo "--- exit $? $(date -u +%FT%TZ)" >>"$LOG"
}

run_tool() {
  name=$1; shift
  echo "=== $(date -u +%FT%TZ) $name ===" >>"$LOG"
  timeout -k 30 2400 python -u -m "cake_tpu.tools.$name" "$@" >>"$LOG" 2>&1
  echo "--- $name exit $? $(date -u +%FT%TZ)" >>"$LOG"
}

echo "queue5 start $(date -u +%FT%TZ)" >>"$LOG"
for i in $(seq 1 40); do
  if probe; then
    echo "grant healthy at probe $i $(date -u +%FT%TZ)" >>"$LOG"
    # -- tier 1: the metric of record + known-good acquisition paths -----
    run_row CAKE_BENCH_PRESET=8b                       # int8 84.8 record path
    run_row CAKE_BENCH_MULTISTEP=32                    # record-beater attempt:
                                                       # half the host syncs
    run_row CAKE_BENCH_TTFT=1
    # -- tier 2: the r5 feature rows (verdict items 4 and 6) -------------
    run_row CAKE_BENCH_CHURN=1                         # adaptive blocks (64 max)
    run_row CAKE_BENCH_CHURN=1 CAKE_BENCH_LOOKAHEAD=1  # + double-buffered dispatch
    run_row CAKE_BENCH_CHURN=1 CAKE_BENCH_BLOCK_MAX=0  # control: r4 behavior
    run_row CAKE_BENCH_SPEC=8 CAKE_BENCH_SPEC_CORPUS=1 CAKE_BENCH_SEQ=2048
    run_row CAKE_BENCH_SPEC=8                          # synthetic companion
    # -- tier 3: quantized tiers + serving ------------------------------
    run_row CAKE_BENCH_BATCH=8                         # refresh the 465 tok/s
                                                       # aggregate (r2-era row)
    run_row CAKE_BENCH_QUANT=int4
    run_row CAKE_BENCH_QUANT=int4 CAKE_BENCH_BATCH=8
    run_row CAKE_BENCH_BATCH=8 CAKE_BENCH_SEQ=4096 CAKE_BENCH_KV=int8
    # -- tier 4: the 70B stage-slice pricing (verdict item 7) ------------
    run_tool stage_slice --json-out STAGE_SLICE_r5.json
    # -- tier 5: kernel evidence regen (crashed the r4w2 grant; run last)
    run_tool int4_sweep --json-out INT4_SWEEP_r5.json
    run_tool kernel_check --json-out KERNELS_TPU_r5.json
    run_tool flash_sweep --json-out FLASH_SWEEP_r5.json
    echo "queue5 done $(date -u +%FT%TZ)" >>"$LOG"
    exit 0
  fi
  echo "probe $i wedged $(date -u +%FT%TZ); sleeping 20m" >>"$LOG"
  sleep 1200
done
echo "queue5 gave up $(date -u +%FT%TZ)" >>"$LOG"
