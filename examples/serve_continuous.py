"""Runnable tour of the serving plane on a tiny random-weight model (CPU).

Shows the capabilities the single-request reference has no answer to
(SURVEY.md §0), end to end in a few seconds:

- concurrent streams with per-row positions and per-stream keys
- shared-prefix detection (the system prompt is prefilled once)
- continuous batching: an arrival enqueued mid-run is admitted chunk by
  chunk alongside decode, then its slot streams like any other
- batched n-gram speculation: every stream's proposals verified in one
  per-row dispatch (tokens/dispatch > 1 on repetitive streams)
- int8 KV cache + serving stats

Run:  python examples/serve_continuous.py
(set XLA_FLAGS=--xla_force_host_platform_device_count=8 to also shard
over stages/tp on virtual devices)
"""

import jax

from cake_tpu.models.config import tiny
from cake_tpu.models.llama import init_params
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.batch_generator import BatchGenerator


def main() -> None:
    cfg = tiny(max_seq_len=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    system_prompt = [(i * 7) % (cfg.vocab_size - 2) + 1 for i in range(32)]

    gen = BatchGenerator(
        cfg, params,
        settings=SamplerSettings(temperature=0.0, repeat_penalty=1.1),
        dp=1, block_size=4, kv_quant="int8", admit_chunk=16,
        prefix_share_min=16, spec_k=4,
    )
    gen.set_prompts([
        system_prompt + [5, 9, 2],
        system_prompt + [3, 1, 4, 1],
        system_prompt + [8, 8],
    ])
    print("3 streams admitted; shared 32-token prefix prefilled once "
          f"({gen.stats()['admit_dispatches']} prefix dispatch(es))")

    for step in range(20):
        gen.step()
        if step == 4:
            # a request arrives mid-run: it reuses the cached prefix row
            # and prefills only its remainder, interleaved with decode
            gen.finish(stream_id=2)  # pretend stream 2 finished
            gen.enqueue(system_prompt + [2, 6, 4], stream_id=3)
            print("step 5: stream 2 retired, arrival enqueued")
        if gen.pending_admissions() == 0 and step == 8:
            print("step 9: arrival fully admitted (prefix reused)")

    st = gen.stats()
    print(f"\n{st['tokens_emitted']} tokens over "
          f"{st['decode_dispatches']} decode ({st['spec_dispatches']} "
          f"speculative) + {st['admit_dispatches']} admission dispatches "
          f"({st['tokens_per_dispatch']} tokens/dispatch, "
          f"{st['prefix_hits']} prefix hit(s))")
    for i, s in enumerate(gen.streams):
        if s.active:
            print(f"stream {i} (id {s.stream_id}): {s.generated}")

    # Everything above is the in-process engine API. The same engine
    # serves over the network: `python -m cake_tpu.cli --model ... --mode
    # serve` puts an HTTP front end (POST /v1/completions with SSE
    # streaming, admission queue, backpressure) on top of exactly these
    # enqueue/step/finish calls, and `python -m cake_tpu.tools.loadgen`
    # drives it with concurrent clients — see README "Serving over HTTP".


if __name__ == "__main__":
    main()
