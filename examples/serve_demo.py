"""Runnable serving demo — no checkpoint required (tiny random weights).

Shows the serving plane the reference has no equivalent of
(SURVEY.md §0: strictly single-request): N concurrent streams over one
model instance, continuous admission of arrivals mid-run, the adaptive
decode-block ladder, and lookahead double-buffered dispatch. Runs on CPU
in a few seconds:

    python examples/serve_demo.py

Swap ``tiny()`` + ``init_params`` for ``LlamaConfig.from_hf_json`` + the
checkpoint loaders (see README "Multi-stream serving") to serve a real
model the same way; every call below is the production API.
"""

import jax

from cake_tpu.models.config import tiny
from cake_tpu.models.llama import init_params
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.batch_generator import BatchGenerator


def main() -> None:
    cfg = tiny(max_seq_len=128, eos_token_id=-1)
    params = init_params(cfg, jax.random.PRNGKey(0))

    gen = BatchGenerator(
        cfg, params,
        settings=SamplerSettings(temperature=0.8, top_k=40, seed=7),
        block_size=2,        # fused decode steps per dispatch (base)
        block_size_max=8,    # ...doubling while no arrival waits
        lookahead=True,      # dispatch block N+1 before fetching block N
        admit_chunk=32,      # admission prefill chunk per step
    )

    # four concurrent prompts (token ids; pass strings with a tokenizer)
    gen.set_prompts([[5, 9, 2, 11], [3, 1, 4, 1, 5], [7, 7, 2],
                     [2, 8, 1, 6]])
    for _ in range(10):
        gen.step()

    # continuous batching: retire a stream, admit an arrival in its slot —
    # the running batch never stalls behind the new prompt's prefill
    gen.finish(stream_id=0)
    gen.enqueue([4, 4, 2, 9, 1, 3], stream_id=99)
    for _ in range(14):
        gen.step()
    gen.drain()  # emit what the lookahead pipeline already computed

    for s in gen.streams:
        print(f"stream {s.stream_id}: prompt {s.prompt} -> "
              f"{len(s.generated)} tokens {s.generated[:10]}...")
    st = gen.stats()
    print(f"\n{st['tokens_emitted']} tokens in {st['decode_dispatches']} "
          f"decode + {st['admit_dispatches']} admission dispatches "
          f"({st['tokens_per_dispatch']} tokens/dispatch, "
          f"busy {st['busy_s']}s of {st['wall_s']}s wall)")


if __name__ == "__main__":
    main()
