#!/bin/bash
# Wait-then-measure queue (r4): probe the tunnel grant gently until it
# resets, then run the on-chip bench rows in safe-first order (verdict
# item 1b). Gentle cadence — an aggressive probe against a wedged grant
# can re-wedge it (BASELINE.md r3/r4 measurement notes). Every row goes
# through bench.py's own hardened acquisition (HBM preflight on every
# rung, incremental bench_results.jsonl ledger), and NOTHING here kills
# a bench mid-run: a SIGKILLed in-flight compile is what wedges the
# grant in the first place.
set -u
LOG=${LOG:-/tmp/bench_queue.log}
cd /root/repo

probe() {
  # A healthy chip answers in ~15s; 240s timeout matches the r4 monitor
  # cadence that never deepened the wedge.
  timeout -k 10 240 python -c \
    "import jax; d = jax.devices()[0]; assert d.platform == 'tpu', d; print('healthy:', d.device_kind)" \
    >>"$LOG" 2>&1
}

run_row() {
  echo "=== $(date -u +%FT%TZ) row: $* ===" >>"$LOG"
  # Probe budget is small here: the grant was just verified healthy, so a
  # failure means it wedged between rows — degrade fast, keep the ledger.
  env "$@" CAKE_BENCH_PROBE_BUDGET=120 python -u bench.py >>"$LOG" 2>&1
  echo "--- exit $? $(date -u +%FT%TZ)" >>"$LOG"
}

echo "monitor start $(date -u +%FT%TZ)" >>"$LOG"
for i in $(seq 1 40); do
  if probe; then
    echo "grant healthy at probe $i $(date -u +%FT%TZ)" >>"$LOG"
    run_row                                   # default row: driver-grade record first
    run_row CAKE_BENCH_QUANT=int4             # int4 tier: 2x the int8 roofline
    run_row CAKE_BENCH_TTFT=1                 # p50/p95 TTFT (metric of record)
    run_row CAKE_BENCH_SPEC=8                 # n-gram speculation
    run_row CAKE_BENCH_CHURN=1                # continuous-batching churn
    run_row CAKE_BENCH_SPEC=8 CAKE_BENCH_BATCH=4  # batched serving speculation
    run_row CAKE_BENCH_QUANT=int4 CAKE_BENCH_BATCH=8  # int4 aggregate serving
    run_row CAKE_BENCH_BATCH=8 CAKE_BENCH_SEQ=4096 CAKE_BENCH_KV=int8  # riskiest last
    echo "=== $(date -u +%FT%TZ) kernel_check ===" >>"$LOG"
    python -u -m cake_tpu.tools.kernel_check --json-out KERNELS_TPU_r4.json >>"$LOG" 2>&1
    echo "=== $(date -u +%FT%TZ) flash_sweep ===" >>"$LOG"
    python -u -m cake_tpu.tools.flash_sweep --json-out FLASH_SWEEP_r4.json >>"$LOG" 2>&1
    echo "queue done $(date -u +%FT%TZ)" >>"$LOG"
    exit 0
  fi
  echo "probe $i wedged $(date -u +%FT%TZ); sleeping 20m" >>"$LOG"
  sleep 1200
done
echo "gave up after 40 probes $(date -u +%FT%TZ)" >>"$LOG"
exit 1
