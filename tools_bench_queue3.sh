#!/bin/bash
# Third-stage queue (r4): the sync-amortization curve rows the w3 records
# motivated — spec with more fused rounds per host sync (94 ms math vs
# ~170 ms tunnel RTT per dispatch at rounds=8) and churn with a larger
# fused block between admission checks. Run AFTER tools_bench_queue2.sh.
set -u
LOG=${LOG:-/tmp/bench_queue3.log}
cd /root/repo

probe() {
  timeout -k 10 240 python -c \
    "import jax; d = jax.devices()[0]; assert d.platform == 'tpu', d; print('healthy:', d.device_kind)" \
    >>"$LOG" 2>&1
}

run_row() {
  echo "=== $(date -u +%FT%TZ) row: $* ===" >>"$LOG"
  env "$@" CAKE_BENCH_PROBE_BUDGET=120 python -u bench.py >>"$LOG" 2>&1
  echo "--- exit $? $(date -u +%FT%TZ)" >>"$LOG"
}

echo "monitor3 start $(date -u +%FT%TZ)" >>"$LOG"
for i in $(seq 1 30); do
  if probe; then
    echo "grant healthy at probe $i $(date -u +%FT%TZ)" >>"$LOG"
    run_row CAKE_BENCH_SPEC=8 CAKE_BENCH_SPEC_ROUNDS=16 CAKE_BENCH_SEQ=1024
    run_row CAKE_BENCH_SPEC=8 CAKE_BENCH_SPEC_ROUNDS=32 CAKE_BENCH_SEQ=2048
    run_row CAKE_BENCH_CHURN=1 CAKE_BENCH_MULTISTEP=32
    run_row CAKE_BENCH_BATCH=4   # plain-b4 baseline for the b4+spec8 row
    echo "queue3 done $(date -u +%FT%TZ)" >>"$LOG"
    exit 0
  fi
  echo "probe $i wedged $(date -u +%FT%TZ); sleeping 20m" >>"$LOG"
  sleep 1200
done
echo "gave up after 30 probes $(date -u +%FT%TZ)" >>"$LOG"
exit 1
