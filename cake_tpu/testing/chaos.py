"""Chaos proxy: deterministic fault injection for the wire path.

A seeded TCP proxy that sits between master and worker and injects
failures by schedule — the piece that makes the failure-domain hardening
(reconnect+replay, retry/backoff, op deadlines, replica failover)
*systematically testable* instead of "unplug a cable and watch". The
reference stack simply dies on any of these (SURVEY §5, client.rs:52-61);
here every one of them must be survivable, so every one of them needs a
reproducible trigger.

The proxy is frame-aware: it parses the wire framing (magic + type + len
+ payload + CRC, `native/cake_wire.cc`) as it relays, so faults land at
exact protocol states — "kill after the 7th master->worker frame" hits
the first BATCH of a CAP_PING handshake deterministically, every run.

Faults (one :class:`Fault` per proxied connection, in accept order):

=========== =============================================================
``kill``     forward frame N, then close both directions (worker restart)
``truncate`` forward half of frame N's payload, then close (cut mid-frame)
``corrupt``  flip one payload byte of frame N, keep the original CRC
             trailer (the receiver's CRC check must fire)
``stall``    hold frame N for ``param`` ms before forwarding (a peer
             stalled longer than ``--op-timeout`` must fault, shorter
             must NOT)
``blackhole`` swallow frame N and everything after it; the connection
             stays open (the classic hung-peer hole)
``refuse``   close ``param`` (default 1) connections at accept, before
             any bytes flow (worker not up yet; pairs with
             ``--connect-retries``)
=========== =============================================================

Frames are counted 1-based per direction; a fault with ``dir="reply"``
triggers on worker->master frames instead. ``schedule_from_seed`` maps a
seed to a schedule deterministically, so "the run that failed under
``--chaos seed=1337``" is reproducible from its seed alone, in CI or at a
dev box. Applied faults are recorded in :attr:`ChaosProxy.events` for
assertions and post-mortems.

Control-plane chaos (ISSUE 19): the same seeded-schedule discipline,
aimed at the gateway's *membership* plane instead of the data path.
:class:`ControlFault` / :func:`control_schedule_from_seed` /
:class:`ControlPlaneChaos` drive registration storms, heartbeat flaps,
stale deregisters, and gateway restarts against a live fleet — the
invariants (idempotent duplicate registration, demote-don't-delete
leases, membership re-forming from heartbeats within one interval) get
reproducible triggers exactly like the wire faults above.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import socket
import struct
import threading
import time

from cake_tpu.runtime import wire

log = logging.getLogger("cake_tpu.chaos")

FAULT_KINDS = ("kill", "truncate", "corrupt", "stall", "blackhole", "refuse",
               "none")  # `none`: explicit clean connection in a schedule
_HDR = wire._HEADER  # <IBI: magic, msg_type, payload_len


@dataclasses.dataclass(frozen=True)
class Fault:
    """One injected failure: ``kind`` at 1-based ``frame`` of one proxied
    connection. ``param`` is milliseconds for ``stall``, a connection
    count for ``refuse``. ``dir`` selects which frame stream is counted:
    ``"req"`` (master->worker, default) or ``"reply"``."""

    kind: str
    frame: int = 1
    param: float = 0.0
    dir: str = "req"

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown chaos fault {self.kind!r} (know {FAULT_KINDS})"
            )
        if self.dir not in ("req", "reply"):
            raise ValueError(f"chaos fault dir must be req|reply: {self.dir!r}")
        if self.frame < 1:
            # frames are 1-based; a 0/negative frame would silently never
            # fire while the operator believes resilience was exercised
            raise ValueError(f"chaos fault frame must be >= 1: {self.frame}")

    def __str__(self) -> str:
        s = f"{self.kind}@{'r' if self.dir == 'reply' else ''}{self.frame}"
        return f"{s}={self.param:g}" if self.param else s


def schedule_from_seed(seed: int, n: int = 1, max_frame: int = 10) -> list[Fault]:
    """Seed -> deterministic fault schedule (same seed, same faults,
    forever — the whole point). Random draws cover the recoverable kinds;
    ``refuse``/``blackhole`` are opt-in by explicit spec since they only
    make sense with specific knobs armed."""
    rng = random.Random(seed)
    out = []
    for _ in range(n):
        kind = rng.choice(("kill", "truncate", "corrupt", "stall"))
        frame = rng.randint(1, max_frame)
        param = float(rng.randint(200, 1200)) if kind == "stall" else 0.0
        out.append(Fault(kind, frame, param))
    return out


def parse_spec(spec: str) -> list[Fault]:
    """``--chaos`` spec -> schedule. Comma-separated directives, each
    ``kind[@[r]FRAME][=PARAM]`` (``r`` counts reply frames), applied to
    successive proxied connections — so ``kill@7,stall@2=500`` kills the
    first connection at its 7th request frame and stalls the SECOND
    (post-recovery) connection's 2nd frame for 500 ms. ``seed=N`` expands
    to :func:`schedule_from_seed`."""
    faults: list[Fault] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if part.startswith("seed="):
            faults.extend(schedule_from_seed(int(part[5:])))
            continue
        head, _, param = part.partition("=")
        kind, _, frame = head.partition("@")
        d = "req"
        if frame.startswith("r"):
            d, frame = "reply", frame[1:]
        faults.append(Fault(
            kind=kind.strip(),
            frame=int(frame) if frame else 1,
            param=float(param) if param else 0.0,
            dir=d,
        ))
    if not faults:
        raise ValueError(f"empty chaos spec {spec!r}")
    return faults


def _read_exact(sock: socket.socket, n: int) -> bytes:
    bufs, got = [], 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            raise ConnectionError("peer closed")
        bufs.append(chunk)
        got += len(chunk)
    return b"".join(bufs)


def _read_frame(sock: socket.socket) -> tuple[bytes, bytes, bytes]:
    """One wire frame off ``sock`` -> (header, payload, crc_trailer)."""
    header = _read_exact(sock, _HDR.size)
    magic, _t, plen = _HDR.unpack(header)
    if magic != wire.MAGIC or plen > wire.MAX_PAYLOAD:
        raise ConnectionError("stream desynced (not a wire frame)")
    payload = _read_exact(sock, plen) if plen else b""
    return header, payload, _read_exact(sock, 4)


class ChaosProxy:
    """Frame-aware TCP proxy in front of one worker address.

    Faults apply to successive accepted connections in schedule order
    (connections absorbed by a pending multi-connect ``refuse`` don't
    consume a slot; later connections run clean once the schedule is
    exhausted — a recovery reconnect is expected to succeed).
    Thread-per-pump, daemonized; test lifetimes only."""

    def __init__(self, upstream_host: str, upstream_port: int,
                 faults: list[Fault] | None = None,
                 listen_host: str = "127.0.0.1", port: int = 0):
        self.upstream = (upstream_host, int(upstream_port))
        self.faults = list(faults or [])
        self.events: list[tuple[int, str]] = []  # (conn_idx, str(fault))
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((listen_host, port))
        self._lsock.listen(16)
        self.host, self.port = listen_host, self._lsock.getsockname()[1]
        self.addr = f"{self.host}:{self.port}"
        self._stop = threading.Event()
        self._conn_idx = 0
        self._sched_idx = 0  # schedule cursor, advanced apart from
        # _conn_idx so connections absorbed by a pending multi-connect
        # refusal don't silently consume the faults scheduled after it
        self._refusals_left = 0
        self._thread = threading.Thread(target=self._serve, daemon=True)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ChaosProxy":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:  # wake the blocked accept
            socket.create_connection((self.host, self.port), timeout=1).close()
        except OSError:
            pass
        self._lsock.close()
        self._thread.join(timeout=5)

    def __enter__(self) -> "ChaosProxy":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- accept loop ---------------------------------------------------------
    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._lsock.accept()
            except OSError:
                return
            if self._stop.is_set():
                client.close()
                return
            idx = self._conn_idx
            self._conn_idx += 1
            fault = None
            if self._refusals_left > 0:
                self._refusals_left -= 1
                fault = Fault("refuse")
            elif self._sched_idx < len(self.faults):
                fault = self.faults[self._sched_idx]
                self._sched_idx += 1
                if fault.kind == "none":  # scheduled clean connection
                    fault = None
                elif fault.kind == "refuse":
                    # refuse covers THIS connect plus param-1 more
                    self._refusals_left = max(0, int(fault.param or 1) - 1)
            if fault is not None and fault.kind == "refuse":
                self._note(idx, fault)
                client.close()
                continue
            try:
                server = socket.create_connection(self.upstream, timeout=5)
            except OSError as e:
                log.warning("chaos: upstream %s unreachable: %s",
                            self.upstream, e)
                client.close()
                continue
            try:
                for s in (client, server):
                    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                # a fault injector must not itself leak fds on error paths
                client.close()
                server.close()
                continue
            pair = _Pair(client, server)
            threading.Thread(
                target=self._pump, daemon=True,
                args=(pair, idx, "req",
                      fault if fault and fault.dir == "req" else None),
            ).start()
            threading.Thread(
                target=self._pump, daemon=True,
                args=(pair, idx, "reply",
                      fault if fault and fault.dir == "reply" else None),
            ).start()

    def _note(self, idx: int, fault: Fault) -> None:
        self.events.append((idx, str(fault)))
        log.info("chaos: conn %d %s", idx, fault)

    # -- frame pump ----------------------------------------------------------
    def _pump(self, pair: "_Pair", idx: int, direction: str,
              fault: Fault | None) -> None:
        src, dst = pair.ends(direction)
        frame_no = 0
        try:
            while True:
                header, payload, crc = _read_frame(src)
                frame_no += 1
                if fault is not None and frame_no == fault.frame:
                    self._note(idx, fault)
                    if fault.kind == "kill":
                        dst.sendall(header + payload + crc)
                        pair.close()
                        return
                    if fault.kind == "truncate":
                        dst.sendall(header + payload[: len(payload) // 2])
                        pair.close()
                        return
                    if fault.kind == "corrupt":
                        # flip a payload byte, keep the original CRC: the
                        # receiver's integrity check must catch it
                        bad = bytearray(payload)
                        if bad:
                            bad[len(bad) // 2] ^= 0xFF
                            dst.sendall(header + bytes(bad) + crc)
                        else:  # empty payload: corrupt the trailer itself
                            dst.sendall(header + bytes(4))
                        fault = None
                        continue
                    if fault.kind == "stall":
                        time.sleep(fault.param / 1e3)
                        dst.sendall(header + payload + crc)
                        fault = None
                        continue
                    if fault.kind == "blackhole":
                        # swallow this and every later frame; keep the
                        # socket open so only a deadline can save the peer
                        while True:
                            _read_frame(src)
                dst.sendall(header + payload + crc)
        except (ConnectionError, OSError):
            pair.close()


# -- control-plane chaos (ISSUE 19) ------------------------------------------

CONTROL_FAULT_KINDS = ("storm", "flap", "stale_dereg", "dup_register",
                       "gw_restart", "none")


@dataclasses.dataclass(frozen=True)
class ControlFault:
    """One membership-plane fault. ``param`` is a count (``storm``:
    concurrent registrations, ``flap``: register/deregister cycles,
    ``dup_register``: sequential duplicates); unused otherwise."""

    kind: str
    param: int = 0

    def __post_init__(self):
        if self.kind not in CONTROL_FAULT_KINDS:
            raise ValueError(f"unknown control fault {self.kind!r} "
                             f"(know {CONTROL_FAULT_KINDS})")

    def __str__(self) -> str:
        return f"{self.kind}={self.param}" if self.param else self.kind


def control_schedule_from_seed(seed: int, n: int = 4) -> list[ControlFault]:
    """Seed -> deterministic control-plane fault schedule (same seed,
    same faults, forever). ``gw_restart`` is opt-in by explicit spec —
    it needs a restart hook armed — so the drawn kinds are the ones any
    live gateway can absorb."""
    rng = random.Random(seed)
    out = []
    for _ in range(n):
        kind = rng.choice(("storm", "flap", "stale_dereg", "dup_register"))
        if kind == "storm":
            param = rng.randint(20, 100)
        elif kind == "flap":
            param = rng.randint(2, 5)
        elif kind == "dup_register":
            param = rng.randint(3, 10)
        else:
            param = 0
        out.append(ControlFault(kind, param))
    return out


class ControlPlaneChaos:
    """Applies :class:`ControlFault` schedules against a live gateway's
    fleet endpoints (``/v1/fleet/register`` / ``/v1/fleet/deregister``).

    ``gateway`` is the base URL; ``addrs`` the replica addresses to
    attack with (they should be REAL, serving replicas — the invariants
    under test are about what happens to live traffic). ``restart_fn``
    arms ``gw_restart``: it must kill and restart the gateway, returning
    nothing (the test then asserts membership re-forms from heartbeats).
    Applied faults land in :attr:`events` for assertions."""

    def __init__(self, gateway: str, addrs: list[str], restart_fn=None):
        self.gateway = gateway.rstrip("/")
        self.addrs = list(addrs)
        self.restart_fn = restart_fn
        self.events: list[str] = []

    # -- wire helpers --------------------------------------------------------
    def _post(self, path: str, body: dict) -> dict | None:
        import json as _json
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            self.gateway + path, data=_json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=2) as resp:
                return _json.loads(resp.read() or b"{}")
        except (urllib.error.URLError, OSError, ValueError):
            return None

    def register(self, addr: str) -> dict | None:
        return self._post("/v1/fleet/register", {"addr": addr})

    def deregister(self, addr: str) -> dict | None:
        return self._post("/v1/fleet/deregister", {"addr": addr})

    # -- faults --------------------------------------------------------------
    def apply(self, fault: ControlFault, addr: str | None = None) -> None:
        """Apply one fault (round-robins over ``addrs`` when ``addr`` is
        not pinned)."""
        addr = addr or self.addrs[len(self.events) % len(self.addrs)]
        self.events.append(str(fault))
        log.info("chaos(control): %s against %s", fault, addr)
        if fault.kind == "storm":
            # N concurrent re-registrations of ONE backend: the lease
            # must update in place, never a phantom second entry
            n = max(2, fault.param or 50)
            threads = [threading.Thread(target=self.register, args=(addr,),
                                        daemon=True) for _ in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=10)
        elif fault.kind == "flap":
            # rapid register/deregister cycles, ending REGISTERED: the
            # hysteresis must absorb the thrash, and the final register
            # must clear the deregister pin so traffic routes again
            for _ in range(max(1, fault.param or 3)):
                self.deregister(addr)
                self.register(addr)
        elif fault.kind == "stale_dereg":
            # deregister-then-traffic race: a stale goodbye arrives
            # AFTER the replica already re-registered; the re-register
            # (fresh lease) must win over the later stale dereg only
            # until the next renewal — here we end with a renewal so
            # the member must be routable again within one heartbeat
            self.deregister(addr)
            self.register(addr)
        elif fault.kind == "dup_register":
            for _ in range(max(2, fault.param or 3)):
                self.register(addr)
        elif fault.kind == "gw_restart":
            if self.restart_fn is None:
                raise ValueError("gw_restart needs a restart_fn armed")
            self.restart_fn()
        # "none": explicit clean slot in a schedule

    def run(self, schedule: list[ControlFault]) -> None:
        for fault in schedule:
            self.apply(fault)


# -- spill/preemption chaos (ISSUE 20) ---------------------------------------

SPILL_FAULT_KINDS = ("spill_full", "victim_finish", "resume_storm", "none")


@dataclasses.dataclass(frozen=True)
class SpillFault:
    """One preempt/resume-path fault, armed at the ``at``-th consult of
    its kind (1-based): ``spill_full`` makes the store refuse the claim
    (the preemption must not land and the victim must keep decoding),
    ``victim_finish`` injects the victim-finished-between-pick-and-
    export race (the scheduler must bail with nothing touched), and
    ``resume_storm`` resumes every spilled victim at once (attaches
    queue FIFO-fair; pool pressure drives ``kvpool.admit_defers``)."""

    kind: str
    at: int = 1

    def __post_init__(self):
        if self.kind not in SPILL_FAULT_KINDS:
            raise ValueError(f"unknown spill fault {self.kind!r} "
                             f"(know {SPILL_FAULT_KINDS})")
        if self.at < 1:
            raise ValueError(f"fault 'at' must be >= 1, got {self.at}")

    def __str__(self) -> str:
        return f"{self.kind}@{self.at}"


def spill_schedule_from_seed(seed: int, n: int = 3) -> list[SpillFault]:
    """Seed -> deterministic spill-path fault schedule (same seed, same
    faults, forever)."""
    rng = random.Random(seed)
    return [SpillFault(rng.choice(("spill_full", "victim_finish",
                                   "resume_storm")),
                       at=rng.randint(1, 3)) for _ in range(n)]


class SpillChaos:
    """Scheduler-side fault injector: the scheduler consults
    ``fire(kind)`` at each spill-protocol point (engine thread only),
    and a consult that matches an armed fault's ``(kind, at)`` returns
    True exactly once. Fired faults land in :attr:`events` as
    ``(str(fault), consult_index)`` for assertions."""

    _THREAD_DOMAIN = "engine"

    def __init__(self, faults: list[SpillFault]):
        self.faults = list(faults)
        self.events: list[tuple[str, int]] = []
        self._counts: dict[str, int] = {}

    def fire(self, kind: str) -> bool:
        n = self._counts.get(kind, 0) + 1
        self._counts[kind] = n
        for i, f in enumerate(self.faults):
            if f.kind == kind and f.at == n:
                del self.faults[i]
                self.events.append((str(f), n))
                log.info("chaos(spill): firing %s", f)
                return True
        return False


class _Pair:
    """Two sockets closed as one unit (either pump dying drops both —
    TCP proxies must not leave half-open directions behind)."""

    def __init__(self, client: socket.socket, server: socket.socket):
        self.client, self.server = client, server
        self._lock = threading.Lock()
        self._closed = False

    def ends(self, direction: str) -> tuple[socket.socket, socket.socket]:
        return ((self.client, self.server) if direction == "req"
                else (self.server, self.client))

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        for s in (self.client, self.server):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
