"""Deterministic failure-injection utilities (chaos harness).

Test-support code that ships in the package (not under tests/) because
the CLI's ``--chaos`` dev flag and external integration suites drive the
same proxy the unit tests do.
"""

from cake_tpu.testing.chaos import (  # noqa: F401
    ChaosProxy,
    Fault,
    parse_spec,
    schedule_from_seed,
)
