"""Autoregressive generation loop (single-host, all-local path).

Equivalent of the reference's `Generator` trait + `LLama::next_token`
(`cake-core/src/model/mod.rs:21-29,46-58`, `model/llama.rs:223-272`):
``next_token(index) -> Token{id, text, is_end_of_stream}``, ``last()`` flushes
the detokenizer tail, ``generated_tokens()`` counts. The KV-cache context
windowing matches llama.rs:228-232 — the full prompt is fed once (prefill),
every later step feeds exactly one token.

TPU-first design:

- **Two compiled programs**: ``prefill`` (prompt at bucketed lengths) and
  ``decode_step``. The decode step fuses the *entire* per-token pipeline —
  embed -> all layers -> ln_f -> lm_head -> repeat penalty -> sampling — into
  one XLA program with the cache donated, so each token costs one dispatch
  and zero host round-trips except the sampled id (the reference downloads
  full logits to the CPU sampler every token, llama.rs:241-265).
- **Prompt bucketing**: prompts are right-padded to a power-of-two bucket so
  prefill compiles O(log max_seq) times, not per prompt length. Padded
  positions write garbage K/V beyond the prompt, which is invisible under the
  causal mask and overwritten by subsequent decode steps before it ever
  enters the frontier.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from functools import partial

import jax
import jax.numpy as jnp

from cake_tpu.models.config import LlamaConfig
from cake_tpu.models import llama
from cake_tpu.obs import flight as obs_flight
from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.obs.trace import span
from cake_tpu.ops import quant
from cake_tpu.ops.kvcache import KVCache, init_cache
from cake_tpu.ops.rope import rope_tables
from cake_tpu.ops import sampling
from cake_tpu.ops.norms import rms_norm
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.utils.token_stream import TokenOutputStream


@dataclasses.dataclass
class Token:
    """Mirror of the reference ``Token`` (model/mod.rs:46-52), plus the
    serving plane's optional per-token top-k logprob report: a list of
    ``(token_id, logprob)`` pairs over the raw model distribution, None
    when the engine was not built with ``logprobs``."""

    id: int
    text: str | None
    is_end_of_stream: bool
    logprobs: list[tuple[int, float]] | None = None


def encode_prompt(prompt, tokenizer, config, max_seq: int) -> list[int]:
    """THE prompt-intake rules, shared by every serving surface (the
    single-stream generators, the batch engine, and the HTTP plane's
    adapters): strings tokenize with a BOS prepend, id lists pass through
    as-is; reject empty prompts, prompts that fill the window, and
    out-of-range ids (which would clamp in the embed gather and silently
    corrupt just this stream)."""
    if isinstance(prompt, str):
        if tokenizer is None:
            raise ValueError("string prompt requires a tokenizer")
        enc = tokenizer.encode(prompt)
        ids = list(getattr(enc, "ids", enc))
        if config.bos_token_id is not None and (
            not ids or ids[0] != config.bos_token_id
        ):
            ids = [config.bos_token_id] + ids
    else:
        ids = list(prompt)
    if not ids:
        raise ValueError("empty prompt")
    if len(ids) >= max_seq:
        raise ValueError(f"prompt length {len(ids)} >= max_seq {max_seq}")
    bad = [t for t in ids if not (0 <= t < config.vocab_size)]
    if bad:
        raise ValueError(
            f"prompt token ids out of range [0, {config.vocab_size}): "
            f"{bad[:5]}"
        )
    return ids


def _bucket(n: int, max_seq: int, floor: int = 16) -> int:
    b = floor
    while b < n:
        b *= 2
    return min(b, max_seq)


def _lm_head(params, x_last: jax.Array, config: LlamaConfig) -> jax.Array:
    x_last = rms_norm(x_last, params["norm_f"], config.rms_norm_eps,
                   offset=config.rms_norm_offset)
    return quant.dense(x_last, params["lm_head"]).astype(jnp.float32)


def prefill_fn(params, tokens, cache: KVCache, last_index, config: LlamaConfig):
    """Prompt pass. ``tokens [B, T_pad]``; logits read at ``last_index``
    (the last *real* prompt position). Returns (logits [B, vocab], cache)."""
    cos, sin = rope_tables(config.head_dim, cache.max_seq, config.rope_theta,
                           scaling=config.rope_scaling)
    x = llama.embed_tokens(params, tokens, config)
    x, cache = llama.forward_layers(params["layers"], x, cache, cos, sin, 0, config)
    x_last = jnp.take_along_axis(
        x, last_index.reshape(-1, 1, 1).astype(jnp.int32), axis=1
    )[:, 0, :]
    return _lm_head(params, x_last, config), cache


def decode_step_fn(
    params,
    token,  # [B] int32 — previous sampled token
    cache: KVCache,
    pos,  # scalar int32
    key,
    history,  # [repeat_last_n] int32
    hist_slot,
    config: LlamaConfig,
    settings: SamplerSettings,
    mask_table=None,  # [M, ceil(V/8)] uint8 packed constraint masks
    mask_row=None,  # scalar int32 — current DFA-state row
):
    """One fused decode step: forward one token + sample the next. The
    optional trailing mask operands are the constrained-decoding path
    (constrain/): a gather from the device-resident packed bitmask table
    + one jnp.where inside the same compiled program. Calls without them
    trace the exact pre-constraint program — unconstrained streams stay
    bit-identical."""
    cos, sin = rope_tables(config.head_dim, cache.max_seq, config.rope_theta,
                           scaling=config.rope_scaling)
    x = llama.embed_tokens(params, token[:, None], config)
    x, cache = llama.forward_layers(params["layers"], x, cache, cos, sin, pos, config)
    logits = _lm_head(params, x[:, -1, :], config)
    mask = None
    if mask_table is not None:
        mask = sampling.unpack_mask_bits(mask_table[mask_row],
                                         config.vocab_size)
    next_tok = sampling.sample_token(logits[0], key, history, settings,
                                     mask=mask)
    history, hist_slot = sampling.push_history(history, hist_slot, next_tok)
    return next_tok, cache, history, hist_slot


def decode_scan_fn(
    params,
    token,  # [1] int32 — previous sampled token
    cache: KVCache,
    pos,  # scalar int32 — position of `token`'s KV slot
    key0,  # BASE stream key (unfolded); see key schedule note below
    history,
    hist_slot,
    config: LlamaConfig,
    settings: SamplerSettings,
    steps: int,
    index0=0,  # absolute token index of the first emitted token
):
    """``steps`` fused decode steps in ONE dispatch (lax.scan over
    decode_step_fn). Sampling is already on-device, so the token feedback
    loop needs no host round-trip; emitting K tokens per dispatch amortizes
    dispatch/tunnel latency that otherwise dominates single-token decode.

    Key schedule: step ``i`` samples with ``fold_in(key0, index0 + i)`` —
    the SAME schedule as the single-step path (``fold_in(base_key, index)``),
    so a given seed produces an identical stochastic stream at every block
    size. Returns (tokens [steps], cache, history, hist_slot)."""

    def body(carry, i):
        token, cache, pos, history, hist_slot = carry
        tok, cache, history, hist_slot = decode_step_fn(
            params, token, cache, pos,
            jax.random.fold_in(key0, jnp.asarray(index0, jnp.int32) + i),
            history, hist_slot, config=config, settings=settings,
        )
        return (tok.reshape(1), cache, pos + 1, history, hist_slot), tok

    (_, cache, _, history, hist_slot), toks = jax.lax.scan(
        body,
        (token, cache, jnp.asarray(pos, jnp.int32), history, hist_slot),
        jnp.arange(steps, dtype=jnp.int32),
    )
    return toks, cache, history, hist_slot


class GeneratorBase:
    """Shared Generator-trait state machine (model/mod.rs:21-29,46-58):
    prompt validation + per-stream reset, repeat-penalty history seeding,
    token bookkeeping, EOS detection, streaming detok, counters. Subclasses
    implement the model execution (`next_token`)."""

    def __init__(
        self,
        config: LlamaConfig,
        tokenizer=None,
        settings: SamplerSettings | None = None,
        max_seq: int | None = None,
    ):
        self.config = config
        self.settings = settings or SamplerSettings()
        self.max_seq = max_seq or config.max_seq_len
        self.tokenizer = tokenizer
        self.stream = TokenOutputStream(tokenizer) if tokenizer is not None else None
        self._key = jax.random.PRNGKey(self.settings.seed)
        self._history, self._hist_slot = sampling.init_history(
            self.settings.repeat_last_n
        )
        self._prompt_tokens: list[int] = []
        self._generated: list[int] = []
        self._pos = 0
        self._last_token: int | None = None
        self._eos_ids = set(config.eos_ids())
        sampling.validate_logit_bias(self.settings, config.vocab_size)
        # Constrained decoding (cake_tpu/constrain): a Guide set via
        # set_guide() masks every sampling step. Subclasses that can
        # apply the mask flip supports_guide; the base refuses, so a
        # serve adapter can never silently ignore a constraint.
        self.guide = None
        self.guide_dead = False  # DFA dead end hit (end_reason constraint)
        # fused block-decode buffer (subclasses with block_size > 1);
        # deque: the per-token pop is O(1), not the O(n) list.pop(0)
        self.block_size = 1
        self._block_buf: deque[int] = deque()

    # -- prompt handling ----------------------------------------------------
    def set_prompt(self, prompt: str | list[int]) -> None:
        ids = encode_prompt(prompt, self.tokenizer, self.config,
                            self.max_seq)
        self._prompt_tokens = ids
        # Reset all per-stream state so a generator can serve a new prompt
        # (the stale KV beyond the new prompt is invisible under the causal
        # mask and overwritten as decode advances, so the cache itself does
        # not need zeroing).
        self._generated.clear()
        self._pos = 0
        self._last_token = None
        if self.stream is not None:
            self.stream.clear()
        # Seed the repeat-penalty window with the prompt tail (llama.rs:250-259
        # penalizes over all generated context; we include the prompt tail) —
        # one vectorized write, not a per-token device loop.
        self._history, self._hist_slot = sampling.init_history(
            self.settings.repeat_last_n
        )
        tail = ids[-self.settings.repeat_last_n :]
        if tail:
            idx = jnp.arange(len(tail), dtype=jnp.int32)
            self._history = self._history.at[idx].set(
                jnp.asarray(tail, jnp.int32)
            )
            self._hist_slot = jnp.int32(len(tail))
        self._block_buf = deque()
        self.guide = None  # constraints are per-request: re-set_guide
        self.guide_dead = False
        self._on_new_prompt()

    def _on_new_prompt(self) -> None:
        """Hook for subclasses (e.g. reset remote runner caches)."""

    # -- constrained decoding -----------------------------------------------
    supports_guide = False

    @property
    def eos_ids(self) -> frozenset:
        """Public EOS-id surface (the serve facade contract)."""
        return frozenset(self._eos_ids)

    def set_guide(self, guide) -> None:
        """Attach (or clear, with None) a constrain.Guide for the CURRENT
        prompt — call after set_prompt, before next_token(0). Every
        sampled token is then masked to the grammar's allowed set and
        advances the host-side DFA cursor."""
        if guide is not None and not self.supports_guide:
            raise ValueError(
                f"{type(self).__name__} does not support constrained "
                "decoding (no masked sampling path)")
        if guide is not None:
            guide.reset()
        self.guide = guide
        self.guide_dead = False
        self._on_guide()

    def _on_guide(self) -> None:
        """Hook: upload/refresh device-side mask state for self.guide."""

    # -- shared bookkeeping --------------------------------------------------
    def _require_prompt(self) -> None:
        if not self._prompt_tokens:
            raise RuntimeError("set_prompt first")

    def _check_capacity(self) -> None:
        if self._pos >= self.max_seq:
            raise RuntimeError(
                f"KV cache exhausted: position {self._pos} >= max_seq "
                f"{self.max_seq} (raise max_seq or shorten the stream)"
            )

    def _finish_token(self, tok_id: int) -> Token:
        self._last_token = tok_id
        self._generated.append(tok_id)
        is_eos = tok_id in self._eos_ids
        if self.guide is not None and not is_eos:
            # host-side DFA advance between compiled steps; a dead end
            # (no emittable token at the new state) ends the stream
            if not self.guide.advance(tok_id) or self.guide.dead_end:
                from cake_tpu.constrain.guide import DEAD_ENDS

                self.guide_dead = True
                DEAD_ENDS.inc()
        # the EOS id is an end marker, not text (toy tokenizers map it to
        # an arbitrary printable char)
        text = (self.stream.next_token(tok_id)
                if self.stream is not None and not is_eos else None)
        return Token(id=tok_id, text=text,
                     is_end_of_stream=is_eos or self.guide_dead)

    def _decode_next(self, index: int, run_block, run_single) -> Token:
        """Shared block-decode control flow: pop the buffer, else collect
        an in-flight lookahead block, else dispatch a fused
        ``block_size``-step block (``run_block(index) -> list[int]``,
        which must advance ``_pos``/history), else a single step
        (``run_single(index) -> int``) for block_size == 1 or the tail of
        the KV window. The in-flight check runs BEFORE the capacity check:
        a lookahead block dispatched up to the window edge has already
        advanced ``_pos`` to ``max_seq``, and its tokens must still be
        delivered."""
        if self._block_buf:
            return self._finish_token(self._block_buf.popleft())
        toks = self._take_inflight(index)
        if toks is not None:
            self._block_buf.extend(toks)
            return self._finish_token(self._block_buf.popleft())
        self._check_capacity()
        if (self.block_size > 1 and self.guide is None
                and self._pos + self.block_size <= self.max_seq):
            # a live guide forces single-step dispatch: the in-block
            # feedback tokens would sample against a stale mask row
            self._block_buf.extend(run_block(index))
            return self._finish_token(self._block_buf.popleft())
        return self._finish_token(run_single(index))

    def _take_inflight(self, index: int) -> list[int] | None:
        """Hook: tokens already computed (or computing) on device from a
        lookahead dispatch. Default: none."""
        return None

    # -- Generator trait surface --------------------------------------------
    def next_token(self, index: int) -> Token:  # pragma: no cover - abstract
        raise NotImplementedError

    def last(self) -> str | None:
        """Flush residual detokenizer text (model/mod.rs `last`,
        llama.rs via token_output_stream.rs:55-69)."""
        return self.stream.decode_rest() if self.stream else None

    def generated_tokens(self) -> int:
        return len(self._generated)

    @property
    def generated_ids(self) -> list[int]:
        return list(self._generated)

    def close(self) -> None:
        pass


class LlamaGenerator(GeneratorBase):
    """Single-stream generator over an all-local model. (The distributed,
    topology-sharded equivalent — runtime.master.DistributedGenerator —
    shares this base and swaps the execution path for a runner walk.)

    Supports constrained decoding (``set_guide``): the guide's packed DFA
    mask table uploads once per prompt (rows padded to a pow2 capacity so
    the masked trace is stable across grammars), the decode step gathers
    the current state's row on device, and the DFA cursor advances
    host-side in ``_finish_token``. While a guide is live, fused
    block/lookahead dispatch is bypassed — tokens 2..K of a block would
    sample against a stale mask row."""

    supports_guide = True

    def __init__(
        self,
        config: LlamaConfig,
        params,
        tokenizer=None,
        settings: SamplerSettings | None = None,
        max_seq: int | None = None,
        cache_dtype=None,
        block_size: int = 1,
        kv_quant: str | None = None,
        lookahead: bool = False,
    ):
        """``block_size > 1`` fuses that many decode steps into one dispatch
        (lax.scan; sampling stays on-device) and streams the buffered tokens
        one at a time — dispatch latency amortizes ~K-fold, which dominates
        single-token decode on remote-attached chips. The sampling key
        schedule is block-size-invariant (absolute token index), so a given
        seed yields the same stream at any block size.

        ``lookahead`` (needs block_size > 1) dispatches block N+1 from the
        DEVICE-side feedback token before block N's rows are fetched to the
        host, hiding the device->host readback + detok + emission behind
        device compute (JAX async dispatch). Token streams are bit-identical
        to the non-lookahead path: the feedback token is exactly the one the
        host would have fed back, and the key schedule is absolute-index
        based.

        ``kv_quant="int8"`` stores the KV cache as int8 + per-slot scales
        (half the cache HBM; quantize-on-write, kvcache.QuantizedKV)."""
        super().__init__(config, tokenizer, settings, max_seq)
        self.params = params
        self.block_size = max(1, block_size)
        self._lookahead = bool(lookahead) and self.block_size > 1
        self._inflight = None  # un-fetched [steps] device tokens
        self._guide_table = None  # device mask table (set_guide uploads)
        # per-token dispatch latency (block dispatches record ms/token so
        # the series is comparable across block sizes) and prompt-pass ms
        self._decode_hist = obs_metrics.Histogram("generator.decode_ms")
        self._prefill_hist = obs_metrics.Histogram("generator.prefill_ms")
        obs_metrics.registry().publish(self._decode_hist, self._prefill_hist)
        self.cache = init_cache(config, batch=1, max_seq=self.max_seq,
                                dtype=cache_dtype, quant=kv_quant)
        self._prefill = jax.jit(
            partial(prefill_fn, config=config),
            donate_argnames=("cache",),
        )
        # single-step program: block_size 1, and the tail of the KV window
        self._decode_single = jax.jit(
            partial(decode_step_fn, config=config, settings=self.settings),
            donate_argnames=("cache",),
        )
        self._decode = (
            jax.jit(
                partial(decode_scan_fn, config=config, settings=self.settings,
                        steps=self.block_size),
                donate_argnames=("cache",),
            )
            if self.block_size > 1 else self._decode_single
        )

    def _on_new_prompt(self) -> None:
        # an in-flight lookahead block belongs to the previous stream; its
        # stale KV writes sit beyond the new prompt's causal frontier (the
        # same invariant set_prompt documents for the cache itself)
        self._inflight = None

    def _on_guide(self) -> None:
        """Upload the guide's packed mask table (pow2-padded rows: one
        masked-program trace per capacity, not per grammar)."""
        if self.guide is None:
            self._guide_table = None
            return
        bits = self.guide.dfa.mask_bits
        cap = 64
        while cap < bits.shape[0]:
            cap *= 2
        table = jnp.zeros((cap, bits.shape[1]), jnp.uint8)
        self._guide_table = table.at[: bits.shape[0]].set(
            jnp.asarray(bits))

    def _dispatch_block(self, token_dev, index0: int):
        """Async-dispatch one fused ``block_size``-step block and advance
        the host-side position; the ``[steps]`` device token rows return
        UN-fetched so the caller chooses when to pay the host sync."""
        toks, self.cache, self._history, self._hist_slot = self._decode(
            self.params,
            token_dev,
            self.cache,
            jnp.int32(self._pos),
            self._key,  # base key; scan folds with the absolute index
            self._history,
            self._hist_slot,
            index0=jnp.int32(index0),
        )
        self._pos += self.block_size
        return toks

    def _run_block(self, index: int) -> list[int]:
        t0 = time.perf_counter()
        with span("decode.block", index=index, steps=self.block_size):
            if self._inflight is not None:
                toks = self._inflight  # block already computing on device
                self._inflight = None
            else:
                toks = self._dispatch_block(
                    jnp.asarray([self._last_token], jnp.int32), index
                )
            if self._lookahead and self._pos + self.block_size <= self.max_seq:
                # enqueue block N+1 from the DEVICE feedback token (exactly
                # the token the host would feed back) BEFORE block N's host
                # fetch — the device computes ahead while the host detoks
                # and emits; measured wall below is therefore mostly the
                # residual fetch wait, not the block's math
                self._inflight = self._dispatch_block(
                    toks[-1].reshape(1).astype(jnp.int32),
                    index + self.block_size,
                )
            out = [int(t) for t in toks]
        dt_ms = (time.perf_counter() - t0) * 1e3
        self._decode_hist.observe(dt_ms / self.block_size)
        rec = obs_flight.recorder()
        if rec.enabled:
            rec.record(
                index=index, kind="decode", total_ms=round(dt_ms, 3),
                steps=self.block_size, lookahead=self._lookahead,
            )
        return out

    def _take_inflight(self, index: int) -> list[int] | None:
        if self._inflight is None:
            return None
        return self._run_block(index)

    def _run_single(self, index: int) -> int:
        t0 = time.perf_counter()
        # constrained streams ride the same jitted step with the two mask
        # operands added (a separate trace; the unconstrained trace is
        # untouched). mask_row is the only per-token upload — the table
        # went up once at set_guide.
        kwargs = (
            dict(mask_table=self._guide_table,
                 mask_row=jnp.int32(self.guide.state))
            if self.guide is not None else {}
        )
        with span("decode.step", index=index):
            tok, self.cache, self._history, self._hist_slot = (
                self._decode_single(
                    self.params,
                    jnp.asarray([self._last_token], jnp.int32),
                    self.cache,
                    jnp.int32(self._pos),
                    jax.random.fold_in(self._key, index),
                    self._history,
                    self._hist_slot,
                    **kwargs,
                )
            )
            self._pos += 1
            out = int(tok)
        dt_ms = (time.perf_counter() - t0) * 1e3
        self._decode_hist.observe(dt_ms)
        rec = obs_flight.recorder()
        if rec.enabled:
            rec.record(
                index=index, kind="decode", total_ms=round(dt_ms, 3), steps=1,
            )
        return out

    def next_token(self, index: int) -> Token:
        """index 0: prefill the whole prompt; index>0: one-token decode
        (context windowing per llama.rs:228-232), or pop from the current
        fused block when block_size > 1."""
        if index == 0:
            self._require_prompt()
            n = len(self._prompt_tokens)
            t0 = time.perf_counter()
            with span("prefill", tokens=n):
                t_pad = _bucket(n, self.max_seq)
                padded = self._prompt_tokens + [0] * (t_pad - n)
                tokens = jnp.asarray([padded], jnp.int32)
                logits, self.cache = self._prefill(
                    self.params, tokens, self.cache,
                    jnp.asarray([n - 1], jnp.int32)
                )
                step_key = jax.random.fold_in(self._key, 0)
                tok = sampling.sample_token(
                    logits[0], step_key, self._history, self.settings,
                    mask=(jnp.asarray(self.guide.mask_bool())
                          if self.guide is not None else None),
                )
                self._history, self._hist_slot = sampling.push_history(
                    self._history, self._hist_slot, tok
                )
                self._pos = n
                tok_id = int(tok)
            dt_ms = (time.perf_counter() - t0) * 1e3
            self._prefill_hist.observe(dt_ms)
            rec = obs_flight.recorder()
            if rec.enabled:
                rec.record(
                    index=0, kind="prefill", total_ms=round(dt_ms, 3),
                    tokens=n,
                )
            return self._finish_token(tok_id)
        return self._decode_next(index, self._run_block, self._run_single)
