"""Master: distributed generation across topology-assigned runners.

Equivalent of the reference master + distributed LLama model
(`cake-core/src/cake/master.rs` + `model/llama.rs:61-219`): the master holds
the embedding, final norm, lm_head, tokenizer and sampler (llama.rs:61-76),
walks the decoder blocks in order with contiguous same-owner runs coalesced
into one call (llama.rs:88-119), and streams tokens with a tokens/sec report
that excludes the warm-up token (master.rs:36-65).

The walk is planned *statically* from the topology into segments
(topology.segments) — local segments run as one jitted scan on this host's
device, remote segments as one wire round-trip to their worker
(parallel/runner.py). This is the cross-host runtime; the on-pod equivalent
(whole pipeline in one compiled program over a mesh) is parallel/pipeline.py.
"""

from __future__ import annotations

import logging
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models import llama
from cake_tpu.models.config import LlamaConfig
from cake_tpu.ops import sampling
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.runner import BlockRunner, LocalRunner, RemoteRunner
from cake_tpu.parallel.topology import Topology
from cake_tpu.runtime import wire
from cake_tpu.runtime.generator import GeneratorBase, Token, _bucket, _lm_head

log = logging.getLogger("cake_tpu.master")


def build_runners(
    config: LlamaConfig,
    topology: Topology,
    local_params_loader,  # callable (start, stop) -> stacked layers pytree
    max_seq: int | None = None,
) -> list[BlockRunner]:
    """Plan the block walk: one runner per contiguous same-owner segment.
    Unassigned layers run locally on the master (llama.rs:177-193: topology
    decides Client vs local Transformer per layer)."""
    runners: list[BlockRunner] = []
    for seg in topology.segments(config.num_hidden_layers):
        if seg.owner is None:
            runners.append(
                LocalRunner(
                    config, local_params_loader(seg.start, seg.stop),
                    seg.start, seg.stop, max_seq=max_seq or config.max_seq_len,
                )
            )
        else:
            node = topology[seg.owner]
            runner = RemoteRunner(
                node.host, seg.start, seg.stop,
                max_seq=max_seq or config.max_seq_len,
            )
            log.info("connected: %s", runner.info)
            runners.append(runner)
    return runners


class DistributedGenerator(GeneratorBase):
    """Generator-trait surface over a runner plan (shares GeneratorBase with
    the all-local runtime.generator.LlamaGenerator; only the execution path
    differs: embed + runner walk + head here, one fused program there)."""

    def __init__(
        self,
        config: LlamaConfig,
        head_params: dict,  # embed, norm_f, lm_head
        runners: list[BlockRunner],
        tokenizer=None,
        settings: SamplerSettings | None = None,
        max_seq: int | None = None,
    ):
        super().__init__(config, tokenizer, settings, max_seq)
        self.runners = runners
        self.embed = head_params["embed"]
        self.norm_f = head_params["norm_f"]
        self.lm_head = head_params["lm_head"]
        # Same head math as the all-local path (generator._lm_head) — one
        # implementation, no drift between the fused and distributed runtimes.
        self._head_fn = jax.jit(
            partial(
                _lm_head,
                {"norm_f": self.norm_f, "lm_head": self.lm_head},
                config=config,
            )
        )
        self._sample_fn = jax.jit(
            partial(sampling.sample_token, settings=self.settings)
        )
        self._t_start: float | None = None
        # per-runner cumulative forward time (the TPU-side analogue of the
        # reference's per-worker ops/s + handshake-latency stats, worker.rs:19);
        # the first call per runner (prefill + XLA compile) is kept apart so
        # avg_ms reflects steady-state decode, like tokens_per_sec
        self._runner_time = [0.0] * len(runners)
        self._runner_calls = [0] * len(runners)
        self._runner_warmup = [0.0] * len(runners)
        self.recoveries = 0  # successful mid-stream reconnect+replay count
        self._consec_recoveries = 0  # capped so a dead link can't loop forever
        self._timing_paused = False  # replay forwards are not decode samples

    MAX_CONSEC_RECOVERIES = 3

    def _on_new_prompt(self) -> None:
        self._t_start = None
        # each prompt's first forward is a fresh prefill — re-classify it as
        # warm-up so avg_ms stays steady-state decode only
        self._runner_warmup = [0.0] * len(self.runners)
        for r in self.runners:
            r.reset()

    # -- forward across runners --------------------------------------------
    def _forward(self, tokens: list[int], pos: int, last_index: int) -> jax.Array:
        # through the shared embedding entry point so family deltas (Gemma's
        # sqrt(hidden) embed scaling) hold on the distributed path too
        x = np.asarray(
            llama.embed_tokens({"embed": self.embed},
                               jnp.asarray([tokens], jnp.int32), self.config)
        )
        for i, runner in enumerate(self.runners):
            t0 = time.perf_counter()
            x = runner.forward(x, pos)
            dt = time.perf_counter() - t0
            if self._timing_paused:
                pass  # recovery replay: prefill-sized, not steady-state
            elif self._runner_warmup[i] == 0.0:
                self._runner_warmup[i] = dt
            else:
                self._runner_time[i] += dt
                self._runner_calls[i] += 1
        x_last = jnp.asarray(x[:, last_index, :])
        return self._head_fn(x_last)[0]

    def _replay_context(self) -> jax.Array:
        """Failure recovery the reference lacks (SURVEY §5: a dropped worker
        connection just ends the generation, client.rs:52-61): reconnect
        every segment — a fresh connection means a fresh worker-side KV
        cache (worker.rs:52-61) — and rebuild all segment caches by
        replaying prompt + generated-so-far in one pass. Returns logits at
        the last context position, ready to sample the next token."""
        for r in self.runners:
            r.reset()
        ctx = self._prompt_tokens + self._generated
        n = len(ctx)
        if n > self.max_seq:
            raise RuntimeError("cannot recover: context exceeds max_seq")
        t_pad = _bucket(n, self.max_seq)
        self._timing_paused = True
        try:
            logits = self._forward(ctx + [0] * (t_pad - n), 0, n - 1)
        finally:
            self._timing_paused = False
        self._pos = n
        self.recoveries += 1
        return logits

    # -- Generator trait ----------------------------------------------------
    def next_token(self, index: int) -> Token:
        if index == 0:
            self._require_prompt()
            n = len(self._prompt_tokens)
            t_pad = _bucket(n, self.max_seq)
            logits = self._forward(
                self._prompt_tokens + [0] * (t_pad - n), 0, n - 1
            )
            self._pos = n
        else:
            self._check_capacity()
            try:
                logits = self._forward([self._last_token], self._pos, 0)
                self._pos += 1
                self._consec_recoveries = 0
            # Transport failures only: a worker-reported op error
            # (protocol.WorkerOpError) is deterministic — replaying the
            # context would just re-run the same failing op at prefill cost.
            except (OSError, wire.WireError) as e:
                self._consec_recoveries += 1
                if self._consec_recoveries > self.MAX_CONSEC_RECOVERIES:
                    raise RuntimeError(
                        f"giving up after {self.MAX_CONSEC_RECOVERIES} "
                        f"consecutive recovery attempts"
                    ) from e
                log.warning("segment forward failed (%s); reconnecting and "
                            "replaying %d-token context", e,
                            len(self._prompt_tokens) + len(self._generated))
                logits = self._replay_context()

        step_key = jax.random.fold_in(self._key, index)
        tok = self._sample_fn(logits, step_key, self._history)
        self._history, self._hist_slot = sampling.push_history(
            self._history, self._hist_slot, tok
        )
        if index == 0:
            # tokens/sec excludes the warm-up token (master.rs:37-40)
            self._t_start = time.perf_counter()
        return self._finish_token(int(tok))

    def tokens_per_sec(self) -> float | None:
        """Decode throughput excluding the warm-up token (master.rs:57-65)."""
        if self._t_start is None or len(self._generated) < 2:
            return None
        return (len(self._generated) - 1) / (time.perf_counter() - self._t_start)

    def runner_stats(self) -> list[dict]:
        """Per-segment steady-state decode latency (warm-up call reported
        separately). Remote entries include the handshake RTT recorded at
        connect time (client.rs:72-86 shows the same in the reference's
        WorkerInfo)."""
        stats = []
        for i, r in enumerate(self.runners):
            calls = self._runner_calls[i]
            entry = {
                "ident": r.ident(),
                "layers": f"{r.start}-{r.stop - 1}",
                "calls": calls,
                "avg_ms": (self._runner_time[i] / calls * 1e3) if calls else 0.0,
                "warmup_ms": self._runner_warmup[i] * 1e3,
            }
            info = getattr(r, "info", None)
            if info is not None and getattr(info, "latency_ms", None):
                entry["handshake_ms"] = round(info.latency_ms, 2)
            stats.append(entry)
        return stats

    def close(self) -> None:
        for r in self.runners:
            r.close()
