"""Master: distributed generation across topology-assigned runners.

Equivalent of the reference master + distributed LLama model
(`cake-core/src/cake/master.rs` + `model/llama.rs:61-219`): the master holds
the embedding, final norm, lm_head, tokenizer and sampler (llama.rs:61-76),
walks the decoder blocks in order with contiguous same-owner runs coalesced
into one call (llama.rs:88-119), and streams tokens with a tokens/sec report
that excludes the warm-up token (master.rs:36-65).

The walk is planned *statically* from the topology into segments
(topology.segments) — local segments run as one jitted scan on this host's
device, remote segments as one wire round-trip to their worker
(parallel/runner.py). This is the cross-host runtime; the on-pod equivalent
(whole pipeline in one compiled program over a mesh) is parallel/pipeline.py.
"""

from __future__ import annotations

import logging
import time
from functools import partial

import jax
import jax.numpy as jnp

from cake_tpu.models import llama
from cake_tpu.models.config import LlamaConfig
from cake_tpu.obs import flight as obs_flight
from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.obs.trace import span
from cake_tpu.ops import sampling
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.runner import BlockRunner, LocalRunner, RemoteRunner
from cake_tpu.parallel.topology import Topology
from cake_tpu.runtime import wire
from cake_tpu.runtime.generator import GeneratorBase, Token, _bucket, _lm_head

log = logging.getLogger("cake_tpu.master")


def build_runners(
    config: LlamaConfig,
    topology: Topology,
    local_params_loader,  # callable (start, stop) -> stacked layers pytree
    max_seq: int | None = None,
    wire_codec: str = "none",
    op_timeout_s: float | None = None,
    connect_retries: int = 0,
    recover_deadline_s: float | None = None,
) -> list[BlockRunner]:
    """Plan the block walk: one runner per contiguous same-owner segment.
    Unassigned layers run locally on the master (llama.rs:177-193: topology
    decides Client vs local Transformer per layer). ``wire_codec`` selects
    the activation encoding for every remote hop (negotiated against each
    worker's advertised set at handshake). The failure-domain knobs pass
    straight through to every RemoteRunner: ``op_timeout_s``
    (``--op-timeout``) bounds each wire round trip, ``connect_retries``
    (``--connect-retries``) retries the initial handshake with backoff so
    a master can start before its workers, ``recover_deadline_s``
    (``--recover-deadline``) budgets each replica's mid-stream reconnect.
    A topology node whose ``host`` is a LIST hands the whole replica set
    to its runner (failover order)."""
    runners: list[BlockRunner] = []
    for seg in topology.segments(config.num_hidden_layers):
        if seg.owner is None:
            runners.append(
                LocalRunner(
                    config, local_params_loader(seg.start, seg.stop),
                    seg.start, seg.stop, max_seq=max_seq or config.max_seq_len,
                )
            )
        else:
            node = topology[seg.owner]
            runner = RemoteRunner(
                node.hosts or node.host, seg.start, seg.stop,
                max_seq=max_seq or config.max_seq_len,
                wire_codec=wire_codec,
                op_timeout_s=op_timeout_s,
                connect_retries=connect_retries,
                recover_deadline_s=recover_deadline_s,
            )
            log.info("connected: %s", runner.info)
            runners.append(runner)
    return runners


class DistributedGenerator(GeneratorBase):
    """Generator-trait surface over a runner plan (shares GeneratorBase with
    the all-local runtime.generator.LlamaGenerator; only the execution path
    differs: embed + runner walk + head here, one fused program there)."""

    def __init__(
        self,
        config: LlamaConfig,
        head_params: dict,  # embed, norm_f, lm_head
        runners: list[BlockRunner],
        tokenizer=None,
        settings: SamplerSettings | None = None,
        max_seq: int | None = None,
    ):
        super().__init__(config, tokenizer, settings, max_seq)
        self.runners = runners
        # identities resolved once: span kwargs on the per-token walk must
        # not re-derive them (disabled-tracer cost stays near-zero)
        self._seg_idents = [r.ident() for r in runners]
        self.embed = head_params["embed"]
        self.norm_f = head_params["norm_f"]
        self.lm_head = head_params["lm_head"]
        # Same head math as the all-local path (generator._lm_head) — one
        # implementation, no drift between the fused and distributed runtimes.
        self._head_fn = jax.jit(
            partial(
                _lm_head,
                {"norm_f": self.norm_f, "lm_head": self.lm_head},
                config=config,
            )
        )
        self._sample_fn = jax.jit(
            partial(sampling.sample_token, settings=self.settings)
        )
        self._t_start: float | None = None
        # Per-segment forward-time histograms (the TPU-side analogue of the
        # reference's per-worker ops/s + handshake-latency stats, worker.rs:19);
        # the first call per runner per prompt (prefill + XLA compile) is kept
        # apart in a warmup gauge so the histogram holds steady-state decode
        # only, like tokens_per_sec. The instruments are per-instance (each
        # generator's runner_stats reads its own) and published into the
        # global registry under stable names, latest instance winning, so
        # --metrics-out and the Prometheus dump see the live generator.
        reg = obs_metrics.registry()
        self._seg_hist = [
            obs_metrics.Histogram(f"master.segment{i}.decode_ms")
            for i in range(len(runners))
        ]
        self._seg_warm = [
            obs_metrics.Gauge(f"master.segment{i}.warmup_ms")
            for i in range(len(runners))
        ]
        reg.publish(*self._seg_hist, *self._seg_warm)
        self._tokens_ctr = obs_metrics.counter("master.tokens_generated")
        self._recoveries_ctr = obs_metrics.counter("master.recoveries")
        self._failovers_ctr = obs_metrics.counter("master.failovers")
        self._last_seg_ms: list[float] = []  # per-segment ms of the last walk
        self._last_sample_ms = 0.0
        self.recoveries = 0  # successful mid-stream reconnect+replay count
        self.failovers = 0  # recoveries that landed on a different replica
        self._scraper = None  # lazy ClusterScraper (cluster_scraper())
        self._consec_recoveries = 0  # capped so a dead link can't loop forever
        self._timing_paused = False  # replay forwards are not decode samples

    MAX_CONSEC_RECOVERIES = 3

    def _on_new_prompt(self) -> None:
        self._t_start = None
        # the consecutive-recovery cap guards ONE stream's recovery loop;
        # carrying the count across prompts would let a long session
        # accumulate unrelated recoveries until a healthy stream trips
        # MAX_CONSEC_RECOVERIES spuriously
        self._consec_recoveries = 0
        # each prompt's first forward is a fresh prefill — re-classify it as
        # warm-up so avg_ms stays steady-state decode only
        for g in self._seg_warm:
            g.set(0.0)
        # recover(), not bare reset(): the per-prompt reconnect is the same
        # failure domain as a mid-stream one (a worker restarting between
        # prompts, a dead primary with a live replica) and must get the
        # same backoff budget + failover instead of dying on the first
        # refused connect
        self._recover_runners()

    def _recover_runners(self) -> None:
        """Bring every runner back (reconnect with backoff, possibly
        failing over to the next replica), keeping the failover counter
        and the per-segment identities in sync — span tags and
        runner_stats must show the live replica from the first
        post-recovery token."""
        for i, r in enumerate(self.runners):
            if r.recover():
                self.failovers += 1
                self._failovers_ctr.inc()
                self._seg_idents[i] = r.ident()

    # -- forward across runners --------------------------------------------
    def _forward(self, tokens: list[int], pos: int, last_index: int) -> jax.Array:
        # through the shared embedding entry point so family deltas (Gemma's
        # sqrt(hidden) embed scaling) hold on the distributed path too.
        # Device-resident walk: ``x`` stays a jax.Array across consecutive
        # LocalRunner segments (async dispatch, no host sync) and is only
        # materialized as numpy at remote boundaries — on a mixed topology
        # this removes two host copies per local segment per token (the
        # reference bounces every hop through host memory, llama.rs:100-119).
        # Per-segment timings therefore measure dispatch for local segments;
        # their compute lands in the next remote hop's encode sync or the
        # head fetch, which is exactly the overlap being bought.
        x = llama.embed_tokens({"embed": self.embed},
                               jnp.asarray([tokens], jnp.int32), self.config)
        self._last_seg_ms = []
        for i, runner in enumerate(self.runners):
            runner.last_call = {}
            t0 = time.perf_counter()
            with span("decode.segment", seg=i, ident=self._seg_idents[i]):
                x = runner.forward_jax(x, pos)
            dt = time.perf_counter() - t0
            self._last_seg_ms.append(dt * 1e3)
            # the periodic clock refresh (3 ping RTTs every 30s) and any
            # wait on the scraper's STATS round trip ride inside the
            # forward call; keep both out of the steady-state histogram so
            # the segment p99 measures the worker, not the estimator or
            # --top. The flight record keeps the full wall time.
            seg_ms = dt * 1e3 - runner.last_call.get(
                "clock_refresh_ms", 0.0) - runner.last_call.get(
                "lock_wait_ms", 0.0)
            if self._timing_paused:
                pass  # recovery replay: prefill-sized, not steady-state
            elif self._seg_warm[i].value == 0.0:
                self._seg_warm[i].set(seg_ms)
            else:
                self._seg_hist[i].observe(seg_ms)
        x_last = jnp.asarray(x[:, last_index, :])
        return self._head_fn(x_last)[0]

    def _replay_context(self) -> jax.Array:
        """Failure recovery the reference lacks (SURVEY §5: a dropped worker
        connection just ends the generation, client.rs:52-61): reconnect
        every segment — a fresh connection means a fresh worker-side KV
        cache (worker.rs:52-61) — and rebuild all segment caches by
        replaying prompt + generated-so-far in one pass. Each remote
        reconnect retries with backoff under the runner's recovery
        deadline and may FAIL OVER to the segment's next replica (the
        replay rebuilds KV there from scratch, so a replica needs no
        state transfer). Returns logits at the last context position,
        ready to sample the next token."""
        self._recover_runners()
        ctx = self._prompt_tokens + self._generated
        n = len(ctx)
        if n > self.max_seq:
            raise RuntimeError("cannot recover: context exceeds max_seq")
        t_pad = _bucket(n, self.max_seq)
        self._timing_paused = True
        try:
            with span("recover.replay", tokens=n):
                logits = self._forward(ctx + [0] * (t_pad - n), 0, n - 1)
        finally:
            self._timing_paused = False
        self._pos = n
        self.recoveries += 1
        self._recoveries_ctr.inc()
        return logits

    def _recover(self, e: Exception) -> jax.Array:
        """Recovery driver: reconnect+replay until logits land or the
        consecutive-recovery cap trips. The loop (rather than a single
        attempt) covers the replay ITSELF faulting — a worker that dies
        again mid-replay, or a replica that accepts the handshake and
        then drops — each round burning one unit of the cap. Transport
        failures only: a worker-reported op error
        (protocol.WorkerOpError) is deterministic — replaying the context
        would just re-run the same failing op at prefill cost."""
        while True:
            self._consec_recoveries += 1
            if self._consec_recoveries > self.MAX_CONSEC_RECOVERIES:
                raise RuntimeError(
                    f"giving up after {self.MAX_CONSEC_RECOVERIES} "
                    f"consecutive recovery attempts"
                ) from e
            log.warning("segment forward failed (%s); reconnecting "
                        "and replaying %d-token context", e,
                        len(self._prompt_tokens) + len(self._generated))
            try:
                return self._replay_context()
            except (OSError, wire.WireError) as e2:
                e = e2

    # -- Generator trait ----------------------------------------------------
    def next_token(self, index: int) -> Token:
        t_tok0 = time.perf_counter()
        recoveries0 = self.recoveries
        failovers0 = self.failovers
        if index == 0:
            self._require_prompt()
            n = len(self._prompt_tokens)
            t_pad = _bucket(n, self.max_seq)
            with span("prefill", tokens=n):
                # prefill recovers like decode (the seed only guarded
                # decode steps): the replay context IS the prompt at this
                # point, so _recover rebuilds exactly the prefill state
                try:
                    logits = self._forward(
                        self._prompt_tokens + [0] * (t_pad - n), 0, n - 1
                    )
                    self._pos = n
                except (OSError, wire.WireError) as e:
                    logits = self._recover(e)
                tok_id = self._sample(logits, index)
        else:
            self._check_capacity()
            with span("decode.step", index=index):
                try:
                    logits = self._forward([self._last_token], self._pos, 0)
                    self._pos += 1
                    self._consec_recoveries = 0
                except (OSError, wire.WireError) as e:
                    logits = self._recover(e)
                tok_id = self._sample(logits, index)

        if index == 0:
            # tokens/sec excludes the warm-up token (master.rs:37-40)
            self._t_start = time.perf_counter()
        self._tokens_ctr.inc()
        rec = obs_flight.recorder()
        if rec.enabled:
            wire_tot = {"wire_bytes_out": 0, "wire_bytes_in": 0,
                        "wire_bytes_raw": 0,
                        "serialize_ms": 0.0, "deserialize_ms": 0.0}
            for r in self.runners:
                for k in wire_tot:
                    wire_tot[k] += r.last_call.get(k, 0)
            rec.record(
                index=index,
                kind="prefill" if index == 0 else "decode",
                total_ms=round((time.perf_counter() - t_tok0) * 1e3, 3),
                segments_ms=[round(ms, 3) for ms in self._last_seg_ms],
                sample_ms=round(self._last_sample_ms, 3),
                recovery=self.recoveries > recoveries0,
                failover=self.failovers > failovers0,
                **{k: round(v, 3) if isinstance(v, float) else v
                   for k, v in wire_tot.items()},
            )
        return self._finish_token(tok_id)

    # Constrained decoding rides for free on the wire path: sampling (and
    # therefore masking) is master-side — workers only ever see
    # activations, so a grammar constrains a distributed topology without
    # any protocol change. The [V]-bit mask row uploads per token here
    # (the single-stream wire walk is host-loop-bound anyway; the batch
    # engine is where the device-resident-table design pays).
    supports_guide = True

    def _sample(self, logits: jax.Array, index: int) -> int:
        """Sample + history push, timed for the flight record (the int()
        fetch synchronizes, so sample_ms covers the real device work)."""
        t0 = time.perf_counter()
        with span("sample", index=index):
            step_key = jax.random.fold_in(self._key, index)
            if self.guide is not None:
                tok = self._sample_fn(
                    logits, step_key, self._history,
                    mask=jnp.asarray(self.guide.mask_bool()))
            else:
                tok = self._sample_fn(logits, step_key, self._history)
            self._history, self._hist_slot = sampling.push_history(
                self._history, self._hist_slot, tok
            )
            tok_id = int(tok)
        self._last_sample_ms = (time.perf_counter() - t0) * 1e3
        return tok_id

    def tokens_per_sec(self) -> float | None:
        """Decode throughput excluding the warm-up token (master.rs:57-65).
        None until two tokens landed, and None again if the clock has not
        measurably advanced (a sub-microsecond elapsed denominator would
        report garbage teraTokens/sec)."""
        if self._t_start is None or len(self._generated) < 2:
            return None
        dt = time.perf_counter() - self._t_start
        if dt < 1e-6:
            return None
        return (len(self._generated) - 1) / dt

    def runner_stats(self) -> list[dict]:
        """Per-segment steady-state decode latency percentiles from the
        registry histograms (warm-up call reported separately). Remote
        entries include the handshake RTT recorded at connect time
        (client.rs:72-86 shows the same in the reference's WorkerInfo) and,
        for capability-advertising workers, the ping-estimated link RTT and
        clock offset (obs.clock) behind the merged trace."""
        from cake_tpu.obs.cluster import runner_link

        stats = []
        for i, r in enumerate(self.runners):
            h = self._seg_hist[i]
            entry = {
                "ident": r.ident(),
                "layers": f"{r.start}-{r.stop - 1}",
                "calls": h.count,
                "avg_ms": h.mean,
                "p50_ms": h.percentile(0.5),
                "p99_ms": h.percentile(0.99),
                "warmup_ms": self._seg_warm[i].value,
            }
            info = getattr(r, "info", None)
            if info is not None and getattr(info, "latency_ms", None):
                entry["handshake_ms"] = round(info.latency_ms, 2)
            # full failover set (runner_link below contributes "replica",
            # the live-index view — one source of truth for its format)
            addrs = getattr(r, "addrs", None)
            if addrs and len(addrs) > 1:
                entry["replicas"] = list(addrs)
            # same rtt/offset definition as the cluster report (ping
            # estimate, handshake-RTT fallback) — one source of truth
            entry.update({k: v for k, v in runner_link(r).items()
                          if v is not None})
            stats.append(entry)
        return stats

    # -- cluster view --------------------------------------------------------
    def cluster_scraper(self, straggler_factor: float | None = None):
        """The ClusterScraper over this plan's remote segments: a
        WireSource per CAP_STATS worker (in-band, works without any worker
        status port); a worker without the capability but advertising a
        ``status_port`` in its handshake is scraped over HTTP at its
        connection host instead. Cached so ``--top`` and
        ``--cluster-report`` aggregate into the same ``cluster.*``
        series."""
        from cake_tpu.obs import cluster as obs_cluster
        from cake_tpu.runtime import protocol

        if getattr(self, "_scraper", None) is None:
            sources = []
            for r in self.runners:
                if not isinstance(r, RemoteRunner):
                    continue
                if protocol.CAP_STATS in r.caps:
                    sources.append(obs_cluster.WireSource(r))
                elif getattr(r.info, "status_port", 0):
                    # mixed-version/third-party peer: advertises a status
                    # page but not the in-band STATS dialect. Reachability
                    # is the operator's call — the page binds loopback
                    # unless the worker ran with --status-bind opened up.
                    host = r.addr.rsplit(":", 1)[0]
                    sources.append(obs_cluster.HttpSource(
                        f"http://{host}:{r.info.status_port}/",
                        name=r.info.name, runner=r))
            self._scraper = obs_cluster.ClusterScraper(
                sources,
                straggler_factor or obs_cluster.DEFAULT_STRAGGLER_FACTOR,
            )
        return self._scraper

    def cluster_report(self, straggler_factor: float | None = None) -> dict:
        """One aggregation pass over every remote worker plus this
        master's own per-segment view — the ``--cluster-report`` artifact."""
        report = self.cluster_scraper(straggler_factor).scrape()
        report["segments"] = self.runner_stats()
        report["tokens_per_sec"] = self.tokens_per_sec()
        report["recoveries"] = self.recoveries
        report["failovers"] = self.failovers
        return report

    def close(self) -> None:
        # The per-segment series stay registered after close: the CLI's
        # exit-time --metrics-out dump runs AFTER run_master closes the
        # generator, and those histograms are the dump's whole point. A
        # successor generator rebinds overlapping names via publish();
        # only a successor with FEWER segments can leave a predecessor's
        # high-index rows visible, and callers who care can
        # registry().unregister(name, inst) explicitly.
        for r in self.runners:
            r.close()
