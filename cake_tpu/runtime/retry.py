"""Deadline-budgeted retry with exponential backoff and full jitter.

The failure-domain policy plane for the distributed runtime: every place
that re-attempts a network operation — the master's mid-stream
reconnect+replay (`--recover-deadline`), the initial topology connect
(`--connect-retries`, so a master can start before its workers), replica
failover — goes through :func:`retry_call` so backoff shape, jitter, and
budget accounting live in exactly one place.

Full jitter (sleep ~ U[0, min(cap, base * mult^attempt)]) rather than
plain exponential: when a worker restarts, every master attached to it
reconnects at once, and deterministic backoff synchronizes those retries
into thundering herds. The RNG is injectable so tests (and the chaos
harness) can make the schedule reproducible.

Time spent sleeping is accounted in the ``recover.backoff_ms`` registry
counter — visible in ``--metrics-out`` and the cluster report next to
``master.recoveries``/``master.failovers``.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time

from cake_tpu.obs import metrics as _metrics

log = logging.getLogger("cake_tpu.retry")

# total milliseconds slept in backoff across every retry_call in the
# process — the "how long were we blind" counter next to recoveries
_BACKOFF_MS = _metrics.counter("recover.backoff_ms")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Backoff shape + budget. At least one of ``deadline_s`` /
    ``max_attempts`` must bound the loop."""

    deadline_s: float | None = 30.0  # total wall budget (None = unbounded)
    max_attempts: int | None = None  # total tries incl. the first
    base_s: float = 0.05  # first backoff ceiling
    cap_s: float = 2.0  # per-sleep ceiling
    multiplier: float = 2.0

    def __post_init__(self):
        if self.deadline_s is None and self.max_attempts is None:
            raise ValueError("retry policy needs a deadline or max_attempts")

    def backoff_s(self, attempt: int, rng: random.Random) -> float:
        """Full-jitter sleep before retry ``attempt`` (0-based)."""
        ceil = min(self.cap_s, self.base_s * self.multiplier**attempt)
        return rng.uniform(0.0, ceil)


def retry_call(
    fn,
    policy: RetryPolicy,
    *,
    retry_on: tuple = (OSError,),
    describe: str = "operation",
    rng: random.Random | None = None,
    sleep=time.sleep,
    clock=time.monotonic,
):
    """Call ``fn()`` until it succeeds or the policy's budget runs out.

    Only exceptions in ``retry_on`` are retried — anything else (e.g. a
    deterministic handshake rejection like a layer-coverage mismatch) is
    a configuration error and propagates immediately. When the budget is
    exhausted the LAST transport error propagates, so the caller sees
    what actually kept failing, not a synthetic timeout."""
    rng = rng if rng is not None else random.Random()
    t0 = clock()
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            attempt += 1
            if policy.max_attempts is not None and attempt >= policy.max_attempts:
                raise
            delay = policy.backoff_s(attempt - 1, rng)
            if policy.deadline_s is not None:
                remaining = policy.deadline_s - (clock() - t0)
                if remaining <= 0:
                    raise
                # never sleep past the deadline: the last attempt should
                # land inside the budget, not straddle it
                delay = min(delay, remaining)
            _BACKOFF_MS.inc(round(delay * 1e3, 3))
            log.warning(
                "%s failed (%s); retry %d in %.0f ms",
                describe, e, attempt, delay * 1e3,
            )
            sleep(delay)
