"""Worker: serves its topology-assigned decoder layers over the wire.

Equivalent of `cake-core/src/cake/worker.rs`: look up own node by name
(worker.rs:73-83), load ONLY the assigned layers' weights (worker.rs:85-98),
accept master connections, give each connection a fresh KV cache
(worker.rs:52-61), and loop decoding SingleOp/Batch requests into forward
passes with a Tensor reply (worker.rs:180-224), logging throughput every
5 ops (worker.rs:19,244-254).

TPU-native differences:

- Layers are loaded as *stacked contiguous runs* and executed as one jitted
  `lax.scan` per run (no per-layer dispatch; the reference loops blocks
  sequentially per op, worker.rs:208-219).
- Request ops are grouped into those runs server-side, so a Batch covering a
  whole segment costs one XLA dispatch.
- Errors are reported to the master as Error messages instead of dropping
  the connection.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import struct
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models.config import LlamaConfig
from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.obs.trace import span, tracer
from cake_tpu.ops.kvcache import KVCache, init_cache
from cake_tpu.parallel.topology import Topology
from cake_tpu.runtime import protocol, wire
from cake_tpu.runtime.protocol import MsgType, WorkerInfo

log = logging.getLogger("cake_tpu.worker")

STATS_EVERY = 5  # ops between throughput log lines (worker.rs:19)


def _contiguous_runs(indices: list[int]) -> list[tuple[int, int]]:
    """[0,1,2,7,8] -> [(0,3),(7,9)]."""
    runs: list[tuple[int, int]] = []
    for i in sorted(indices):
        if runs and runs[-1][1] == i:
            runs[-1] = (runs[-1][0], i + 1)
        else:
            runs.append((i, i + 1))
    return runs


class Worker:
    """Layer server. ``params_by_run`` maps (start, stop) -> stacked layer
    weights for that run (loaded via utils.weights.load_llama_params with
    layer_range, or sliced from a full params pytree)."""

    def __init__(
        self,
        name: str,
        config: LlamaConfig,
        topology: Topology,
        params_loader,  # callable (start, stop) -> stacked layers pytree
        address: str = "0.0.0.0:10128",
        max_seq: int | None = None,
        kv_quant: str | None = None,
        wire_codec: str | None = None,
    ):
        if name not in topology:
            raise ValueError(f"worker '{name}' not present in topology")
        self.name = name
        self.config = config
        self.node = topology[name]
        self.max_seq = max_seq or config.max_seq_len
        # int8 per-connection KV caches: halves this worker's cache HBM
        # (each connection gets fresh quantized buffers, same isolation)
        self.kv_quant = kv_quant
        # Activation wire codecs advertised in the handshake. By default
        # every codec is on offer and the master picks per connection
        # (--wire-codec); setting one here restricts the offer to
        # {none, that codec} — the operator's lever to forbid lossy
        # compression on a worker regardless of master flags.
        if wire_codec is None:
            self.codecs = list(protocol.CODECS)
        else:
            protocol.check_codec(wire_codec)
            self.codecs = (["none"] if wire_codec == "none"
                           else ["none", wire_codec])
        indices = self.node.layer_indices()
        if not indices:
            raise ValueError(f"worker '{name}' has no layers assigned")
        self.runs = _contiguous_runs(indices)
        log.info("worker %s loading layers %s", name, self.runs)
        # Only the stacked weights are held long-term; KV caches are allocated
        # fresh per connection (worker.rs:52-61) — nothing idle pins HBM.
        self._layers = {
            (lo, hi): params_loader(lo, hi) for lo, hi in self.runs
        }
        from functools import partial

        from cake_tpu.models import llama

        self._fn = jax.jit(partial(llama.hidden_forward_layers, config=config))
        addr, port = address.rsplit(":", 1)
        self.listener = wire.Listener(addr, int(port))
        self.port = self.listener.port
        self._bind_host = addr
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # live counters behind the status surface (the reference's worker
        # app renders this state in a SwiftUI view, ContentView.swift:28-56;
        # on a headless TPU VM the equivalent is an HTTP JSON endpoint)
        self._stat_lock = threading.Lock()
        self._conns_live = 0
        self._conns_total = 0
        self._started = time.time()
        self._status_httpd = None
        self._status_port = 0  # bound status-page port, advertised in _info()
        # Serving counters as per-instance obs instruments (the
        # Registry.publish pattern) — the single source of truth for both
        # status() and the registry dumps.
        self._ops_ctr = obs_metrics.Counter("worker.ops")
        self._bytes_in_ctr = obs_metrics.Counter("worker.bytes_in")
        self._bytes_out_ctr = obs_metrics.Counter("worker.bytes_out")
        # steady-state forward times only; each connection's first op
        # (prefill + possible XLA compile) lands in the warmup gauge — the
        # master's warmup/steady split, worker-side, so the cluster
        # straggler check compares decode behavior, not compile luck
        self._fwd_hist = obs_metrics.Histogram("worker.forward_ms")
        self._warm_gauge = obs_metrics.Gauge("worker.warmup_ms")
        self._prefill_hist = obs_metrics.Histogram("worker.prefill_ms")
        # Shapes whose XLA compile this PROCESS has already paid. Warmup
        # detection must share the compile cache's scope (jit caches per
        # process, not per connection): after a master reconnect the first
        # op of a shape on the NEW connection is a fast steady-state call
        # and belongs in the histogram, not the warmup gauge.
        self._warmed_shapes: set = set()
        obs_metrics.registry().publish(
            self._ops_ctr, self._bytes_in_ctr, self._bytes_out_ctr,
            self._fwd_hist, self._warm_gauge, self._prefill_hist)

    # -- serving ------------------------------------------------------------
    def serve_forever(self) -> None:
        log.info("worker %s listening on port %d", self.name, self.port)
        while not self._stop.is_set():
            try:
                conn = self.listener.accept()
            except Exception:
                if self._stop.is_set():
                    return
                raise
            if self._stop.is_set():  # woken by shutdown's dummy connect
                conn.close()
                return
            th = threading.Thread(
                target=self._handle_connection, args=(conn,), daemon=True
            )
            th.start()
            self._threads = [t for t in self._threads if t.is_alive()]
            self._threads.append(th)

    def serve_in_background(self) -> threading.Thread:
        th = threading.Thread(target=self.serve_forever, daemon=True)
        th.start()
        return th

    # -- status surface ------------------------------------------------------
    def status(self, include_metrics: bool = True) -> dict:
        """Live worker state as a plain dict: identity (the WorkerInfo
        handshake fields), assigned layer runs, and serving counters.
        ``include_metrics=False`` skips the full registry snapshot — the
        in-band STATS reply wants the cheap top-level fields only."""
        from cake_tpu.utils.memory import rss_bytes

        info = self._info()
        with self._stat_lock:
            st = {
                "name": info.name,
                "version": info.version,
                "os": info.os,
                "arch": info.arch,
                "device": info.device,
                "device_idx": info.device_idx,
                "dtype": info.dtype,
                "kv_quant": self.kv_quant,
                "wire_codecs": list(self.codecs),
                "wire_caps": info.caps,
                "max_seq": self.max_seq,
                "port": self.port,
                "layer_runs": [list(r) for r in self.runs],
                "uptime_s": round(time.time() - self._started, 1),
                "connections_live": self._conns_live,
                "connections_total": self._conns_total,
                "ops_total": self._ops_ctr.value,
                "bytes_in": self._bytes_in_ctr.value,
                "bytes_out": self._bytes_out_ctr.value,
                # THIS worker's segment forward-time distribution, from the
                # instance-owned histogram (the registry series of the same
                # name is last-publisher-wins when several Workers share a
                # process; the cluster scraper's per-worker p50/p99 must
                # not be)
                "forward_ms": self._fwd_hist.snapshot(),
                "prefill_ms": self._prefill_hist.snapshot(),
                "warmup_ms": self._warm_gauge.value,
                "rss_bytes": rss_bytes(),
            }
            if include_metrics:
                # full registry snapshot: wire frame/byte/CRC counters and
                # layer forward-time histograms with p50/p99, one page
                st["metrics"] = obs_metrics.registry().snapshot()
            return st

    def start_status_server(self, port: int = 0,
                            bind: str | None = None) -> int:
        """Serve ``status()`` as JSON over HTTP on ``port`` (0 = ephemeral;
        returns the bound port). The headless-deployment equivalent of the
        reference's worker GUI (`cake-ios-worker-app/Cake
        Worker/ContentView.swift:28-56` renders name/device/layers/state;
        here ``curl :port/`` or a browser does). ``bind`` defaults to
        loopback (CLI ``--status-bind``): the page leaks identity, layer
        assignments, and traffic counters, so exposure beyond the host is
        an explicit choice, independent of the serving ``--address``.
        Daemon-threaded; stopped by :meth:`shutdown`."""
        from cake_tpu.obs import statusd

        bind = bind if bind is not None else "127.0.0.1"
        self._status_httpd, bound = statusd.start_status_server(
            self.status, bind=bind, port=port)
        self._status_port = bound
        log.info("worker %s status page on http://%s:%d/", self.name,
                 bind, bound)
        return bound

    def shutdown(self) -> None:
        self._stop.set()
        if self._status_httpd is not None:
            self._status_httpd.shutdown()
            self._status_httpd.server_close()
            self._status_httpd = None
            self._status_port = 0
        # A blocked accept() does not return when the fd is closed from
        # another thread on Linux; wake it with a throwaway connection.
        try:
            wire.connect("127.0.0.1", self.port, timeout_ms=1000).close()
        except Exception:
            pass
        self.listener.close()

    # -- per-connection loop ------------------------------------------------
    def _info(self) -> WorkerInfo:
        dev = jax.devices()[0]
        return WorkerInfo(
            name=self.name,
            device=getattr(dev, "device_kind", str(dev)),
            device_idx=getattr(dev, "id", 0),
            dtype=self.config.dtype,
            max_seq=self.max_seq,
            codecs=list(self.codecs),
            caps=list(protocol.ALL_CAPS),
            status_port=self._status_port,
            layers=[
                f"model.layers.{i}"
                for lo, hi in self.runs
                for i in range(lo, hi)
            ],
        )

    def _handle_connection(self, conn: wire.Connection) -> None:
        """One master connection: Hello -> WorkerInfo, then op loop with a
        per-connection fresh cache (worker.rs:149-258)."""
        # fresh per-connection caches: isolation over synchronization.
        # Allocated lazily on the first op — a PING/STATS-only connection
        # (the cluster scraper, a health probe) must not pin cache HBM.
        caches: dict[tuple[int, int], KVCache] | None = None
        ops_done = 0
        t_window = time.perf_counter()
        bytes_in = bytes_out = 0
        with self._stat_lock:
            self._conns_live += 1
            self._conns_total += 1
        try:
            # timeout=None is a decision, not a default (cakelint CK-WIRE):
            # the accepted side legitimately waits forever for the master's
            # next request; TCP keepalive bounds the dead-peer case.
            t, _ = conn.recv(timeout=None)
            if t != MsgType.HELLO:
                conn.send(MsgType.ERROR, protocol.encode_error("expected HELLO"))
                return
            conn.send(MsgType.WORKER_INFO, self._info().to_bytes())
            while not self._stop.is_set():
                try:
                    t, payload = conn.recv(timeout=None)
                except wire.PeerClosed:
                    return
                if t == MsgType.GOODBYE:
                    return
                if t == MsgType.PING:
                    # clock probe (CAP_PING): echo the master's opaque
                    # timestamp back with this process's perf_counter so
                    # the master can estimate the inter-clock offset
                    conn.send(MsgType.PING, [
                        memoryview(payload),
                        struct.pack("<d", time.perf_counter()),
                    ])
                    continue
                if t == MsgType.STATS:
                    # status snapshot over the op connection (CAP_STATS) —
                    # the scrape path for workers that never opened a
                    # --status-port. The full registry snapshot stays on
                    # the HTTP page: the scraper reads only the top-level
                    # fields, and this reply is serialized against live
                    # forwards by the master's connection lock, so every
                    # byte here is decode stall.
                    conn.send(MsgType.STATS, json.dumps(
                        self.status(include_metrics=False)).encode())
                    continue
                if t not in (MsgType.SINGLE_OP, MsgType.BATCH):
                    conn.send(
                        MsgType.ERROR,
                        protocol.encode_error(f"unexpected message type {t}"),
                    )
                    continue
                bytes_in += len(payload)
                t_handle0 = time.perf_counter()
                try:
                    x, ops, codec, trailer = protocol.decode_ops_traced(
                        payload)
                    t_dec1 = time.perf_counter()
                    if codec not in self.codecs:
                        # enforce the advertised restriction server-side: a
                        # client that skipped the handshake check must not
                        # smuggle lossy compression onto a worker whose
                        # operator forbade it
                        raise ValueError(
                            f"wire codec '{codec}' not accepted by this "
                            f"worker (offers {self.codecs})"
                        )
                    if caches is None:
                        caches = {
                            (lo, hi): init_cache(
                                self.config, batch=1, max_seq=self.max_seq,
                                num_layers=hi - lo, quant=self.kv_quant,
                            )
                            for lo, hi in self.runs
                        }
                    t0 = time.perf_counter()
                    with span("worker.forward", ops=len(ops)):
                        out = self._run_ops(x, ops, caches)
                    t_fwd1 = time.perf_counter()
                    # XLA compiles per activation shape; the process-wide
                    # first op of each shape (prefill [1,T,H], then the
                    # first [1,1,H] decode) pays it. Those land in the
                    # warmup gauge so the histogram — and the cluster
                    # straggler check built on its p99 — holds steady-state
                    # decode behavior only, mirroring the master's
                    # warmup/steady split.
                    shape = tuple(np.shape(x))
                    with self._stat_lock:
                        warmed = shape in self._warmed_shapes
                        self._warmed_shapes.add(shape)
                    fwd_ms = (t_fwd1 - t0) * 1e3
                    if not warmed:
                        self._warm_gauge.set(fwd_ms)
                    elif len(shape) >= 2 and shape[1] > 1:
                        # warmed multi-token forward: a fresh prompt's
                        # prefill or the master's recovery replay. Real
                        # work, but ~100x a decode step — it mirrors the
                        # master's _timing_paused/_seg_warm exclusions
                        # into its own series so forward_ms (and the
                        # straggler p99 built on it) stays decode-only.
                        self._prefill_hist.observe(fwd_ms)
                    else:
                        self._fwd_hist.observe(fwd_ms)
                except Exception as e:  # report, keep serving
                    log.exception("op failed")
                    conn.send(MsgType.ERROR, protocol.encode_error(str(e)))
                    continue
                # the reply mirrors the request's codec (master chose it at
                # handshake against this worker's advertised set)
                reply = protocol.encode_activation_parts(out, codec)
                t_enc1 = time.perf_counter()
                tc = (trailer or {}).get("tc")
                if tc is not None:
                    # the request carried a Dapper-style trace context: ship
                    # back a compact span digest (this clock's timebase; the
                    # master rebases via its ClockSync) and mirror the same
                    # spans into this process's own tracer when it is on.
                    # No context -> byte-identical legacy reply.
                    digest_spans = [
                        ["ops.handle", t_handle0, t_enc1 - t_handle0],
                        ["ops.decode", t_handle0, t_dec1 - t_handle0],
                        ["ops.forward", t0, t_fwd1 - t0],
                        ["ops.encode", t_fwd1, t_enc1 - t_fwd1],
                    ]
                    reply.append(json.dumps({"digest": {
                        "name": self.name,
                        "seq": tc.get("seq"),
                        "spans": [[n, round(ts, 7), round(d, 7)]
                                  for n, ts, d in digest_spans],
                    }}).encode())
                    tr = tracer()
                    if tr.enabled:
                        args = {"trace_id": tc.get("tid"),
                                "parent_span_id": tc.get("psid"),
                                "seq": tc.get("seq")}
                        for n, ts, d in digest_spans:
                            tr.record(n, ts, d, args)
                reply_len = sum(len(p) for p in reply)
                bytes_out += reply_len
                conn.send(MsgType.TENSOR, reply)
                ops_done += len(ops)
                self._ops_ctr.inc(len(ops))
                self._bytes_in_ctr.inc(len(payload))
                self._bytes_out_ctr.inc(reply_len)
                if ops_done >= STATS_EVERY:
                    dt = time.perf_counter() - t_window
                    log.info(
                        "%s: %.1f ops/s, read %.1f MB/s, write %.1f MB/s",
                        self.name, ops_done / dt,
                        bytes_in / dt / 1e6, bytes_out / dt / 1e6,
                    )
                    t_window = time.perf_counter()
                    ops_done = 0
                    bytes_in = bytes_out = 0
        # A handler thread must never die silently: per-op failures are
        # answered with ERROR replies above, so anything arriving here is
        # connection-level (a master that vanished mid-reply, a poisoned
        # frame stream) or a genuine bug — log it and fall through to the
        # cleanup either way.
        except wire.PeerClosed:
            # abrupt close without GOODBYE (health probe, killed master):
            # routine from the server's side, not worth a warning
            log.debug("%s: peer closed without GOODBYE", self.name)
        except (wire.WireError, OSError) as e:
            log.warning("%s: connection lost (%s); dropping it", self.name, e)
        except Exception:
            log.exception("%s: connection handler crashed; dropping the "
                          "connection", self.name)
        finally:
            with self._stat_lock:
                self._conns_live -= 1
            # Drop this connection's KV caches NOW: the exception paths
            # above can keep the handler frame alive in traceback refs,
            # and HBM-backed cache buffers must not stay pinned until GC
            # gets around to them (a crash-looping client would otherwise
            # accumulate dead caches).
            if caches:
                caches.clear()
            conn.close()

    def _run_ops(
        self,
        x: np.ndarray,
        ops: list[tuple[str, int]],
        caches: dict[tuple[int, int], KVCache],
    ) -> np.ndarray:
        """Execute the requested layer ops in order, grouping into stored
        contiguous runs (one jitted scan per group)."""
        indices: list[tuple[int, int]] = []
        for name, pos in ops:
            if not name.startswith("model.layers."):
                raise ValueError(f"unknown layer name '{name}'")
            indices.append((int(name.rsplit(".", 1)[1]), int(pos)))

        h = jnp.asarray(x, self.config.jax_dtype)
        i = 0
        while i < len(indices):
            layer_idx, pos = indices[i]
            run = next(
                (r for r in self.runs if r[0] <= layer_idx < r[1]), None
            )
            if run is None:
                raise ValueError(
                    f"layer {layer_idx} not served by worker '{self.name}'"
                )
            # extend over consecutive ops staying in this run at same pos
            j = i
            while (
                j + 1 < len(indices)
                and indices[j + 1][0] == indices[j][0] + 1
                and indices[j + 1][0] < run[1]
                and indices[j + 1][1] == pos
            ):
                j += 1
            lo, hi = indices[i][0], indices[j][0] + 1
            run_layers = self._layers[run]
            cache = caches[run]
            if (lo, hi) == run:
                # fast path: the whole stored run in one jitted scan
                h, caches[run] = self._fn(
                    run_layers, h, cache, jnp.int32(pos)
                )
            else:
                # partial-run request: slice weights + cache, write back
                layers = jax.tree.map(
                    lambda a: a[lo - run[0] : hi - run[0]], run_layers
                )
                sub = KVCache(
                    k=cache.k[lo - run[0] : hi - run[0]],
                    v=cache.v[lo - run[0] : hi - run[0]],
                )
                h, sub = self._fn(layers, h, sub, jnp.int32(pos))
                caches[run] = KVCache(
                    k=cache.k.at[lo - run[0] : hi - run[0]].set(sub.k),
                    v=cache.v.at[lo - run[0] : hi - run[0]].set(sub.v),
                )
            i = j + 1
        return np.asarray(h)
