"""Runtime twin of cakelint CK-THREAD: thread-domain stamps + asserts.

The static checker (:mod:`cake_tpu.analysis.thread_domains`) proves that
annotated code never *calls* across a thread domain except through the
declared crossing points. This module validates the model against real
execution: with ``CAKE_THREAD_STRICT=1`` (or :func:`set_strict`), the
scheduler's engine thread stamps itself into its engine's
:class:`DomainStamp` when it starts, and every annotated mutator
(``BatchGenerator.step``/``enqueue``/..., ``PagePool.alloc``/``pin``/...)
asserts the calling thread is the stamped one — the same opt-in
strict-twin pattern as ``CAKE_OBS_STRICT`` for the metrics catalog.

The stamp is **per engine instance** (one ``DomainStamp`` shared by an
engine, its page pool, and its prefix tree), not process-global: test
fleets run several engines in one process, each with its own owner
thread. Before the stamp (construction, priming, warmups — all
happens-before the engine thread exists) and after it clears (the
engine thread exited; drain replays may legitimately drive the engine
from the survivor thread) the assert is vacuous, so direct single-
threaded drives (bench, examples, unit tests) run unchanged even with
strict on.

Disabled (the default), the whole twin is one module-bool read per
mutator call.
"""

from __future__ import annotations

import os
import threading

_STRICT = os.environ.get("CAKE_THREAD_STRICT", "") not in ("", "0")


def strict() -> bool:
    return _STRICT


def set_strict(on: bool) -> bool:
    """Flip strict mode (tests); returns the previous value."""
    global _STRICT
    prev, _STRICT = _STRICT, bool(on)
    return prev


class DomainStamp:
    """Owner-thread stamp for one thread domain instance.

    ``stamp()`` from the owning thread; ``check(what)`` from every
    annotated mutator. Unstamped (or cleared) stamps pass every check —
    ownership only exists while the owning thread is alive and claimed.
    """

    __slots__ = ("domain", "ident", "name")

    def __init__(self, domain: str = "engine"):
        self.domain = domain
        self.ident: int | None = None
        self.name = ""

    def stamp(self) -> None:
        self.ident = threading.get_ident()
        self.name = threading.current_thread().name

    def clear(self) -> None:
        self.ident = None
        self.name = ""

    def check(self, what: str) -> None:
        if not _STRICT:
            return
        ident = self.ident
        if ident is None or ident == threading.get_ident():
            return
        raise RuntimeError(
            f"CAKE_THREAD_STRICT: {what} called from thread "
            f"{threading.current_thread().name!r} but its "
            f"{self.domain!r} domain is owned by thread {self.name!r} — "
            "route the work through the owner's declared crossing points "
            "(scheduler submit/inbox, session queues) instead of touching "
            "domain state directly"
        )
