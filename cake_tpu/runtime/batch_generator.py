"""Multi-stream serving: N prompts decode concurrently over the mesh batch.

The reference is strictly single-request — "no batching of concurrent
requests" (SURVEY.md §0; one master walks one stream, master.rs:21-65). This
is the TPU-native capability on top of the same pipeline: the batch axis of
the fused mesh program (parallel/pipeline.py) shards over the ``dp`` mesh
axis, and every decode dispatch advances *all* streams by one token (or one
``block_size`` block).

Per-stream independence is real, not cosmetic:

- **positions**: prompts are right-padded to a shared bucket but each stream
  decodes at its own position (``pos [B]`` — per-row RoPE slices, KV writes,
  and causal frontiers down through the Pallas decode kernel), so a token's
  positional geometry is identical to a single-stream run of the same prompt.
- **sampling keys**: stream ``s`` owns ``fold_in(PRNGKey(seed), stream_id)``,
  stepped by the absolute token index inside the compiled program
  (pipeline per_row mode). A stream's stochastic output depends only on
  (seed, stream_id, prompt) — invariant to batch composition, dp layout, and
  block size.
- **repeat-penalty history**: per-stream ring buffers seeded with each
  prompt's tail, with per-stream ring slots (``hist_slot [B]``).
- **EOS / detok**: tracked per stream; a finished stream stops emitting while
  the batch keeps running (its rows keep computing into discarded outputs —
  the SPMD analogue of the pipeline's gated inactive stages).

Sequence parallelism (r4): on an ``sp > 1`` plan the KV window is sharded
across the sp axis and every stream still decodes at its own frontier —
the per-row positions flow through the owner-masked sp cache write and the
per-row-masked distributed flash decode (ops/ring.py). This is the
many-LONG-streams composition: window HBM splits over sp while the batch
splits over dp. Continuous admission, the prefix store, batched
speculation, AND the interleaved schedules all compose with ``sp > 1``
too (r5): staged/fed token blocks run chunk-replicated over sp against
the sequence-sharded cache (owner-masked range writes — per-row for the
verification plane — plus the T>1 distributed-flash chunk attend), the
slot splice is sharding-agnostic, and the interleaved cycle loop's
resident microbatch decodes against its sequence-sharded KV rows. The
one remaining sp == 1 path is GPipe microbatch PREFILL (prompts at
sp > 1 ride the ring prefill instead).

Continuous batching: arrivals ``enqueue`` into a FIFO and are admitted into
freed slots without stalling the batch — each ``step()`` advances the head
arrival's prefill by one chunk dispatch (one replicated row into a staging
cache, ``parallel.pipeline.build_admit_prefill``) alongside the running
decode dispatch, then splices the finished row into its slot. ``admit()``
is the synchronous variant. Admission timing never changes a stream's
output (per-row positions + per-row token indices).

Int8-weight determinism: ``ops.quant.quant_matmul``'s measured m>=16
crossover would pick its backend per shape, so the SAME stream could see
different low-order logit bits between batch-size buckets or between
prefix-hit and prefix-miss admission prefills. An instance therefore PINS
one backend for its whole lifetime (``quant.pinned_impl``): explicitly via
``quant_backend=``, else chosen at first ``set_prompts`` from the dp-local
batch geometry against the measured crossover. Every program the instance
dispatches traces under that pin, so WITHIN an instance sampled int8
streams are invariant to batch-size buckets, admission timing, and
prefix-cache hits. Across two *differently sized* instances that land on
opposite sides of the crossover the pins (and low-order logit bits) can
still differ — pass the same explicit ``quant_backend`` to both when
cross-instance bit-reproducibility matters more than the measured
crossover's throughput.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.kvpool import (
    SINK,
    PagePool,
    PoolExhausted,
    PrefixLRU,
    PrefixTree,
)
from cake_tpu.kvpool import pool as kvpool_pool
from cake_tpu.models.config import LlamaConfig
from cake_tpu.obs import flight as obs_flight
from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.obs import prof as obs_prof
from cake_tpu.obs.trace import span
from cake_tpu.ops import quant, sampling
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.mesh import (
    MeshPlan,
    init_cache_on_mesh,
    shard_params,
)
from cake_tpu.parallel.pipeline import (
    build_admit_prefill,
    build_interleaved_decode,
    build_sharded_decode,
    build_sharded_prefill,
)
from cake_tpu.runtime.generator import Token, _bucket, encode_prompt
from cake_tpu.runtime import threadcheck
from cake_tpu.utils.token_stream import TokenOutputStream


@dataclasses.dataclass
class _Stream:
    stream_id: int
    prompt: list[int]
    generated: list[int] = dataclasses.field(default_factory=list)
    done: bool = False
    active: bool = True  # False: batch-padding dummy, never emitted
    detok: TokenOutputStream | None = None
    # why the stream ended: "eos" | "length" (window full) | "constraint"
    # (grammar dead end) — the serve scheduler's finish_reason source
    end_reason: str | None = None


# initial device mask-table capacity (rows); grows by doubling as guides
# attach, so the masked decode program compiles once per pow2 table shape
_MASK_CAP0 = 64

# Disaggregated-serving counters (cake_tpu/disagg): KV-page snapshots
# leaving and entering this engine's pool. Process-wide get-or-create —
# the serve scheduler and the gateway's tier map read the same story.
_EXPORTS = obs_metrics.counter("disagg.exports")
_IMPORTS = obs_metrics.counter("disagg.imports")
_RESUMES = obs_metrics.counter("disagg.resumes")
_IMPORT_ABORTS = obs_metrics.counter("disagg.import_aborts")

# arrival-queue entry kinds (4th tuple field): None marks a plain prompt
# arrival; imports ride the SAME FIFO so pool-pressure deferral stays
# FIFO-fair between admissions and KV-page imports
_ARR_IMPORT = "import"  # (xfer_id, None, None, _ARR_IMPORT)
_ARR_ATTACH = "attach"  # (xfer_id, sid, None, _ARR_ATTACH)


class BatchGenerator:
    """Serve N prompts concurrently over one sharded model instance.

    ``batch`` rows are sharded over the plan's dp axis (``N`` is padded up to
    a multiple of dp with inactive dummy rows). ``block_size > 1`` fuses that
    many decode steps per dispatch, same key schedule.
    """

    # Thread domain, machine-checked by cakelint CK-THREAD (the
    # declarative generalization of CK-ENGINE's single-writer rule):
    # every un-listed method runs on the engine-owner thread only —
    # annotated caller code (serve/gateway handler threads, transfer
    # receivers) must route through the scheduler's crossing points.
    # `_encode` is this class's own crossing point: a stateless
    # tokenizer pass the scheduler's handler-facing encode_prompt uses.
    # Instances travel as `self.engine` handles, hence the alias. The
    # runtime twin (CAKE_THREAD_STRICT=1, runtime/threadcheck) asserts
    # the same contract: the scheduler stamps its engine thread into
    # _domain_stamp at start and the annotated mutators check it.
    _THREAD_DOMAIN = "engine"
    _THREAD_ALIASES = ("engine",)
    _THREAD_SAFE = ("_encode",)

    def __init__(
        self,
        config: LlamaConfig,
        params,
        plan: MeshPlan | None = None,
        tokenizer=None,
        settings: SamplerSettings | None = None,
        max_seq: int | None = None,
        num_stages: int = 1,
        tp: int = 1,
        dp: int = 1,
        ep: int = 1,
        devices=None,
        block_size: int = 1,
        block_size_max: int = 0,
        lookahead: bool = False,
        kv_quant: str | None = None,
        admit_chunk: int | None = None,
        prefix_share_min: int = 32,
        interleave: bool | None = None,
        prefix_cache_entries: int = 2,
        prefix_block: int = 64,
        quant_backend: str | None = None,
        spec_k: int = 0,
        spec_ngram: int = 3,
        spec_rounds: int = 8,
        logprobs: int = 0,
        kv_layout: str = "slot",
        kv_page_size: int = 16,
        kv_pool_pages: int | None = None,
    ):
        if plan is None:
            plan = MeshPlan.build(config, num_stages=num_stages, tp=tp,
                                  dp=dp, sp=1, ep=ep, devices=devices)
        # sp > 1 (r4): multi-stream serving over a sequence-sharded window —
        # per-row frontiers flow through the sp owner-masked KV write and
        # per-row-masked distributed flash decode. Admission, the prefix
        # store, batched speculation, and the interleaved schedules all
        # compose with sp > 1 (r5, chunk-replicated programs + sp-aware
        # cycle loops); only GPipe microbatch prefill stays sp == 1
        # (_pick_prefill serializes it).
        # spec_k composes with sp > 1 (r5): the per-row verification
        # program runs each row's fed block chunk-replicated over sp
        # (pipeline.build_sharded_verify_rows) with per-row range writes.
        # (r5: the interleaved schedules compose with sp > 1 too — the
        # resident microbatch's decode/verify runs against its
        # sequence-sharded KV rows inside the cycle loop)
        self.config = config
        self.plan = plan
        # engine-owner thread stamp (runtime twin of CK-THREAD): the
        # serve scheduler stamps its engine thread here at start and
        # clears it on exit; unstamped, every check is vacuous, so
        # single-threaded drives (bench, examples, tests) run unchanged
        # even under CAKE_THREAD_STRICT=1
        self._domain_stamp = threadcheck.DomainStamp("engine")
        self.settings = settings or SamplerSettings()
        sampling.validate_logit_bias(self.settings, config.vocab_size)
        # Per-token top-k logprob reporting (serve `logprobs: N`): the
        # decode programs additionally return the top-k log-softmax of
        # the raw logits. Pure extra outputs — the sampled streams are
        # bit-identical with it on or off.
        self.logprobs_k = max(0, int(logprobs))
        if self.logprobs_k and spec_k:
            raise ValueError("logprobs do not compose with batched "
                             "speculation (spec_k): accepted runs have no "
                             "per-step logits to report")
        self.max_seq = max_seq or config.max_seq_len
        if plan.sp > 1 and self.max_seq % plan.sp:
            raise ValueError(
                f"max_seq {self.max_seq} must divide by sp {plan.sp} (the "
                "KV window shards over the sp axis)"
            )
        # Paged KV (cake_tpu/kvpool): the per-slot contiguous cache is
        # replaced by a pooled page array addressed through per-stream
        # page tables fed into the compiled decode step as gather
        # indices. Admission and retirement become host-side page-table
        # edits (plus a one-page-per-stream write-back per dispatch)
        # instead of cache-tensor splices, and refcounted pages turn the
        # prefix store into a real shared-prefix tree — n streams with
        # the same system prompt share physical prefill pages.
        if kv_layout not in ("slot", "paged"):
            raise ValueError(
                f"kv_layout must be 'slot' or 'paged', got {kv_layout!r}")
        self._paged = kv_layout == "paged"
        self._page_size = int(kv_page_size)
        self._pool_pages_req = kv_pool_pages
        if self._paged:
            if plan.dp != 1 or plan.sp != 1:
                raise ValueError(
                    "kv_layout='paged' requires dp == 1 and sp == 1 (the "
                    "page axis is unsharded; batch/sequence sharding of "
                    "pooled pages is future work)")
            if spec_k:
                raise ValueError(
                    "kv_layout='paged' does not compose with batched "
                    "speculation (spec_k): the fused verify rounds write "
                    "K+1 slots per row outside the page write-back plan")
            if self._page_size < 1 or self.max_seq % self._page_size:
                raise ValueError(
                    f"kv_page_size {self._page_size} must be a positive "
                    f"divisor of max_seq {self.max_seq}")
            if kv_pool_pages is not None and (
                    kv_pool_pages < 2
                    or kv_pool_pages & (kv_pool_pages - 1)):
                # shape validation belongs HERE with the other paged
                # knobs (the CLI's try/except turns ctor ValueErrors into
                # clean exits); only the batch-dependent >= need bound
                # waits for set_prompts (_init_pool)
                raise ValueError(
                    f"kv_pool_pages must be a power of two >= 2, got "
                    f"{kv_pool_pages}")
            self._ppp = self.max_seq // self._page_size  # pages per stream
        self._pagepool = None          # host free-list/refcounts (kvpool)
        self._prefix_tree = None       # page-granular shared-prefix trie
        self._tables: list[list[int]] = []  # per-slot physical page lists
        # KV-page imports (cake_tpu/disagg): xfer_id -> record. Pages of
        # a begun-but-unattached import are PINNED in the pool (a claim
        # outside stream tables and the prefix tree — kvpool pin/unpin),
        # so eviction storms under pressure can never free them before
        # the resume attaches or the import is aborted.
        self._imports: dict[str, dict] = {}
        self._attach_failures: list[int] = []  # sids whose attach missed
        self._page_map_dev = None      # memoized device page map (tables
        #                                change rarely; scatter ids do not)
        self._staged_prefix = None     # set_prompts staged prefix row
        self._admit_deferred = False   # last tick deferred on pool pressure
        self.tokenizer = tokenizer
        self.block_size = max(1, block_size)
        # Adaptive decode blocks (the continuous-batching dispatch lever):
        # with block_size_max > block_size, the fused block DOUBLES each
        # dispatch while the arrival queue is empty — amortizing the
        # per-dispatch host sync over more tokens — and snaps back to
        # block_size the moment an arrival waits, so admission latency
        # stays one base block. Grown sizes live on a doubling ladder
        # (base*2^k) so the window-headroom cap below can halve back onto
        # a compiled program; block_size_max is rounded down to the
        # ladder. warm_blocks() compiles the ladder outside the serving
        # window. The r4 churn row measured ~1.5 s of dispatch wall per
        # ~190 ms of device math through the tunnel — block growth is the
        # repo's own diagnosed fix (BASELINE.md churn row).
        bmax = max(0, int(block_size_max))
        if bmax > self.block_size:
            k = (bmax // self.block_size).bit_length() - 1
            self.block_size_max = self.block_size * (1 << k)
        else:
            self.block_size_max = self.block_size
        self._adaptive = self.block_size
        self.__block_progs: dict = {}
        # Lookahead double-buffering (r5): dispatch block N+1 from the
        # DEVICE-side feedback token (toks[-1]) before fetching block N's
        # rows to the host, so the device computes the next block while
        # the host round-trip for the current one is in flight — on a
        # tunneled chip the fetch RTT is comparable to the block's math
        # (BASELINE.md churn diagnosis), so this overlaps most of it.
        # Token streams are unchanged: the feedback token is exactly the
        # one the host would have fed back, and rows computed past a
        # stream's EOS/retirement are discarded per-row like every other
        # overrun (the admission splice drains the in-flight block's rows
        # BEFORE a slot changes meaning — _finish_admission). Off by
        # default; incompatible with batched speculation (the spec plane
        # needs the host between dispatches).
        if lookahead and spec_k:
            raise ValueError("lookahead dispatch does not compose with "
                             "batched speculation (spec_k)")
        self._lookahead = bool(lookahead)
        self._inflight: tuple | None = None  # (device toks [steps,B], size)
        # int8 KV roughly doubles servable batch x window on a fixed HBM
        # budget (quantize-on-write per slot, kvcache.QuantizedKV) — the
        # serving-side long-context lever
        self.kv_quant = kv_quant
        self.params = shard_params(params, plan.mesh)
        # Int8 backend pin: explicit (quant_backend=) or decided once at
        # first set_prompts from the dp-local batch geometry (measured
        # m>=16 crossover), then applied to every program dispatch for the
        # instance's lifetime — see the module docstring's determinism
        # contract and its cross-instance scope note.
        if quant_backend not in (None, "xla", "pallas"):
            raise ValueError(
                f"quant_backend must be 'xla' or 'pallas', got "
                f"{quant_backend!r}"
            )
        self._quant_pin: str | None = quant_backend

        def _has_quant(p, kinds) -> bool:
            if isinstance(p, dict):
                return any(_has_quant(v, kinds) for v in p.values())
            return isinstance(p, kinds)

        self._params_quantized = _has_quant(
            self.params, (quant.QuantizedLinear, quant.Quantized4Linear)
        )
        self._params_int4 = _has_quant(self.params, quant.Quantized4Linear)
        self._prefill = self._pinned(build_sharded_prefill(
            config, plan, params_like=self.params, kv_quant=kv_quant))
        # raw jit handle kept so tests can pin the compile count — the
        # paged layout's page-table operands are DATA, so table churn
        # (admission, retirement, page growth) must never retrace
        self._decode_single_jit = build_sharded_decode(
            config, self.settings, plan, params_like=self.params,
            per_row=True, kv_quant=kv_quant, logprobs_k=self.logprobs_k,
            paged=self._paged,
        )
        self._decode_single = self._pinned(self._decode_single_jit)
        self._decode_block = (
            self._pinned(build_sharded_decode(config, self.settings, plan,
                                              params_like=self.params,
                                              steps=self.block_size,
                                              per_row=True,
                                              kv_quant=kv_quant,
                                              logprobs_k=self.logprobs_k,
                                              paged=self._paged))
            if self.block_size > 1 else None
        )
        # Interleaved-microbatch schedule (pipeline.build_interleaved_decode):
        # with num_stages > 1 every stage decodes a different microbatch each
        # cycle instead of (S-1)/S of the mesh computing into a discarded
        # select. Output streams are bit-identical, so it swaps in at
        # dispatch whenever the batch divides by the stage count; serialized
        # programs remain the fallback (programs compile lazily on first
        # use, so the unused path costs nothing).
        self._interleave = (
            plan.num_stages > 1 if interleave is None
            else interleave and plan.num_stages > 1
        )
        if self.logprobs_k:
            # the interleaved schedule has no logprob outputs (its head
            # runs vocab-split per stage); serialized programs are
            # bit-identical, so logprob serving just uses those
            self._interleave = False
        if self._paged:
            # the interleaved schedule has no paged twin yet; serialized
            # paged programs are bit-identical, so paged serving uses
            # those (same fallback contract as logprobs)
            self._interleave = False
        self._decode_single_il = (
            self._pinned(build_interleaved_decode(
                config, self.settings, plan, params_like=self.params,
                steps=1, kv_quant=kv_quant))
            if self._interleave else None
        )
        self._decode_block_il = (
            self._pinned(build_interleaved_decode(
                config, self.settings, plan, params_like=self.params,
                steps=self.block_size, kv_quant=kv_quant))
            if self._interleave and self.block_size > 1 else None
        )
        self._base_key = jax.random.PRNGKey(self.settings.seed)
        self.streams: list[_Stream] = []
        self._eos_ids = set(config.eos_ids())
        # Constrained decoding (cake_tpu/constrain): per-slot Guide
        # cursors advanced host-side between steps; their DFAs' packed
        # mask rows live concatenated in ONE device-resident uint8 table
        # (row 0 = all-ones for unconstrained streams) that the masked
        # decode program gathers from by the per-slot mask_row vector.
        # The table re-uploads only when a guide attaches; its row
        # capacity grows by doubling so the masked program compiles once
        # per pow2 shape (compile-count pinned by test).
        self._guides: dict[int, object] = {}       # slot -> Guide
        self._guide_rows: dict[int, int] = {}      # slot -> table base row
        self._mask_table = None                    # jnp [cap, ceil(V/8)] u8
        self.__masked = None                       # _pinned masked program
        self._masked_jit = None                    # raw jit (compile count)
        self._first_lp = None                      # first-token logprobs
        # Continuous-batching admission: arrivals queue here (enqueue) and
        # prefill ONE chunk per step() interleaved with decode dispatches,
        # as a single replicated row in a staging cache — no dp discarded
        # copies, no multi-dispatch stall of the running batch.
        # ``admit_chunk`` sets the per-dispatch chunk length (None: the
        # whole bucketed prompt in one dispatch). It must divide max_seq:
        # otherwise a near-window prompt rounds up PAST the window and the
        # final chunk's clamped dynamic_update_slice would silently
        # overwrite committed KV slots (wrong tokens, no error).
        if admit_chunk is not None and (
            admit_chunk < 1 or self.max_seq % admit_chunk
        ):
            raise ValueError(
                f"admit_chunk {admit_chunk} must be a positive divisor of "
                f"max_seq {self.max_seq} (a chunk round-up past the window "
                "would clamp-overwrite committed KV)"
            )
        self._admit_chunk = admit_chunk
        # Shared-prefix serving: when every prompt in a batch opens with
        # the same >= prefix_share_min tokens (the system-prompt case), the
        # prefix is prefilled once instead of once per stream (0 disables).
        self._prefix_share_min = max(0, prefix_share_min)
        self._arrivals: list[tuple[list[int], int]] = []
        self._staging: dict | None = None
        self.__admit_prefill = None
        self.__prefill_offset = None
        self.__broadcast_progs: dict = {}
        self.__splice = None  # slot-traced admission splice (one compile)
        self.__splice_small = None  # paged: sampler-state-only splice
        self._contiguous_cache = None  # set_prompts -> _pageify_batch hand-off
        # Generalized prefix store (slot layout): staged batch-1 KV rows
        # keyed by their token prefix in an explicit LRU
        # (kvpool.PrefixLRU). Populated by the set_prompts shared prefix
        # AND by every completed admission (its prefix truncated to a
        # prefix_block boundary), so arrivals with DIFFERENT system
        # prompts each hit their own cached prefix. A row may hold donor
        # KV past the match length — positions >= the match base are
        # beyond the reusing stream's causal frontier until its own
        # remainder prefill/decode overwrites them, the same
        # never-attendable invariant as bucketed-prefill padding. Entries
        # cost one batch-1 cache each; prefix_cache_entries caps HBM
        # (0 disables reuse). The paged layout replaces this whole-row
        # store with the page-granular shared-prefix tree (_prefix_tree):
        # hits SHARE physical pages via refcounts instead of copying a
        # staged row, and eviction is pool-pressure-driven.
        self._prefix_entries = max(0, prefix_cache_entries)
        self._prefix_store = PrefixLRU(self._prefix_entries)
        self._prefix_block = max(1, prefix_block)
        self._prefix_hits = 0
        # Batched n-gram speculation (spec_k > 0): each dispatch verifies
        # every live stream's K prompt-lookup proposals in ONE per-row
        # pass (pipeline.build_sharded_verify_rows) and banks the accepted
        # run — 1..K+1 tokens per stream per dispatch. Greedy streams stay
        # bit-identical to plain serving decode (the accept emits the same
        # repeat-penalized argmaxes); sampled streams are distribution-
        # identical via the per-row rejection-sampling accept. A row with
        # no proposal still advances exactly one token (-1 pads never
        # match), so the batched verify subsumes a plain decode step.
        self._spec_k = max(0, int(spec_k))
        self._spec_ngram = int(spec_ngram)
        self._spec_bank: list[list[int]] = []
        self._n_spec_dispatches = 0
        self._n_spec_chains = 0
        # Fused round chaining (spec_rounds > 1): per-round device programs
        # — device n-gram propose, the (mesh) verify, accept+state-update —
        # are dispatched back-to-back with NO host fetch between rounds;
        # banks are fetched once per chain. On a tunneled chip the
        # per-round host sync RTT (~200 ms measured r4) dominates the
        # verify forward itself, so chaining is the serving twin of the
        # single-stream fused scan (runtime/speculative.spec_rounds_fn).
        self._spec_rounds = max(1, int(spec_rounds))
        self._spec_ctx = None  # [B, max_seq] int32 device context rows
        self._spec_ctx_pos: np.ndarray | None = None  # host pos at sync
        self.__spec_propose = None
        self.__spec_update = None
        self.__verify_rows = None
        self.__verify_rows_il = None
        self.__accept_rows = None
        self.__prefill_pipelined = None
        # Serving observability (the worker-side ops/s + master tok/s story
        # of the reference, on the batch plane): dispatch and token
        # counters plus busy wall-clock, reported by stats().
        self._n_decode_dispatches = 0
        self._n_admit_dispatches = 0
        self._n_emitted = 0
        self._busy_s = 0.0
        self._t_start: float | None = None
        # per-instance obs instruments (Registry.publish pattern): stats()
        # percentiles must reflect THIS generator, not samples a
        # predecessor in the same process left in a shared series
        self._dispatch_hist = obs_metrics.Histogram("serve.decode_dispatch_ms")
        self._admit_hist = obs_metrics.Histogram("serve.admit_chunk_ms")
        self._emitted_ctr = obs_metrics.Counter("serve.tokens_emitted")
        obs_metrics.registry().publish(
            self._dispatch_hist, self._admit_hist, self._emitted_ctr)
        # engine profiling plane (obs/prof): sampled step-phase stamps +
        # the runtime retrace sentinel watching this engine's dispatches
        self._prof = obs_prof.profiler()
        self._sentinel = obs_prof.sentinel()
        self._sentinel.install()

    @property
    def _prefill_offset(self):
        """Offset prefill program (shared-prefix remainders), compiled on
        first use."""
        if self.__prefill_offset is None:
            self.__prefill_offset = self._pinned(build_sharded_prefill(
                self.config, self.plan, params_like=self.params,
                kv_quant=self.kv_quant, with_offset=True,
            ))
        return self.__prefill_offset

    def _prefill_shared_prefix(self, prefix: list[int], b: int) -> None:
        """Prefill the common prefix ONCE as a single replicated row (the
        admission-prefill program, chunked) and broadcast the staged KV
        into all ``b`` batch rows of ``self.cache``."""
        chunk = self._admission_chunk_for(len(prefix))
        t_pad = -(-len(prefix) // chunk) * chunk
        toks = np.zeros((1, t_pad), np.int32)
        toks[0, : len(prefix)] = prefix
        staging = init_cache_on_mesh(
            self.config, self.plan.mesh, batch=1, max_seq=self.max_seq,
            quant=self.kv_quant, batch_replicated=True,
        )
        for pos in range(0, t_pad, chunk):
            _, staging = self._admit_prefill(
                self.params, jnp.asarray(toks[:, pos: pos + chunk]),
                staging, jnp.int32(pos),
                jnp.asarray([max(0, len(prefix) - 1 - pos)], jnp.int32),
            )
            self._n_admit_dispatches += 1
        if self._paged:
            # the staged row's full pages become SHARED pool pages at
            # pageification (_pageify_batch) — keep the row until then
            self._staged_prefix = (list(prefix), staging)
        else:
            # keep the staged prefix row: arrivals opening with the same
            # prefix start from a copy of it instead of re-prefilling
            self._store_prefix(list(prefix), staging)
        self.cache = self._broadcast_prog(b)(staging)

    def _broadcast_prog(self, b: int):
        """Compiled prefix-row -> batch-cache broadcast, memoized per batch
        size (a fresh jit closure per call would retrace and recompile on
        every shared-prefix batch admission)."""
        prog = self.__broadcast_progs.get(b)
        if prog is None:
            from functools import partial

            from jax.sharding import NamedSharding, PartitionSpec
            from cake_tpu.parallel.mesh import cache_specs

            out_sh = jax.tree.map(
                lambda s: NamedSharding(self.plan.mesh, s),
                cache_specs(self.kv_quant),
                is_leaf=lambda x: isinstance(x, PartitionSpec),
            )

            @partial(jax.jit, out_shardings=out_sh)
            def prog(r):
                return jax.tree.map(
                    lambda x: jnp.broadcast_to(
                        x, (x.shape[0], b) + x.shape[2:]
                    ),
                    r,
                )

            self.__broadcast_progs[b] = prog
        return prog

    @property
    def _admit_prefill(self):
        """Admission-prefill program, compiled on first use (callers that
        never admit mid-run pay nothing)."""
        if self.__admit_prefill is None:
            self.__admit_prefill = self._pinned(build_admit_prefill(
                self.config, self.plan, params_like=self.params,
                kv_quant=self.kv_quant,
            ))
        return self.__admit_prefill

    @property
    def _verify_rows(self):
        """Per-row speculation-verification program, compiled on first use."""
        if self.__verify_rows is None:
            from cake_tpu.parallel.pipeline import build_sharded_verify_rows

            self.__verify_rows = self._pinned(build_sharded_verify_rows(
                self.config, self.plan, params_like=self.params,
                kv_quant=self.kv_quant,
            ))
        return self.__verify_rows

    def _pick_verify(self):
        """Serialized vs interleaved verification for this dispatch (the
        same schedule choice _pick_decode makes): interleaved needs
        num_stages > 1 and the dp-local batch divisible by the stage
        count; logits are bit-identical either way."""
        S = self.plan.num_stages
        if not self._interleave or S < 2:
            return self._verify_rows
        if (len(self.streams) // self.plan.dp) % S:
            return self._verify_rows
        if self.__verify_rows_il is None:
            from cake_tpu.parallel.pipeline import (
                build_interleaved_verify_rows,
            )

            self.__verify_rows_il = self._pinned(
                build_interleaved_verify_rows(
                    self.config, self.plan, params_like=self.params,
                    kv_quant=self.kv_quant,
                ))
        return self.__verify_rows_il

    @property
    def _accept_rows(self):
        """Batched accept scan (greedy exact-match or rejection sampling),
        jitted on first use."""
        if self.__accept_rows is None:
            from functools import partial

            from cake_tpu.runtime.speculative import (
                accept_fn_rows,
                accept_sampled_fn_rows,
            )

            eos = jnp.asarray(sorted(self._eos_ids) or [-1], jnp.int32)
            accept = (accept_fn_rows if self.settings.greedy
                      else accept_sampled_fn_rows)
            self.__accept_rows = jax.jit(partial(
                accept, eos_ids=eos, settings=self.settings))
        return self.__accept_rows

    @staticmethod
    def _host(x) -> np.ndarray:
        """Device->host fetch that stays valid when the dp axis spans
        PROCESSES (multi-host serving): every host runs the identical
        serving loop and needs the full row for emission bookkeeping, so a
        non-fully-addressable array is process_allgather'd (these are tiny
        [B]-shaped token/count arrays)."""
        try:
            return np.asarray(x)
        except RuntimeError:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x,
                                                                tiled=True))

    def _pinned(self, fn):
        """Wrap a compiled program so every dispatch — and therefore its
        trace, which happens on first call — runs under this instance's
        pinned int8 matmul backend (``quant.pinned_impl``). A no-op until
        the pin is decided and for bf16 weights."""
        def wrapped(*args):
            with quant.pinned_impl(self._quant_pin):
                return fn(*args)
        return wrapped

    # -- constrained decoding (cake_tpu/constrain) ---------------------------
    @property
    def eos_ids(self) -> frozenset:
        """Public EOS-id surface of the engine facade — what the serve
        scheduler maps finish reasons with (no private-attr reaches)."""
        return frozenset(self._eos_ids)

    @property
    def _decode_single_masked(self):
        """The constrained single-step decode program, compiled on first
        use (unconstrained serving never pays for it). ``_masked_jit``
        keeps the raw jitted callable so tests can pin its compile count
        — exactly one compile per (batch, table-capacity) shape."""
        if self.__masked is None:
            self._masked_jit = build_sharded_decode(
                self.config, self.settings, self.plan,
                params_like=self.params, per_row=True,
                kv_quant=self.kv_quant, masked=True,
                logprobs_k=self.logprobs_k, paged=self._paged,
            )
            self.__masked = self._pinned(self._masked_jit)
        return self.__masked

    def _check_guide_ok(self, guide) -> None:
        """Constraint-compatibility gate, raised where callers can turn
        it into a client error (enqueue / set_prompts) — NOT on the
        engine thread mid-step, where it would read as an engine fault
        and drain the server."""
        if guide is not None and self._spec_k:
            raise ValueError(
                "constrained decoding does not compose with batched "
                "speculation (spec_k): the fused verify rounds cannot "
                "advance the host-side DFA between tokens")

    def _attach_guide(self, slot: int, guide, rebuild: bool = True) -> None:
        """Bind a Guide to a batch slot and (by default) refresh the
        device mask table. Engine-thread only (like every other
        mutation); batch attachers pass rebuild=False and rebuild once."""
        self._check_guide_ok(guide)
        guide.reset()
        self._guides[slot] = guide
        if rebuild:
            self._rebuild_mask_table()

    def _drop_guide(self, slot: int) -> None:
        self._guides.pop(slot, None)
        self._guide_rows.pop(slot, None)
        # stale table rows are simply never referenced again; the table
        # re-packs at the next attach

    def _rebuild_mask_table(self) -> None:
        """Re-pack every attached guide's DFA mask rows into one device
        table: [row 0 = all-ones] + each guide's block. One host->device
        upload per ATTACH, never per token; capacity doubles so the
        masked program's traced shape is stable across attachments."""
        v8 = (self.config.vocab_size + 7) // 8
        blocks = [np.full((1, v8), 0xFF, np.uint8)]
        base = 1
        self._guide_rows = {}
        for slot in sorted(self._guides):
            bits = self._guides[slot].dfa.mask_bits
            self._guide_rows[slot] = base
            blocks.append(bits)
            base += bits.shape[0]
        cap = _MASK_CAP0
        while cap < base:
            cap *= 2
        table = np.zeros((cap, v8), np.uint8)
        table[:base] = np.concatenate(blocks)
        self._mask_table = jnp.asarray(table)

    def _guides_live(self) -> bool:
        return any(
            self.streams[i].active and not self.streams[i].done
            for i in self._guides
        )

    def _mask_rows_np(self) -> np.ndarray:
        """Per-slot mask-row vector for the next dispatch: row 0
        (all-ones) for unconstrained/done slots, the guide's current
        DFA-state row otherwise."""
        rows = np.zeros((len(self.streams),), np.int32)
        for slot, g in self._guides.items():
            s = self.streams[slot]
            if s.active and not s.done:
                rows[slot] = self._guide_rows[slot] + g.state
        return rows

    def _first_mask(self, b: int):
        """[B, V] bool constraint mask for the post-prefill first-token
        sampling (host-path), or None when no stream is constrained."""
        if not self._guides:
            return None
        mask = np.ones((b, self.config.vocab_size), bool)
        for slot, g in self._guides.items():
            mask[slot] = g.mask_bool()
        return jnp.asarray(mask)

    def _advance_guide(self, slot: int, s: _Stream, tok_id: int) -> None:
        """Host-side DFA advance for one emitted token; a dead end (no
        emittable token at the new state, not even EOS) retires the
        stream with end_reason 'constraint'."""
        g = self._guides.get(slot)
        if g is None:
            return
        # "guide" nests inside "emit" — sub-phase attribution, not
        # additional step time (obs/prof module doc)
        with self._prof.phase("guide"):
            if s.done:
                self._drop_guide(slot)
                return
            if not g.advance(tok_id) or g.dead_end:
                from cake_tpu.constrain.guide import DEAD_ENDS

                s.done = True
                s.end_reason = "constraint"
                self._drop_guide(slot)
                DEAD_ENDS.inc()

    def warm_constrain(self) -> None:
        """Compile the masked decode program against the live batch
        shapes outside the serving window (same contract as
        ``warm_blocks``/``warm_admission``: the first constrained request
        must not pay XLA compilation mid-serving). Uses a sacrificial
        cache copy; live state untouched."""
        if not self.streams:
            raise RuntimeError("set_prompts first")
        table = self._mask_table
        if table is None:
            v8 = (self.config.vocab_size + 7) // 8
            t = np.zeros((_MASK_CAP0, v8), np.uint8)
            t[0] = 0xFF
            table = jnp.asarray(t)
            self._mask_table = table
        cache = jax.tree.map(lambda x: x.copy(), self.cache)
        out = self._decode_single_masked(
            self.params, self._last_tokens, cache, jnp.asarray(self._pos),
            self._keys, self._history, self._hist_slot,
            jnp.asarray(self._index), table,
            jnp.zeros((len(self.streams),), jnp.int32),
            *self._paged_args_warm(1),
        )
        jax.block_until_ready(out)

    # -- prompt intake -------------------------------------------------------
    def _encode(self, p) -> list[int]:
        """Tokenize/validate one prompt (the shared single-stream
        set_prompt rules: BOS prepend, non-empty, fits the window, ids in
        vocab range — ``generator.encode_prompt``)."""
        return encode_prompt(p, self.tokenizer, self.config, self.max_seq)

    def set_prompts(
        self,
        prompts: list[list[int] | str],
        stream_ids: list[int] | None = None,
        guides: list | None = None,
    ) -> None:
        """Admit a batch of prompts. ``stream_ids`` pin each stream's
        sampling-key identity (default: its index) — the handle that makes a
        stream reproducible in any batch composition. ``guides`` (optional,
        aligned with ``prompts``; None entries = unconstrained) attach a
        constrain.Guide per stream — its grammar masks every sampling step
        including this call's first token."""
        self._domain_stamp.check("BatchGenerator.set_prompts")
        if not prompts:
            raise ValueError("empty batch")
        ids_list = [self._encode(p) for p in prompts]
        if stream_ids is None:
            stream_ids = list(range(len(ids_list)))
        if len(stream_ids) != len(ids_list):
            raise ValueError("stream_ids/prompts length mismatch")
        if guides is not None and len(guides) != len(ids_list):
            raise ValueError("guides/prompts length mismatch")
        if self._paged and self._imports:
            # the pool is rebuilt below (_init_pool): pending KV imports
            # reference pages of the OLD pool and cannot survive
            for xid in list(self._imports):
                self.import_abort(xid)
        self._guides = {}
        self._guide_rows = {}

        # pad the batch to a dp multiple with inactive dummies (they compute,
        # they are never emitted)
        n_active = len(ids_list)
        dp = self.plan.dp
        batch = -(-n_active // dp) * dp
        if self._quant_pin is None:
            # instance-lifetime backend choice, decided before any program
            # traces so every bucket and admission path sees the same
            # backend. int8: the measured m>=16 crossover (BASELINE.md r2).
            # int4: the kernel wins at every geometry (the XLA fallback
            # streams 4x the packed bytes — ops/quant.py), so pin pallas
            # unconditionally.
            self._quant_pin = (
                "pallas"
                if self._params_int4 or batch // dp >= 16
                else "xla"
            )
        self.streams = [
            _Stream(
                stream_id=sid, prompt=ids,
                detok=TokenOutputStream(self.tokenizer)
                if self.tokenizer else None,
            )
            for sid, ids in zip(stream_ids, ids_list)
        ]
        for _ in range(batch - n_active):
            self.streams.append(
                _Stream(stream_id=-1, prompt=list(ids_list[0]), active=False)
            )
        b = len(self.streams)
        if guides is not None:
            for i, g in enumerate(guides):
                if g is not None:
                    self._attach_guide(i, g, rebuild=False)
            if self._guides:
                self._rebuild_mask_table()  # one repack+upload per batch

        # (the prefix store survives set_prompts: rows depend only on
        # params/config, both fixed for the instance's lifetime)
        # Shared-prefix detection: a common system prompt is prefilled ONCE
        # (single replicated row) and broadcast into every stream's cache
        # rows; only the per-stream remainders go through the batched
        # prefill, at offset lcp. Capped one short of the shortest prompt so
        # every row keeps >= 1 remainder token. Bit-identical output —
        # positions and tokens are unchanged, only the redundancy goes.
        lcp = 0
        if b > 1 and self._prefix_share_min:
            first = self.streams[0].prompt
            lcp = min(len(s.prompt) for s in self.streams) - 1
            for i in range(lcp):
                if any(s.prompt[i] != first[i] for s in self.streams):
                    lcp = i
                    break
            if lcp < self._prefix_share_min:
                lcp = 0

        # shared prompt bucket; per-stream true positions (remainder-
        # relative when a prefix is shared). The remainder bucket is capped
        # at the room left above the prefix: a write at offset lcp must
        # never extend past max_seq, or the clamped dynamic_update_slice
        # would silently overwrite committed prefix KV (the same failure
        # the admit_chunk divisibility check prevents on the admission
        # path). The cap still covers every remainder (n_max < max_seq).
        n_max = max(len(s.prompt) for s in self.streams)
        t_pad = min(_bucket(n_max - lcp, self.max_seq), self.max_seq - lcp)
        if self.plan.sp > 1 and lcp == 0 and t_pad % self.plan.sp:
            # sp prefill shards the bucket over the ring: round up to a
            # multiple of sp (junk slots stay beyond every frontier). The
            # shared-prefix remainder path (lcp > 0) runs chunk-replicated
            # over sp instead — no divisibility requirement, and rounding
            # up could push the bucket past max_seq - lcp.
            t_pad = min(-(-t_pad // self.plan.sp) * self.plan.sp,
                        self.max_seq)
        tokens = np.zeros((b, t_pad), np.int32)
        last = np.zeros((b,), np.int32)
        for i, s in enumerate(self.streams):
            rem = s.prompt[lcp:]
            tokens[i, : len(rem)] = rem
            last[i] = len(rem) - 1
        self._pos = np.asarray([len(s.prompt) for s in self.streams], np.int32)

        # per-stream keys + histories seeded with each prompt's tail
        keys = [
            jax.random.fold_in(self._base_key, max(s.stream_id, 0))
            for s in self.streams
        ]
        self._keys = jnp.stack(keys)  # [B, 2] uint32
        n_hist = self.settings.repeat_last_n
        hist = np.full((b, n_hist), -1, np.int32)
        slots = np.zeros((b,), np.int32)
        for i, s in enumerate(self.streams):
            tail = s.prompt[-n_hist:]
            hist[i, : len(tail)] = tail
            slots[i] = len(tail)
        self._history = jnp.asarray(hist)
        self._hist_slot = jnp.asarray(slots)

        self._n_decode_dispatches = 0
        self._n_admit_dispatches = 0
        self._n_emitted = 0
        self._busy_s = 0.0
        self._t_start = time.perf_counter()
        if lcp:
            # broadcast of the staged prefix row IS the batch cache
            self._prefill_shared_prefix(first[:lcp], b)
            logits, self.cache = self._prefill_offset(
                self.params, jnp.asarray(tokens), self.cache,
                jnp.asarray(last), jnp.int32(lcp),
            )
        else:
            self.cache = init_cache_on_mesh(
                self.config, self.plan.mesh, batch=b, max_seq=self.max_seq,
                quant=self.kv_quant,
            )
            logits, self.cache = self._pick_prefill(tokens.shape[1])(
                self.params, jnp.asarray(tokens), self.cache,
                jnp.asarray(last)
            )

        # first token per stream: fold_in(stream_key, 0) — the same absolute
        # token-index schedule the in-program decode steps continue
        keys0 = jax.vmap(lambda k: jax.random.fold_in(k, 0))(self._keys)
        toks = sampling.sample_tokens_keyed(
            logits, keys0, self._history, self.settings,
            mask=self._first_mask(b),
        )
        self._first_lp = None
        if self.logprobs_k:
            lpv, lpi = sampling.topk_logprobs(logits, self.logprobs_k)
            self._first_lp = (self._host(lpv), self._host(lpi))
        self._history, self._hist_slot = sampling.push_history_batched(
            self._history, self._hist_slot, toks
        )
        self._last_tokens = toks.astype(jnp.int32)
        # per-stream absolute token index of the NEXT token (per-row so a
        # stream admitted later starts its own schedule at 1)
        self._index = np.ones((b,), np.int32)
        self._emitted_first = False
        # deque of [B] token rows: the per-step pop is O(1), not list.pop(0)
        self._block_buf: deque[np.ndarray] = deque()
        self._spec_bank = [[] for _ in self.streams]
        self._spec_ctx = None  # fresh prompts: device ctx rows are stale
        self._spec_ctx_pos = None
        # emission rows already recorded (admit() flushing the block buffer)
        # but not yet handed to a step() caller
        self._pending_rows: list[list[Token | None]] = []
        self._inflight = None  # any prior in-flight block is stale now
        if self._paged:
            # hand the freshly prefilled contiguous cache to the pool:
            # from here on self.cache IS the page array and every decode
            # dispatch addresses it through the per-stream page tables
            self._contiguous_cache = self.cache
            self._pageify_batch(
                lcp, self.streams[0].prompt[:lcp] if lcp else [])
        if getattr(self, "_splice_warm_pending", False):
            # warm_admission ran before this set_prompts; the splice warm
            # needs the batch state that only now exists
            self._splice_warm_pending = False
            self._warm_splice()

    def _free_slot(self) -> int | None:
        return next(
            (i for i, s in enumerate(self.streams) if not s.active or s.done),
            None,
        )

    def enqueue(self, prompt, stream_id: int, guide=None) -> None:
        """Queue a prompt for continuous admission. Each subsequent
        ``step()`` advances its prefill by ONE chunk dispatch (a single
        replicated row into a staging cache) alongside the running batch's
        decode dispatch — arrivals never stall the batch for a full prompt
        pass. When the prefill completes, the stream's first token is
        emitted in that step's row and the stream joins the batch. Output
        is bit-identical to the same (seed, stream_id, prompt) in any other
        batch or admission timing (per-row positions + per-row token
        indices). Composes with ``sp > 1`` (r5): the staged row's chunks
        run replicated over sp against the sequence-sharded staging cache
        (owner-masked range writes + the chunk attend,
        pipeline.build_admit_prefill). ``guide`` (a constrain.Guide)
        attaches grammar-constrained decoding to the stream: its mask
        applies from the admission's first sampled token on. Guide
        compatibility is checked HERE (a serve scheduler turns the
        ValueError into a 400) rather than at attach time on the engine
        thread (where it would read as an engine fault)."""
        self._domain_stamp.check("BatchGenerator.enqueue")
        self._check_guide_ok(guide)
        self._arrivals.append((self._encode(prompt), stream_id, guide, None))

    @property
    def paged(self) -> bool:
        """Paged KV layout (the disagg plane's capability gate: KV moves
        between engines as pool pages)."""
        return self._paged

    def pending_admissions(self) -> int:
        """Arrivals not yet fully admitted (queued + in-flight)."""
        return len(self._arrivals) + (1 if self._staging is not None else 0)

    def _store_prefix(self, ids: list[int], row) -> None:
        """Slot layout: insert a staged batch-1 KV row under its token
        prefix, LRU-capped at ``prefix_cache_entries`` rows (the
        eviction policy lives in :class:`cake_tpu.kvpool.PrefixLRU`)."""
        if self._prefix_entries <= 0 or len(ids) < self._prefix_share_min:
            return
        self._prefix_store.put(tuple(ids), row)

    def _match_prefix(self, ids: list[int]):
        """Slot layout: longest stored prefix STRICTLY shorter than the
        prompt (at least one remainder token must produce the first-token
        logits). Returns ``(base, row)``; a hit becomes LRU-most-recent."""
        return self._prefix_store.match(ids)

    # -- paged KV layout (cake_tpu/kvpool) -----------------------------------
    def _init_pool(self, b: int) -> None:
        """(Re)build the page pool for a ``b``-row batch: the device page
        array, the host free-list/refcounts, and a fresh prefix tree.
        Sizing guarantees mid-decode allocation can NEVER fail: with
        ``pages >= b * pages_per_stream + 1`` (sink included), live
        streams can all fill their windows and the only other claims —
        prefix-tree nodes — are evictable."""
        ps = self._page_size
        need = b * self._ppp + 1
        pages = self._pool_pages_req
        if pages is None:
            want = need + 2 * self._ppp  # headroom: tree-held warm prefixes
            pages = 1 << (want - 1).bit_length()
        if pages < need:
            raise ValueError(
                f"kv_pool_pages {pages} < {need} required for batch {b} x "
                f"{self._ppp} pages/stream + sink: a live batch could "
                "exhaust the pool mid-decode")
        self._pagepool = PagePool(pages, ps)
        # the pool shares its engine's domain stamp: page claims are
        # engine-thread mutations wherever they happen
        self._pagepool._domain_stamp = self._domain_stamp
        self._prefix_tree = PrefixTree(self._pagepool)
        self._tables = [[] for _ in range(b)]
        self._page_map_dev = None
        self.cache = kvpool_pool.init_pool_on_mesh(
            self.config, self.plan.mesh, pages, ps, self.kv_quant)
        mesh = self.plan.mesh
        self._row_gather = kvpool_pool.row_gather_prog(
            self.config, mesh, self.kv_quant)
        self._row_scatter = kvpool_pool.row_scatter_prog(
            self.config, mesh, self.kv_quant)
        self._batch_scatter = kvpool_pool.batch_scatter_prog(
            self.config, mesh, self.kv_quant)

    def _alloc_page(self) -> int:
        """One free page, evicting prefix-tree claims under pressure (the
        tree is a cache; live streams are not)."""
        try:
            return self._pagepool.alloc()
        except PoolExhausted:
            if self._prefix_tree.evict_until_free(1):
                return self._pagepool.alloc()
            raise

    def _release_pages(self, slot: int) -> None:
        """Retire a slot's page claims — the whole KV free is this loop
        over a host list (pages shared with the prefix tree or other
        streams survive until their last reference drops)."""
        if not self._paged or slot >= len(self._tables):
            return
        if self._tables[slot]:
            self._page_map_dev = None
        for pid in self._tables[slot]:
            self._pagepool.unref(pid)
        self._tables[slot] = []

    def _ensure_pages(self, size: int) -> None:
        """Grow each live stream's page table to cover the ``size``
        positions this dispatch writes — the one allocation point of the
        steady-state decode path (a handful of list appends per page
        boundary crossed; no device work)."""
        ps = self._page_size
        for i, s in enumerate(self.streams):
            if not s.active or s.done:
                continue
            t = self._tables[i]
            last = min(int(self._pos[i]) + size - 1, self.max_seq - 1) // ps
            while len(t) <= last:
                t.append(self._alloc_page())
                self._page_map_dev = None

    def _page_map_np(self) -> np.ndarray:
        """[B, pages_per_stream] logical->physical map, sink-padded past
        each stream's allocated frontier."""
        m = np.full((len(self.streams), self._ppp), SINK, np.int32)
        for i, t in enumerate(self._tables):
            if t:
                m[i, : len(t)] = t
        return m

    def _scatter_ids_np(self, size: int) -> np.ndarray:
        """[B, W] physical pages receiving this dispatch's KV writes:
        the pages covering ``[pos, pos+size)`` per live row, the sink for
        retired/dummy rows and in-page overrun slots (their writes are
        discarded garbage either way — same invariant as the slot
        layout's clamped overrun writes)."""
        ps = self._page_size
        w = kvpool_pool.writeback_width(size, ps, self._ppp)
        ids = np.full((len(self.streams), w), SINK, np.int32)
        for i, s in enumerate(self.streams):
            if not s.active or s.done:
                continue
            t = self._tables[i]
            pos = int(self._pos[i])
            first = min(pos // ps, self._ppp - w)
            last = min(pos + size - 1, self.max_seq - 1) // ps
            for j in range(w):
                p = first + j
                if first + j <= last and p < len(t):
                    ids[i, j] = t[p]
        return ids

    def _paged_args(self, size: int) -> tuple:
        """The two extra decode operands of the paged layout (empty in
        slot mode, so dispatch sites splat unconditionally). Allocates
        the pages the dispatch will write first. The page map re-uploads
        only when a table actually changed (admission, retirement, page
        growth) — steady-state dispatches reuse the device array; the
        tiny [B, W] scatter-id vector is genuinely per-dispatch."""
        if not self._paged:
            return ()
        # "pages" nests inside "dispatch" (host prep on the dispatch path)
        with self._prof.phase("pages"):
            self._ensure_pages(size)
            if self._page_map_dev is None:
                self._page_map_dev = jnp.asarray(self._page_map_np())
            return (self._page_map_dev,
                    jnp.asarray(self._scatter_ids_np(size)))

    def _paged_args_warm(self, size: int) -> tuple:
        """Warm-path variant: current page map, all-sink write-back (the
        warm dispatch must not allocate pages or touch live content)."""
        if not self._paged:
            return ()
        w = kvpool_pool.writeback_width(size, self._page_size, self._ppp)
        return (jnp.asarray(self._page_map_np()),
                jnp.zeros((len(self.streams), w), jnp.int32))

    def _pageify_batch(self, lcp: int, prefix_ids: list[int]) -> None:
        """Move a freshly prefilled contiguous batch cache into pool
        pages (set_prompts only — every later admission writes pages
        directly). Full pages of a shared prefix become ONE physical copy
        referenced by every stream + the prefix tree; each stream's
        unaligned boundary page (prefix tail + its own remainder) is a
        private copy-on-write materialization."""
        ps = self._page_size
        b = len(self.streams)
        self._init_pool(b)
        pool, contiguous = self.cache, self._contiguous_cache
        n_full = lcp // ps
        shared: list[int] = []
        if n_full:
            _, staging = self._staged_prefix
            ids_vec = np.zeros((self._ppp,), np.int32)
            shared = [self._pagepool.alloc() for _ in range(n_full)]
            # the pages are held only by this local until the per-stream
            # tables take their refs below — release them on the error
            # path (cakelint CK-CLAIM: the scatter dispatch can raise,
            # and stranded alloc claims would pin pool pages forever)
            try:
                ids_vec[:n_full] = shared
                pool = self._row_scatter(pool, staging,
                                         jnp.asarray(ids_vec))
                if self._prefix_entries > 0:
                    # register for future ADMISSION reuse only when the
                    # prefix cache is enabled (0 disables it, same
                    # contract as the slot store) — the batch itself
                    # still shares the physical pages either way, and
                    # without the tree claim they free when the last
                    # sharer retires
                    self._prefix_tree.insert(prefix_ids[: n_full * ps],
                                             shared)
            except BaseException:
                for pid in shared:
                    self._pagepool.unref(pid)
                raise
        self._staged_prefix = None
        ids = np.zeros((b * self._ppp,), np.int32)
        cow = 0
        for i, s in enumerate(self.streams):
            if not s.active:
                continue
            for pid in shared:
                self._pagepool.ref(pid)
            t = list(shared)
            last_page = (len(s.prompt) - 1) // ps
            for p in range(n_full, last_page + 1):
                pid = self._alloc_page()
                t.append(pid)
                ids[i * self._ppp + p] = pid
            if lcp % ps and last_page >= n_full:
                cow += 1  # boundary page: private copy of shared tail
            self._tables[i] = t
        for pid in shared:
            self._pagepool.unref(pid)  # hand the alloc claim off
        if cow:
            self._pagepool.count_cow(cow)
        self.cache = self._batch_scatter(pool, contiguous, jnp.asarray(ids))
        self._contiguous_cache = None

    def _splice_small_fn(self):
        """The paged admission splice: only the per-stream sampler state
        (keys/history/ring slots/feedback token) splices — KV moved by
        the page write-back (``row_scatter``), never by a cache-sized
        scatter. Slot index traced; compiles once."""
        if self.__splice_small is None:
            def splice(keys, history, hist_slot, last, key, hist_row,
                       hist_used, tok, slot):
                upd1 = lambda buf, v: jax.lax.dynamic_update_index_in_dim(
                    buf, v, slot, 0)
                return (
                    upd1(keys, key),
                    upd1(history, hist_row),
                    upd1(hist_slot, hist_used),
                    upd1(last, tok),
                )

            self.__splice_small = jax.jit(splice)
        return self.__splice_small

    # -- KV-page export/import (cake_tpu/disagg) -----------------------------
    def _disagg_fingerprint(self) -> dict:
        """Geometry a snapshot must match to land in this engine's pool
        (the import-side twin of the worker handshake's max_seq check)."""
        cfg = self.config
        return {
            "layers": cfg.num_hidden_layers,
            "kv_heads": cfg.num_key_value_heads,
            "head_dim": cfg.head_dim,
            "dtype": str(cfg.dtype),
            "kv_quant": self.kv_quant,
            "page_size": self._page_size,
            "max_seq": self.max_seq,
            "vocab": cfg.vocab_size,
            "repeat_last_n": self.settings.repeat_last_n,
        }

    def _require_paged(self, what: str) -> None:
        if not self._paged:
            raise ValueError(
                f"{what} needs kv_layout='paged': KV moves between "
                "engines as pool pages (construct with kv_layout='paged' "
                "/ --kv-layout paged)")

    def export_stream(self, stream_id: int, codec: str = "none",
                      trace: dict | None = None) -> bytes:
        """Snapshot a LIVE stream's KV pages + sampler/cursor state into
        versioned, self-describing bytes (cake_tpu/disagg/snapshot) —
        the suspend half of session suspend/resume and the payload the
        prefill tier ships to a decode replica. Engine-thread only.

        Buffered device rows are emitted first (the snapshot must
        reflect the emitted state, not a mid-block one); the stream
        itself keeps running — callers that hand the stream off call
        ``finish(stream_id)`` after. Pages are PINNED for the gather
        (kvpool pin/unpin: a claim outside stream tables and the prefix
        tree), so nothing — not an eviction storm, not the stream
        retiring mid-call — can free one mid-export. ``codec`` rides
        each page through the wire activation codec (``--wire-codec``);
        round trips are bit-identical whenever the codec is lossless for
        the cache dtype (none always; bf16 on a bf16 cache; int8 on an
        int8-quantized pool). ``trace`` (an ``obs.reqtrace`` wire dict)
        rides the snapshot's JSON metadata so the importing tier joins
        the request's trace."""
        from cake_tpu.disagg import snapshot as _snapshot

        self._domain_stamp.check("BatchGenerator.export_stream")
        self._require_paged("export_stream")
        self._drain_buffered_rows()
        slot = next(
            (i for i, s in enumerate(self.streams)
             if s.active and not s.done and s.stream_id == stream_id),
            None)
        if slot is None:
            raise ValueError(f"no live stream with id {stream_id}")
        s = self.streams[slot]
        ps = self._page_size
        n_kv = int(self._pos[slot])
        n_pages = (n_kv - 1) // ps + 1
        table = self._tables[slot][:n_pages]
        guide = self._guides.get(slot)
        guide_spec = getattr(guide, "spec", None) if guide else None
        if guide is not None and guide_spec is None:
            raise ValueError(
                "cannot export a constrained stream whose Guide carries "
                "no grammar spec (build it via constrain.guide_for, or "
                "Guide(dfa, spec=...)) — the importer must recompile "
                "the DFA to resume the cursor")
        import uuid

        for pid in table:
            self._pagepool.pin(pid)
        try:
            ids_vec = np.zeros((self._ppp,), np.int32)
            ids_vec[:n_pages] = table
            staging = self._row_gather(self.cache, jnp.asarray(ids_vec))
            host = jax.tree.map(np.asarray, staging)
        finally:
            for pid in table:
                self._pagepool.unpin(pid)
        pages = []
        for j in range(n_pages):
            lo, hi = j * ps, (j + 1) * ps
            if self.kv_quant == "int8":
                pages.append({
                    "kq": host.k.q[:, 0, :, lo:hi],
                    "ks": host.k.scale[:, 0, :, lo:hi],
                    "vq": host.v.q[:, 0, :, lo:hi],
                    "vs": host.v.scale[:, 0, :, lo:hi],
                })
            else:
                pages.append({"k": host.k[:, 0, :, lo:hi],
                              "v": host.v[:, 0, :, lo:hi]})
        data = _snapshot.encode_snapshot(
            xfer_id=uuid.uuid4().hex,
            fingerprint=self._disagg_fingerprint(),
            codec=codec,
            stream_id=s.stream_id,
            prompt=s.prompt,
            generated=s.generated,
            pos=n_kv,
            index=int(self._index[slot]),
            last_token=int(self._last_tokens[slot]),
            key=np.asarray(self._keys[slot]),
            history=np.asarray(self._history[slot]),
            hist_slot=int(self._hist_slot[slot]),
            guide_spec=guide_spec,
            guide_state=guide.state if guide is not None else 0,
            pages=pages,
            trace=trace,
        )
        # the original stream id rides along so a same-seed resume can
        # keep the identity (the raw key above is what bit-identity
        # actually needs — it survives differing seeds/sids)
        _EXPORTS.inc()
        return data

    def import_begin(self, data) -> dict:
        """Parse + register an inbound snapshot (engine-thread only).
        Validation — magic/version/layout, model fingerprint — happens
        HERE, so a transfer listener can ACK/REJECT before the pages
        land; the pool work itself queues as an arrival in the SAME FIFO
        as prompt admissions (pool pressure defers it FIFO-fair, never
        drops it). Idempotent by transfer id: a duplicate send (retry
        after a lost ACK) returns the existing registration. Returns the
        resume metadata ``{"xfer_id", "stream_id", "prompt",
        "generated", "texts", "n_kv"}`` (``texts`` = the incremental
        detok replay of the generated tokens, what a serve session
        replays to its client)."""
        from cake_tpu.disagg import snapshot as _snapshot

        self._domain_stamp.check("BatchGenerator.import_begin")
        self._require_paged("import_begin")
        if not self.streams:
            raise RuntimeError("set_prompts first")
        snap = _snapshot.decode_snapshot(data)
        if snap.xfer_id in self._imports:
            return self._imports[snap.xfer_id]["meta"]
        snap.check_fingerprint(self._disagg_fingerprint())
        ps = self._page_size
        if snap.n_pages != (snap.pos - 1) // ps + 1:
            raise _snapshot.SnapshotError(
                f"snapshot carries {snap.n_pages} pages for pos "
                f"{snap.pos} at page_size {ps}")
        if not 0 < snap.pos < self.max_seq:
            raise _snapshot.SnapshotError(
                f"snapshot pos {snap.pos} outside (0, {self.max_seq}) — "
                "only live streams export")
        shapes = self._page_shapes()
        for page in snap.pages:
            for k, want in shapes.items():
                got = page.get(k)
                if got is None or got.shape != want[0] \
                        or got.dtype != want[1]:
                    raise _snapshot.SnapshotError(
                        f"page tensor {k!r} is "
                        f"{None if got is None else (got.shape, got.dtype)}"
                        f", expected {want}")
        if snap.guide_spec is not None and self.tokenizer is None:
            raise _snapshot.SnapshotError(
                "snapshot carries a constrained-decoding cursor but this "
                "engine has no tokenizer to recompile its grammar")
        detok = TokenOutputStream(self.tokenizer) if self.tokenizer \
            else None
        texts = [detok.next_token(t) if detok is not None else None
                 for t in snap.generated]
        meta = {
            "xfer_id": snap.xfer_id,
            "stream_id": snap.stream_id,
            "prompt": list(snap.prompt),
            "generated": list(snap.generated),
            "texts": texts,
            "n_kv": snap.pos,
        }
        if snap.trace:
            # the exporter's request-trace context (obs/reqtrace) —
            # surfaced so the scheduler can land a disagg.import span in
            # the same causal tree
            meta["trace"] = snap.trace
        self._imports[snap.xfer_id] = {
            "snap": snap, "pages": None, "detok": detok, "meta": meta,
            "deferred": False, "t": time.monotonic(),
        }
        self._arrivals.append((snap.xfer_id, None, None, _ARR_IMPORT))
        return meta

    def _page_shapes(self) -> dict:
        """Expected (shape, dtype) per page tensor for this geometry."""
        cfg = self.config
        L, KH, D = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                    cfg.head_dim)
        ps = self._page_size
        if self.kv_quant == "int8":
            return {
                "kq": ((L, KH, ps, D), np.dtype(np.int8)),
                "ks": ((L, KH, ps), np.dtype(np.float32)),
                "vq": ((L, KH, ps, D), np.dtype(np.int8)),
                "vs": ((L, KH, ps), np.dtype(np.float32)),
            }
        dt = np.dtype(cfg.jax_dtype)
        return {"k": ((L, KH, ps, D), dt), "v": ((L, KH, ps, D), dt)}

    def _import_begin_tick(self) -> None:
        """Head-of-queue import: land its pages in the pool, or defer
        FIFO-fair under pool pressure (the arrival stays at the head,
        re-priced next tick — same discipline as a prompt admission)."""
        xid = self._arrivals[0][0]
        rec = self._imports.get(xid)
        if rec is None:  # aborted while queued
            self._arrivals.pop(0)
            return
        snap = rec["snap"]
        need = snap.n_pages
        if (self._pagepool.free_count < need
                and not self._prefix_tree.evict_until_free(need)):
            if not rec["deferred"]:
                rec["deferred"] = True
                self._pagepool.count_defer()
            self._admit_deferred = True
            return
        self._admit_deferred = False
        self._arrivals.pop(0)
        staging = self._import_staging(snap)
        pages = []
        for _ in range(need):
            pid = self._alloc_page()
            # reclassify the alloc claim as a transfer PIN: until a
            # stream attaches (or the import aborts), these pages are
            # held by neither a stream table nor the prefix tree, and
            # must still survive any eviction storm
            self._pagepool.pin(pid)
            self._pagepool.unref(pid)
            pages.append(pid)
        # the import record owns the pins from HERE (cakelint CK-CLAIM):
        # if the scatter dispatch below raises, import_abort / the TTL
        # sweep can still unpin — pins held only by the local would leak
        # forever
        rec["pages"] = pages
        ids_vec = np.zeros((self._ppp,), np.int32)
        ids_vec[:need] = pages
        self.cache = self._row_scatter(self.cache, staging,
                                       jnp.asarray(ids_vec))
        _IMPORTS.inc()

    def _import_staging(self, snap) -> object:
        """Snapshot pages -> the batch-1 staging cache ``row_scatter``
        scatters from (host assembly + one upload; positions past the
        snapshot's pages stay zero — beyond the resumed frontier, never
        attendable)."""
        from cake_tpu.ops.kvcache import KVCache, QuantizedKV

        cfg = self.config
        L, KH, D = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                    cfg.head_dim)
        S, ps = self.max_seq, self._page_size
        if self.kv_quant == "int8":
            bufs = {"kq": np.zeros((L, 1, KH, S, D), np.int8),
                    "ks": np.zeros((L, 1, KH, S), np.float32),
                    "vq": np.zeros((L, 1, KH, S, D), np.int8),
                    "vs": np.zeros((L, 1, KH, S), np.float32)}
        else:
            dt = np.dtype(cfg.jax_dtype)
            bufs = {"k": np.zeros((L, 1, KH, S, D), dt),
                    "v": np.zeros((L, 1, KH, S, D), dt)}
        for j, page in enumerate(snap.pages):
            lo, hi = j * ps, (j + 1) * ps
            for k, arr in page.items():
                bufs[k][:, 0, :, lo:hi] = arr
        if self.kv_quant == "int8":
            return KVCache(
                k=QuantizedKV(q=jnp.asarray(bufs["kq"]),
                              scale=jnp.asarray(bufs["ks"])),
                v=QuantizedKV(q=jnp.asarray(bufs["vq"]),
                              scale=jnp.asarray(bufs["vs"])))
        return KVCache(k=jnp.asarray(bufs["k"]), v=jnp.asarray(bufs["v"]))

    def import_attach(self, xfer_id: str, stream_id: int) -> None:
        """Queue the attach of a begun import: when it reaches the FIFO
        head with a free slot, the imported pages become the stream's
        table (page-table edit — ref then unpin, no cache tensor moves)
        and its sampler/cursor state splices in. Decode then continues
        bit-identically to the exporting engine's next step."""
        self._domain_stamp.check("BatchGenerator.import_attach")
        self._require_paged("import_attach")
        if xfer_id not in self._imports:
            raise KeyError(f"unknown or expired transfer {xfer_id!r}")
        self._arrivals.append((xfer_id, stream_id, None, _ARR_ATTACH))

    def _import_attach_tick(self) -> None:
        xid, sid, _, _ = self._arrivals.pop(0)
        rec = self._imports.pop(xid, None)
        if rec is None or rec["pages"] is None:
            # aborted/expired between queue and tick (rec["pages"] is
            # None only if the begin was aborted while queued — FIFO
            # order guarantees the begin tick ran before this one)
            if rec is not None:
                _IMPORT_ABORTS.inc()
            self._attach_failures.append(sid)
            return
        snap, pages = rec["snap"], rec["pages"]
        slot = self._free_slot()
        self._release_pages(slot)
        # rows computed under the slot's previous meaning are recorded
        # before the attach changes it (same rule as the admission splice)
        self._drain_buffered_rows()
        for pid in pages:
            self._pagepool.ref(pid)    # the stream table's claim...
            self._pagepool.unpin(pid)  # ...replaces the transfer pin
        self._tables[slot] = list(pages)
        self._page_map_dev = None
        (self._keys, self._history, self._hist_slot,
         self._last_tokens) = self._splice_small_fn()(
            self._keys, self._history, self._hist_slot,
            self._last_tokens, jnp.asarray(snap.key, jnp.uint32),
            jnp.asarray(snap.history, jnp.int32),
            jnp.int32(snap.hist_slot), jnp.int32(snap.last_token),
            jnp.int32(slot),
        )
        self._pos = np.asarray(self._pos).copy()
        self._pos[slot] = snap.pos
        self._index = np.asarray(self._index).copy()
        self._index[slot] = snap.index
        s = _Stream(stream_id=sid, prompt=list(snap.prompt),
                    detok=rec["detok"])
        s.generated = list(snap.generated)
        self.streams[slot] = s
        self._drop_guide(slot)
        if snap.guide_spec is not None:
            from cake_tpu.constrain.guide import guide_for

            g = guide_for(snap.guide_spec, self.tokenizer, self.config)
            self._attach_guide(slot, g)  # resets the cursor...
            g.state = snap.guide_state   # ...then resume mid-grammar
        _RESUMES.inc()

    def import_abort(self, xfer_id: str) -> bool:
        """Drop a begun import and release its page pins (resume never
        came — gateway died, TTL expired, client cancelled). Returns
        False when the id is unknown (already attached or aborted)."""
        self._domain_stamp.check("BatchGenerator.import_abort")
        rec = self._imports.pop(xfer_id, None)
        if rec is None:
            return False
        if rec["pages"] is not None:
            for pid in rec["pages"]:
                self._pagepool.unpin(pid)
        else:
            self._arrivals = [a for a in self._arrivals
                              if not (a[3] == _ARR_IMPORT
                                      and a[0] == xfer_id)]
        _IMPORT_ABORTS.inc()
        return True

    def expire_imports(self, ttl_s: float) -> int:
        """Abort begun-but-unattached imports older than ``ttl_s``; the
        serve scheduler sweeps this so an orphaned transfer cannot pin
        pool pages forever. Returns the number aborted."""
        self._domain_stamp.check("BatchGenerator.expire_imports")
        if not self._imports:
            return 0
        now = time.monotonic()
        expired = [xid for xid, rec in self._imports.items()
                   if now - rec["t"] > ttl_s]
        for xid in expired:
            self.import_abort(xid)
        return len(expired)

    def take_attach_failures(self) -> list[int]:
        """Stream ids whose attach found its import gone (aborted or
        expired) — the serve scheduler fails those sessions with a
        resumable-elsewhere status instead of letting them hang."""
        out, self._attach_failures = self._attach_failures, []
        return out

    def imports_pending(self) -> int:
        """Begun-but-unattached imports (pages pinned or queued) — the
        ``kv_transfers_inflight`` signal /healthz exposes."""
        return len(self._imports)

    def import_stream(self, data, stream_id: int | None = None,
                      ) -> tuple[int, str]:
        """Synchronous import: begin + attach + drive admission ticks to
        completion (the ``admit()`` of the disagg plane — tests and
        single-process suspend/resume). Returns ``(slot, xfer_id)``.
        Raises when the attach cannot complete without outside help (no
        retirable slot, pool exhausted with nothing evictable)."""
        meta = self.import_begin(data)
        xid = meta["xfer_id"]
        sid = meta["stream_id"] if stream_id is None else stream_id
        self.import_attach(xid, sid)

        def ours_pending() -> bool:
            return any(a[3] in (_ARR_IMPORT, _ARR_ATTACH) and a[0] == xid
                       for a in self._arrivals)

        while ours_pending():
            head = self._arrivals[0]
            # admit()'s no-busy-loop rule, FIFO-wide: any head that needs
            # a slot to start (an attach, ours or not, or a queued
            # prompt — everything but a pages-only import admission)
            # blocks the whole queue when every stream is live, so raise
            # instead of spinning on a no-op tick
            if (head[3] != _ARR_IMPORT and self._staging is None
                    and self._free_slot() is None):
                self.import_abort(xid)
                raise RuntimeError(
                    "no free slot: every stream is still live")
            self._admission_tick()
            # a pool-deferred head — whoever owns it — can only unblock
            # via retires that never happen inside this synchronous loop
            if self._staging is None and self._admit_deferred:
                self.import_abort(xid)
                raise RuntimeError(
                    "kv page pool exhausted: import deferred (retire "
                    "streams, or grow kv_pool_pages)")
        if sid in self._attach_failures:
            self._attach_failures.remove(sid)
            raise RuntimeError(f"import {xid} was aborted before attach")
        slot = next(i for i, s in enumerate(self.streams)
                    if s.active and not s.done and s.stream_id == sid
                    and s.generated[:len(meta["generated"])]
                    == meta["generated"])
        return slot, xid

    def _admission_chunk_for(self, prompt_len: int) -> int:
        """The per-dispatch admission chunk for a prompt of this length:
        the configured interleave granularity, but never padded past the
        prompt's own bucket. Both bounds keep t_pad <= max_seq (the bucket
        by construction, admit_chunk by the constructor's divisibility
        check)."""
        bucket = _bucket(prompt_len, self.max_seq)
        return min(self._admit_chunk, bucket) if self._admit_chunk else bucket

    def warm_admission(self, prompt_len: int) -> None:
        """Compile the admission-prefill program (and staging-cache zeros
        program) for prompts of this length, outside any serving-critical
        window — benchmarks/servers call this once so the first real
        ``enqueue`` does not pay XLA compilation mid-run. The compiled
        shape depends only on the chunk for ``prompt_len``; with prefix
        sharing active, call again with the expected REMAINDER length
        (arrival length minus the shared prefix), since that is the shape
        a prefix-cache hit dispatches.

        With int8 weights, call AFTER ``set_prompts`` (or pass
        ``quant_backend=`` at construction): the warm trace is permanent
        in the jit cache, so tracing before the instance's backend pin is
        decided would bake the per-shape gate in and silently void the
        determinism contract — enforced below."""
        if self._params_quantized and self._quant_pin is None:
            raise ValueError(
                "warm_admission with int8 weights needs the backend pin "
                "decided first: call set_prompts before warming, or pass "
                "quant_backend= at construction"
            )
        chunk = self._admission_chunk_for(prompt_len)
        staging = init_cache_on_mesh(
            self.config, self.plan.mesh, batch=1, max_seq=self.max_seq,
            quant=self.kv_quant, batch_replicated=True,
        )
        logits, staging = self._admit_prefill(
            self.params, jnp.zeros((1, chunk), jnp.int32), staging,
            jnp.int32(0), jnp.zeros((1,), jnp.int32),
        )
        # warm the rest of the admission-completion path too: the first
        # token's sampler and the slot-traced state splice (compiled once,
        # outputs discarded — no donation, the live state is untouched).
        # Before set_prompts the batch state (and its B dimension) doesn't
        # exist yet, so the splice warm is deferred to the next set_prompts
        # — never silently dropped (the compile would otherwise land inside
        # the serving window, the exact stall _splice_fn exists to kill).
        n_hist = self.settings.repeat_last_n
        tok = sampling.sample_token(
            logits[0], jax.random.fold_in(self._base_key, 0),
            jnp.full((n_hist,), -1, jnp.int32), self.settings,
        )
        if getattr(self, "cache", None) is not None:
            self._warm_splice(staging)
        else:
            self._splice_warm_pending = True
        np.asarray(np.asarray(tok).ravel()[:1])  # synchronize

    def _warm_splice(self, staging=None) -> None:
        """Compile the admission-completion programs against the live
        batch state's shapes (outputs discarded; live state untouched).
        Slot: the slot-traced cache splice. Paged: the row gather/scatter
        page programs plus the small sampler-state splice — warmed on
        pool/staging COPIES (both programs donate their first argument)
        with all-sink ids, so no live page is read or written."""
        if staging is None:
            staging = init_cache_on_mesh(
                self.config, self.plan.mesh, batch=1, max_seq=self.max_seq,
                quant=self.kv_quant, batch_replicated=True,
            )
        n_hist = self.settings.repeat_last_n
        if self._paged:
            sink = jnp.zeros((self._ppp,), jnp.int32)
            pool_copy = jax.tree.map(lambda x: x.copy(), self.cache)
            out_pool = self._row_scatter(pool_copy, staging, sink)
            out_row = self._row_gather(self.cache, sink)
            out = self._splice_small_fn()(
                self._keys, self._history, self._hist_slot,
                self._last_tokens, jax.random.fold_in(self._base_key, 0),
                jnp.full((n_hist,), -1, jnp.int32), jnp.int32(0),
                jnp.int32(0), jnp.int32(0),
            )
            jax.block_until_ready((out_pool, out_row, out))
            return
        out = self._splice_fn()(
            self.cache, staging, self._keys, self._history,
            self._hist_slot, self._last_tokens,
            jax.random.fold_in(self._base_key, 0),
            jnp.full((n_hist,), -1, jnp.int32), jnp.int32(0),
            jnp.int32(0), jnp.int32(0),
        )
        jax.block_until_ready(out)

    def _admission_tick(self) -> None:
        """Advance the in-flight admission by one chunk dispatch (or start
        the next queued arrival if a slot is free). KV-page imports
        (cake_tpu/disagg) ride the same FIFO: a begin lands the pages in
        the pool (deferring FIFO-fair under pool pressure exactly like a
        prompt admission), an attach installs the resumed stream into a
        free slot — each one tick, no prefill dispatches."""
        if self._staging is None:
            if not self._arrivals:
                return
            kind = self._arrivals[0][3]
            if kind == _ARR_IMPORT:
                self._import_begin_tick()
                return
            if kind == _ARR_ATTACH:
                if self._free_slot() is not None:
                    self._import_attach_tick()
                return
            if self._free_slot() is None:
                return
            slot = self._free_slot()
            if self._paged:
                # claim point: the slot's previous stream (retired by ANY
                # path, including a caller writing s.done directly) frees
                # its page claims before the arrival's needs are priced
                self._release_pages(slot)
            ids, sid, guide, _ = self._arrivals.pop(0)
            # Prefix reuse: an arrival whose opening tokens match a stored
            # prefix (a staged row in the slot layout, a page chain in the
            # paged one) starts from that content and prefills only its
            # remainder — re-prefilling a known prefix is exactly the
            # waste the store exists to kill. Falls back to a from-scratch
            # prefill when the remainder's bucket would not fit above the
            # prefix.
            row = None
            shared_pages: list[int] = []
            if self._paged:
                base = 0
                if self._prefix_entries > 0:
                    base, shared_pages = self._prefix_tree.match(ids)
            else:
                base, row = self._match_prefix(ids)
            rem = len(ids) - base
            chunk = self._admission_chunk_for(rem)
            t_pad = -(-rem // chunk) * chunk
            if base and base + t_pad > self.max_seq:
                base, row, shared_pages = 0, None, []
                rem = len(ids)
                chunk = self._admission_chunk_for(rem)
                t_pad = -(-rem // chunk) * chunk
            if self._paged:
                # hold the matched pages BEFORE any eviction can touch
                # them, then price the remainder; when its pages cannot
                # be found even by evicting warm prefixes, the arrival
                # defers (stays FIFO head) until retirements free pages
                for pid in shared_pages:
                    self._pagepool.ref(pid)
                ps = self._page_size
                need = (len(ids) - 1) // ps + 1 - len(shared_pages)
                if (self._pagepool.free_count < need
                        and not self._prefix_tree.evict_until_free(need)):
                    for pid in shared_pages:
                        self._pagepool.unref(pid)
                    if not self._admit_deferred:
                        # count DEFERRED ADMISSIONS, not re-priced ticks
                        # (the head arrival is re-tried every step while
                        # it waits). Unreachable under the enforced pool
                        # sizing — reachable the moment in-flight KV
                        # transfers pin pages outside stream tables
                        # (cake_tpu/disagg imports).
                        self._pagepool.count_defer()
                    self._admit_deferred = True
                    self._arrivals.insert(0, (ids, sid, guide, None))
                    return
                self._admit_deferred = False
            tokens = np.zeros((1, t_pad), np.int32)
            tokens[0, :rem] = ids[base:]
            if base:
                self._prefix_hits += 1
                if self._paged:
                    # the staging starts as a GATHER of the shared pages
                    # (prefix KV the remainder chunks attend), not a copy
                    # of a stored row — the pages themselves stay shared
                    ids_vec = np.zeros((self._ppp,), np.int32)
                    ids_vec[: len(shared_pages)] = shared_pages
                    cache = self._row_gather(self.cache,
                                             jnp.asarray(ids_vec))
                else:
                    # copy: the admission program donates its cache
                    # argument, and the stored row must survive for
                    # future hits
                    cache = jax.tree.map(lambda x: x.copy(), row)
            else:
                cache = init_cache_on_mesh(
                    self.config, self.plan.mesh, batch=1,
                    max_seq=self.max_seq, quant=self.kv_quant,
                    batch_replicated=True,
                )
            self._staging = {
                "ids": ids, "sid": sid, "slot": slot,
                "tokens": tokens, "pos": 0, "chunk": chunk, "base": base,
                "cache": cache, "guide": guide, "shared": shared_pages,
            }
        st = self._staging
        pos, chunk, base = st["pos"], st["chunk"], st["base"]
        final = pos + chunk >= st["tokens"].shape[1]
        t0 = time.perf_counter()
        with span("admit.chunk", pos=base + pos, chunk=chunk):
            logits, st["cache"] = self._admit_prefill(
                self.params,
                jnp.asarray(st["tokens"][:, pos: pos + chunk]),
                st["cache"],
                jnp.int32(base + pos),
                jnp.asarray(
                    [len(st["ids"]) - 1 - base - pos if final else 0],
                    jnp.int32,
                ),
            )
            np.asarray(logits.ravel()[:1])  # sync: busy_s must include compute
        self._n_admit_dispatches += 1
        dt = time.perf_counter() - t0
        self._busy_s += dt
        self._admit_hist.observe(dt * 1e3)
        rec = obs_flight.recorder()
        if rec.enabled:
            rec.record(
                kind="admit", total_ms=round(dt * 1e3, 3), chunk=chunk,
                pos=base + pos,
            )
        st["pos"] = pos + chunk
        if final:
            self._finish_admission(logits)

    def _splice_fn(self):
        """The admission splice as ONE jitted program with the slot index
        TRACED: splicing with host-side ``.at[:, slot].set`` bakes the slot
        as a constant, so every distinct slot compiled a fresh cache-sized
        scatter (plus four small-state scatters) *inside the serving
        window* — measured as the dominant churn-bench cost (busy_s 5.3 of
        timed 14.4 s on v5e; the other ~9 s were these compiles). One
        traced program serves every slot and is warmed by
        ``warm_admission``."""
        if self.__splice is None:
            def splice(cache, row, keys, history, hist_slot, last, key,
                       hist_row, hist_used, tok, slot):
                upd1 = lambda buf, v: jax.lax.dynamic_update_index_in_dim(
                    buf, v, slot, 0)
                cache = jax.tree.map(
                    lambda c, r: jax.lax.dynamic_update_index_in_dim(
                        c, r[:, 0], slot, 1),
                    cache, row,
                )
                return (
                    cache,
                    upd1(keys, key),
                    upd1(history, hist_row),
                    upd1(hist_slot, hist_used),
                    upd1(last, tok),
                )

            self.__splice = jax.jit(splice)
        return self.__splice

    def _finish_admission(self, logits) -> None:
        """Splice the staged row into its slot, sample + record the first
        token, and queue its emission row."""
        st, self._staging = self._staging, None
        slot, ids, stream_id = st["slot"], st["ids"], st["sid"]
        guide = st.get("guide")
        # Buffered block rows belong to the pre-admission state: record
        # them before the slot's column changes meaning, so streaming
        # step() consumers still receive every Token. An in-flight
        # lookahead block is the same chronology, one block later — fetch
        # and record it too (its rows are also pre-admission tokens).
        self._drain_buffered_rows()

        # the slot's previous stream is gone; its guide (if any) with it
        self._drop_guide(slot)
        if guide is not None:
            self._attach_guide(slot, guide)
        key = jax.random.fold_in(self._base_key, stream_id)
        n_hist = self.settings.repeat_last_n
        hist_row = np.full((n_hist,), -1, np.int32)
        tail = ids[-n_hist:]
        hist_row[: len(tail)] = tail
        tok = sampling.sample_token(
            logits[0], jax.random.fold_in(key, 0), jnp.asarray(hist_row),
            self.settings,
            mask=jnp.asarray(guide.mask_bool()) if guide is not None
            else None,
        )
        tok_id = int(tok)
        hist_row[len(tail) % n_hist] = tok_id
        lp_row = None
        if self.logprobs_k:
            lpv0, lpi0 = sampling.topk_logprobs(logits[0], self.logprobs_k)
            lp_row = [(int(i), float(v))
                      for v, i in zip(np.asarray(lpv0), np.asarray(lpi0))]

        if self._paged:
            # the paged "splice": scatter the staged row's NEW pages into
            # the pool (shared prefix pages are already there — their
            # id-vector slots stay sink, so refcounted pages are never
            # rewritten) and install the table. Only the small sampler
            # state splices as tensors; the KV hand-off is a page write.
            ps = self._page_size
            shared = st.get("shared", [])
            n_shared = len(shared)
            last_page = (len(ids) - 1) // ps
            ids_vec = np.zeros((self._ppp,), np.int32)
            # the fresh pages are held only by this local until the
            # table install below — release them on the error path
            # (cakelint CK-CLAIM). The alloc loop itself sits INSIDE
            # the try: the admission pre-check ran steps ago (chunked
            # prefill), and an import landing in between can pin pages
            # past it, so a mid-loop PoolExhausted must release what
            # this row already took, same as a raising scatter dispatch.
            new_pages: list[int] = []
            try:
                for _ in range(last_page + 1 - n_shared):
                    new_pages.append(self._alloc_page())
                ids_vec[n_shared: last_page + 1] = new_pages
                self.cache = self._row_scatter(self.cache, st["cache"],
                                               jnp.asarray(ids_vec))
            except BaseException:
                for pid in new_pages:
                    self._pagepool.unref(pid)
                raise
            self._release_pages(slot)  # idempotent (freed at claim too)
            self._tables[slot] = shared + new_pages
            self._page_map_dev = None
            (self._keys, self._history, self._hist_slot,
             self._last_tokens) = self._splice_small_fn()(
                self._keys, self._history, self._hist_slot,
                self._last_tokens, key, jnp.asarray(hist_row),
                jnp.int32(len(tail) + 1), jnp.int32(tok_id),
                jnp.int32(slot),
            )
        else:
            (self.cache, self._keys, self._history, self._hist_slot,
             self._last_tokens) = self._splice_fn()(
                self.cache, st["cache"], self._keys, self._history,
                self._hist_slot, self._last_tokens, key,
                jnp.asarray(hist_row), jnp.int32(len(tail) + 1),
                jnp.int32(tok_id), jnp.int32(slot),
            )
        self._pos = np.asarray(self._pos).copy()
        self._pos[slot] = len(ids)
        self._index = np.asarray(self._index).copy()
        self._index[slot] = 1

        s = _Stream(
            stream_id=stream_id, prompt=ids,
            detok=TokenOutputStream(self.tokenizer) if self.tokenizer else None,
        )
        self.streams[slot] = s
        if self._spec_k:
            self._spec_bank[slot] = []  # the slot's old stream is gone
            # the device ctx row still holds the OLD stream's tokens; a
            # pos-coincidence could otherwise pass the staleness check
            self._spec_ctx = None
            self._spec_ctx_pos = None
        s.generated.append(tok_id)
        window_full = len(ids) + 1 >= self.max_seq
        is_eos = tok_id in self._eos_ids
        s.done = is_eos or window_full
        if s.done:
            s.end_reason = "eos" if is_eos else "length"
        self._advance_guide(slot, s, tok_id)
        text = (s.detok.next_token(tok_id)
                if s.detok is not None and not is_eos else None)
        self._n_emitted += 1
        self._emitted_ctr.inc()
        row: list[Token | None] = [None] * len(self.streams)
        row[slot] = Token(id=tok_id, text=text, is_end_of_stream=s.done,
                          logprobs=lp_row)
        self._pending_rows.append(row)

        # Feed the store: this arrival's prefix becomes reusable by future
        # arrivals with the same opening. Paged: the stream's FULL prompt
        # pages register in the prefix tree (zero copies — the tree just
        # takes references; a later same-prefix arrival shares the
        # physical pages, which is the copy-on-write fan-out). Slot: the
        # staging row is retained under the prefix truncated to a
        # prefix_block boundary (the splice above copied values out, so
        # retaining it costs no extra dispatch).
        if self._paged:
            n_full = len(ids) // self._page_size
            if (self._prefix_entries > 0 and n_full
                    and n_full * self._page_size
                    >= max(1, self._prefix_share_min)):
                self._prefix_tree.insert(ids, self._tables[slot][:n_full])
        else:
            base_new = ((len(ids) - 1) // self._prefix_block) \
                * self._prefix_block
            if base_new >= max(1, self._prefix_share_min):
                self._store_prefix(ids[:base_new], st["cache"])
        if s.done and self._paged:
            # first sampled token ended the stream: free its claims now
            # (AFTER the tree store above took its references)
            self._release_pages(slot)

    def finish(self, stream_id: int) -> bool:
        """Retire the stream with this ``stream_id`` at ANY point in its
        lifecycle. Live: it stops emitting and its slot (batch row + KV
        rows) becomes admissible to the next ``enqueue``/``admit`` arrival
        — the admission splice overwrites the row in place, so retirement
        IS the KV free on the batch plane. Still queued in the arrival
        FIFO, or mid-admission in the staging cache: the arrival is
        dropped before it can splice in (a server cancelling a request
        whose prefill never finished must not leak an ownerless stream
        into a slot). The public serving-side retirement API (a server
        ending a stream at its token budget, client disconnect, or
        deadline); EOS/window exhaustion retire streams the same way
        internally. Returns False when the id is unknown (already done,
        or never admitted) — retirement races are normal for a server,
        not errors. Tokens the device already computed for the stream
        (buffered fused-block rows, an in-flight lookahead block, banked
        speculation runs) are discarded at emission like any other
        past-EOS overrun."""
        self._domain_stamp.check("BatchGenerator.finish")
        for i, s in enumerate(self.streams):
            if s.active and not s.done and s.stream_id == stream_id:
                s.done = True
                self._drop_guide(i)
                # paged: retirement IS the KV free — a host-side unref
                # loop over the slot's page list, no cache tensor touched
                self._release_pages(i)
                return True
        if self._staging is not None and self._staging["sid"] == stream_id:
            if self._paged:
                for pid in self._staging.get("shared", []):
                    self._pagepool.unref(pid)
            self._staging = None  # staged KV row is dropped with it
            return True
        n0 = len(self._arrivals)
        # a cancelled resume drops its queued attach AND aborts the
        # import behind it (the pinned pages must not wait out the TTL)
        drop_xfers = [a[0] for a in self._arrivals
                      if a[1] == stream_id and a[3] == _ARR_ATTACH]
        self._arrivals = [a for a in self._arrivals if a[1] != stream_id]
        for xid in drop_xfers:
            self.import_abort(xid)
        return len(self._arrivals) != n0

    def admit(self, prompt, stream_id: int) -> tuple[int, Token]:
        """Admit a new prompt into a finished slot of a RUNNING batch,
        synchronously: the chunked one-row admission prefill runs to
        completion here and the first token is returned (recorded;
        subsequent ``step()`` calls carry the stream forward). Use
        ``enqueue`` to interleave the prefill with decode instead. Raises
        if no stream is done."""
        if not self.streams:
            raise RuntimeError("set_prompts first")
        ids = self._encode(prompt)
        self._arrivals.append((ids, stream_id, None, None))
        # Drain until OUR arrival (tracked by list identity — FIFO order
        # admits anything queued ahead of it first) is fully admitted. If
        # the queue head cannot start because every stream is live, raise
        # instead of busy-looping on a no-op tick.
        while (any(a[0] is ids for a in self._arrivals)
               or (self._staging is not None
                   and self._staging["ids"] is ids)):
            if self._staging is None and self._free_slot() is None:
                self._arrivals = [a for a in self._arrivals
                                  if a[0] is not ids]
                raise RuntimeError("no free slot: every stream is still live")
            self._admission_tick()
            if self._staging is None and self._admit_deferred:
                # paged pool pressure: nothing inside a synchronous
                # admit() will retire streams and free pages, so busy-
                # looping on the deferred head would never terminate
                self._arrivals = [a for a in self._arrivals
                                  if a[0] is not ids]
                raise RuntimeError(
                    "kv page pool exhausted: admission deferred (retire "
                    "streams via step()/finish(), or grow kv_pool_pages)")
        # the emission row just queued duplicates the returned Token: drop it
        row = self._pending_rows.pop()
        slot = next(i for i, t in enumerate(row) if t is not None)
        return slot, row[slot]

    # -- stepping ------------------------------------------------------------
    def _emit(self, row: np.ndarray, skip: list[bool] | None = None,
              lp=None) -> list[Token | None]:
        """Turn one [B] token row into per-stream Tokens (None when done or
        dummy), updating per-stream bookkeeping. ``skip[i]`` excludes a
        stream from this row without marking it done. ``lp`` is the
        optional per-row top-k logprob pair ``(vals [B, K], ids [B, K])``.
        Constrained streams advance their host-side DFA cursor here —
        the one host-side step per token the no-retrace design needs."""
        lpv, lpi = lp if lp is not None else (None, None)
        out: list[Token | None] = []
        with self._prof.phase("emit"):
            for i, s in enumerate(self.streams):
                if not s.active or s.done or (skip is not None and skip[i]):
                    out.append(None)
                    continue
                tok_id = int(row[i])
                s.generated.append(tok_id)
                window_full = (len(s.prompt) + len(s.generated)
                               >= self.max_seq)
                is_eos = tok_id in self._eos_ids
                s.done = is_eos or window_full
                if s.done:
                    s.end_reason = "eos" if is_eos else "length"
                self._advance_guide(i, s, tok_id)
                if s.done and self._paged:
                    # EOS/window/constraint retirement frees the pages
                    # here — the slot is admissible the moment the row
                    # is emitted
                    self._release_pages(i)
                # the EOS id is an end marker, not text: detokenizing it
                # would append its (toy tokenizers: arbitrary) surface form
                text = (s.detok.next_token(tok_id)
                        if s.detok is not None and not is_eos else None)
                lp_i = None
                if lpv is not None:
                    lp_i = [(int(lpi[i, j]), float(lpv[i, j]))
                            for j in range(lpi.shape[1])]
                out.append(Token(id=tok_id, text=text,
                                 is_end_of_stream=s.done, logprobs=lp_i))
        emitted = sum(1 for t in out if t is not None)
        self._n_emitted += emitted
        self._emitted_ctr.inc(emitted)
        return out

    def _emit_buffered(self, entry) -> list[Token | None]:
        """Emit one buffered fused-block row: ``(row [B], lp-or-None)``."""
        row, lp = entry
        return self._emit(row, lp=lp)

    def step(self) -> list[Token | None]:
        """Advance every live stream one token; returns one entry per active
        stream slot (None for finished/dummy streams). A queued arrival
        (``enqueue``) advances by one admission-prefill chunk per call,
        interleaved with the decode dispatches."""
        self._domain_stamp.check("BatchGenerator.step")
        if not self.streams:
            raise RuntimeError("set_prompts first")
        prof = self._prof
        prof.step_begin("batch")
        try:
            if not self._emitted_first:
                self._emitted_first = True
                # skip streams that already recorded tokens — a stream
                # admit()ed into a dummy slot before the first step() had
                # its first token returned by admit(), and must not be
                # double-recorded here
                return self._emit(
                    self._host(self._last_tokens),
                    skip=[bool(s.generated) for s in self.streams],
                    lp=self._first_lp,
                )
            if self._staging is not None or self._arrivals:
                # stamp only real admission work, or an idle batch would
                # flood the admit histogram with ~0 ms no-op ticks
                with prof.phase("admit"):
                    self._admission_tick()
            if self._pending_rows:
                return self._pending_rows.pop(0)
            return self._step_decode()
        finally:
            prof.step_end()

    def _spec_emit_or_round(self):
        """Drain the per-stream accepted-token banks one row per call;
        when empty, run one batched verification round. Returns None — the
        caller falls through to the plain decode path (single or fused
        block) — when speculation cannot or should not run:

        - no live streams;
        - a live stream within K+1 slots of its window (its fed row's
          per-row KV write would clamp-overwrite committed slots). This
          gate is batch-global but BOUNDED: such a stream fills its window
          and goes done within <= K+1 plain dispatches, after which spec
          rounds resume;
        - greedy with no proposal on any live stream: a proposal-less
          round is a (K+1)-wide forward that advances every stream exactly
          one token — strictly worse than a plain dispatch, and for greedy
          the outputs are identical either way. Sampled streams keep the
          always-verify path: their round draws live in the spec fold
          domain, and skipping rounds based on OTHER streams' proposals
          would break composition invariance."""
        if any(self._spec_bank):
            return self._emit_spec_bank()
        live = [i for i, s in enumerate(self.streams)
                if s.active and not s.done]
        if not live:
            return None
        if (self._spec_rounds > 1
                and all(int(self._pos[i])
                        + self._spec_rounds * (self._spec_k + 1)
                        < self.max_seq for i in live)):
            # fused chain: R rounds, one sync. A proposal-less greedy round
            # inside the chain costs one weight sweep for one token — the
            # same per-token HBM cost as the plain path — so the chain
            # skips the host-side "all proposals empty" probe (which would
            # itself force the per-round sync the chain exists to avoid).
            self._spec_chain(live)
            return self._emit_spec_bank()
        if any(int(self._pos[i]) + self._spec_k + 1 > self.max_seq
               for i in live):
            return None
        from cake_tpu.runtime.speculative import ngram_propose

        b = len(self.streams)
        k = self._spec_k
        props = np.full((b, k), -1, np.int32)
        with self._prof.phase("spec_propose"):
            for i in live:
                s = self.streams[i]
                pr = ngram_propose(s.prompt + s.generated,
                                   self._spec_ngram, k)
                props[i, : len(pr)] = pr
        if self.settings.greedy and (props < 0).all():
            return None
        self._spec_round(live, props)
        return self._emit_spec_bank()

    def _spec_round(self, live: list[int], props: np.ndarray) -> None:
        b = len(self.streams)
        k = self._spec_k
        fed = np.zeros((b, k + 1), np.int32)
        fed[:, 0] = self._host(self._last_tokens)
        fed[:, 1:] = np.maximum(props, 0)  # -1 pads embed as 0; never match
        t0 = time.perf_counter()
        with self._prof.phase("spec_verify"), self._sentinel.decode_phase():
            logits, self.cache = self._pick_verify()(
                self.params, jnp.asarray(fed), self.cache,
                jnp.asarray(self._pos),
            )
        with self._prof.phase("spec_accept"), self._sentinel.decode_phase():
            if self.settings.greedy:
                (toks, count, self._history,
                 self._hist_slot) = self._accept_rows(
                    logits, jnp.asarray(props), self._history,
                    self._hist_slot)
            else:
                # per-row round keys in their own fold domain (0x5bec),
                # keyed by the row's position — unique per round, disjoint
                # from the plain per-token-index sampling schedule
                rkeys = jax.vmap(lambda kk, p: jax.random.fold_in(
                    jax.random.fold_in(kk, 0x5BEC), p))(
                        self._keys, jnp.asarray(self._pos))
                (toks, count, self._history,
                 self._hist_slot) = self._accept_rows(
                    logits, jnp.asarray(props), self._history,
                    self._hist_slot, round_keys=rkeys)
            toks = self._host(toks)
            count = self._host(count)
        self._n_decode_dispatches += 1
        self._n_spec_dispatches += 1
        self._busy_s += time.perf_counter() - t0
        live_mask = np.zeros((b,), bool)
        live_mask[live] = True
        # non-live rows advance exactly one slot (parity with the plain
        # path's clamped discarded writes); live rows bank their run
        n = np.where(live_mask, np.maximum(count, 1), 1)
        from cake_tpu.runtime import speculative as _spec_obs

        _spec_obs.record_acceptance(
            int((props[live] >= 0).sum()),
            int(sum(max(0, int(n[i]) - 1) for i in live)))
        for i in live:
            self._spec_bank[i] = toks[i, : n[i]].tolist()
        self._pos = np.asarray(self._pos) + n
        self._index = np.asarray(self._index) + n
        last = toks[np.arange(b), n - 1]
        # fed[:, 0] already holds this round's pre-fetched last tokens —
        # no second device fetch (on multi-host each fetch is a collective)
        self._last_tokens = jnp.asarray(
            np.where(live_mask, last, fed[:, 0]), jnp.int32,
        )

    @property
    def _spec_propose(self):
        """Jitted batched device proposer: per-row prompt-lookup over the
        device ctx rows + fed assembly — the host proposer never runs
        inside a fused chain."""
        if self.__spec_propose is None:
            from functools import partial

            from cake_tpu.runtime.speculative import ngram_propose_device

            def propose(ctx, pos, last, *, n_max, k):
                props = jax.vmap(
                    lambda c, p: ngram_propose_device(
                        c, p + 1, n_max=n_max, k=k)
                )(ctx, pos)
                fed = jnp.concatenate(
                    [last[:, None], jnp.maximum(props, 0)], axis=1)
                return props, fed

            self.__spec_propose = jax.jit(partial(
                propose, n_max=self._spec_ngram, k=self._spec_k))
        return self.__spec_propose

    @property
    def _spec_update(self):
        """Jitted accept + state update for one fused round: batched accept
        scan, per-row freeze (``done``), ctx append, pos/last advance. The
        same key schedule as :meth:`_spec_round` (fold domain 0x5bec keyed
        by the row's position), so sampled streams are bit-identical to the
        per-round host loop."""
        if self.__spec_update is None:
            from functools import partial

            from cake_tpu.runtime.speculative import (
                accept_fn_rows,
                accept_sampled_fn_rows,
            )

            eos = jnp.asarray(sorted(self._eos_ids) or [-1], jnp.int32)
            greedy = self.settings.greedy
            settings = self.settings

            def update(logits, props, ctx, pos, history, hist_slot, done,
                       last, keys):
                if greedy:
                    toks, count, h2, s2 = accept_fn_rows(
                        logits, props, history, hist_slot, eos, settings)
                else:
                    rkeys = jax.vmap(lambda kk, p: jax.random.fold_in(
                        jax.random.fold_in(kk, 0x5BEC), p))(keys, pos)
                    toks, count, h2, s2 = accept_sampled_fn_rows(
                        logits, props, history, hist_slot, eos, rkeys,
                        settings)
                n = jnp.where(done, 0, count)
                history = jnp.where(done[:, None], history, h2)
                hist_slot = jnp.where(done, hist_slot, s2)
                # append each row's run at pos+1 (ctx[i, pos_i] holds the
                # token that fed this round). Frozen rows write junk past
                # their frontier — masked by pos everywhere; a frozen row
                # parked near the window end may clamp-write inside its own
                # dead row, which is never proposed from again.
                ctx = jax.vmap(
                    lambda c, t, p: jax.lax.dynamic_update_slice(
                        c, t, (p + 1,))
                )(ctx, toks, pos)
                t_idx = jnp.arange(toks.shape[1], dtype=jnp.int32)
                eos_hit = (
                    (toks[:, :, None] == eos[None, None, :]).any(-1)
                    & (t_idx[None, :] < n[:, None])
                ).any(axis=1)
                new_last = jnp.take_along_axis(
                    toks, jnp.maximum(n - 1, 0)[:, None], axis=1)[:, 0]
                last = jnp.where(done, last, new_last)
                pos = pos + n
                done = done | eos_hit
                return toks, n, ctx, pos, history, hist_slot, done, last

            self.__spec_update = self._pinned(jax.jit(update))
        return self.__spec_update

    def _spec_chain(self, live: list[int]) -> None:
        """Run ``spec_rounds`` propose→verify→accept rounds with a single
        host↔device sync at the end (async dispatch pipelines the chained
        programs). The caller guarantees every live row has
        ``pos + spec_rounds*(K+1) < max_seq`` headroom."""
        b = len(self.streams)
        if (self._spec_ctx is None or self._spec_ctx_pos is None
                or not np.array_equal(self._spec_ctx_pos,
                                      np.asarray(self._pos))):
            buf = np.zeros((b, self.max_seq), np.int32)
            for i, s in enumerate(self.streams):
                ctx_i = (s.prompt + s.generated + self._spec_bank[i]
                         if s.active else [0])
                buf[i, : len(ctx_i)] = ctx_i
            self._spec_ctx = jnp.asarray(buf)
        t0 = time.perf_counter()
        ctx = self._spec_ctx
        pos = jnp.asarray(np.asarray(self._pos, np.int32))
        done = jnp.asarray(np.asarray(
            [not (s.active and not s.done) for s in self.streams]))
        last = self._last_tokens
        verify = self._pick_verify()
        toks_rounds, n_rounds = [], []
        with self._prof.phase("spec_verify"), self._sentinel.decode_phase():
            for _ in range(self._spec_rounds):
                props, fed = self._spec_propose(ctx, pos, last)
                logits, self.cache = verify(
                    self.params, fed, self.cache, pos)
                (toks, n, ctx, pos, self._history, self._hist_slot, done,
                 last) = self._spec_update(
                    logits, props, ctx, pos, self._history, self._hist_slot,
                    done, last, self._keys)
                toks_rounds.append(toks)
                n_rounds.append(n)
        # one combined fetch — two sequential _host calls would pay a
        # second tunnel round trip, the very latency the chain amortizes
        # (cross-process dp still takes the allgather path per array)
        with self._prof.phase("spec_accept"):
            try:
                toks_all, n_all = jax.device_get(
                    (jnp.stack(toks_rounds), jnp.stack(n_rounds))
                )  # [R, B, K+1], [R, B]
            except RuntimeError:
                toks_all = self._host(jnp.stack(toks_rounds))
                n_all = self._host(jnp.stack(n_rounds))
        self._n_decode_dispatches += self._spec_rounds
        self._n_spec_dispatches += self._spec_rounds
        self._n_spec_chains += 1
        self._busy_s += time.perf_counter() - t0
        from cake_tpu.runtime import speculative as _spec_obs

        # device proposer — actual per-row proposal lengths never reach the
        # host, so proposed is the K×rows×rounds upper bound (accept_rate is
        # a lower bound on the chain path, exact on the per-round path)
        _spec_obs.record_acceptance(
            self._spec_k * len(live) * n_all.shape[0],
            int(sum(max(0, int(n_all[r, i]) - 1)
                    for r in range(n_all.shape[0]) for i in live)))
        for i in live:
            self._spec_bank[i] = [
                int(t)
                for r in range(n_all.shape[0])
                for t in toks_all[r, i, : n_all[r, i]]
            ]
        adv = n_all.sum(axis=0)
        self._pos = np.asarray(self._pos) + adv
        self._index = np.asarray(self._index) + adv
        self._last_tokens = last
        self._spec_ctx = ctx
        self._spec_ctx_pos = np.asarray(self._pos).copy()

    def _emit_spec_bank(self) -> list:
        row = np.zeros((len(self.streams),), np.int64)
        skip = []
        for i, bank in enumerate(self._spec_bank):
            if bank:
                row[i] = bank.pop(0)
                skip.append(False)
            else:
                skip.append(True)
        return self._emit(row, skip=skip)

    def _pick_prefill(self, t: int):
        """Serialized vs GPipe-pipelined batch prefill: on a staged mesh a
        prompt bucket divisible into num_stages chunks streams through the
        stages concurrently (~S× prompt throughput once the pipeline
        fills, identical results — parallel.pipeline microbatch mode);
        anything else uses the serialized program."""
        S = self.plan.num_stages
        if not self._interleave or S < 2 or t % S or self.plan.sp != 1:
            # sp > 1 prompts ride the ring prefill (GPipe microbatching
            # over a sequence-sharded prompt remains unimplemented — the
            # one schedule x sp combination left)
            return self._prefill
        if self.__prefill_pipelined is None:
            self.__prefill_pipelined = self._pinned(build_sharded_prefill(
                self.config, self.plan, params_like=self.params,
                microbatch=S, kv_quant=self.kv_quant,
            ))
        return self.__prefill_pipelined

    def _pick_decode(self, block: bool):
        """Serialized vs interleaved schedule for this dispatch: the
        interleaved program needs the dp-local batch divisible by the stage
        count; outputs are bit-identical either way."""
        serial = self._decode_block if block else self._decode_single
        il = self._decode_block_il if block else self._decode_single_il
        if il is None:
            return serial
        local = len(self.streams) // self.plan.dp
        return il if local % self.plan.num_stages == 0 else serial

    def _block_prog(self, steps: int):
        """The fused decode program for an adaptive-ladder block size
        (compiled lazily, memoized per (steps, schedule)); the base size
        reuses the constructor's programs."""
        if steps == self.block_size and self._decode_block is not None:
            return self._pick_decode(block=True)
        il_ok = (
            self._decode_single_il is not None
            and (len(self.streams) // self.plan.dp)
            % self.plan.num_stages == 0
        )
        key = (steps, il_ok)
        prog = self.__block_progs.get(key)
        if prog is None:
            if il_ok:
                prog = self._pinned(build_interleaved_decode(
                    self.config, self.settings, self.plan,
                    params_like=self.params, steps=steps,
                    kv_quant=self.kv_quant))
            else:
                prog = self._pinned(build_sharded_decode(
                    self.config, self.settings, self.plan,
                    params_like=self.params, steps=steps, per_row=True,
                    kv_quant=self.kv_quant,
                    logprobs_k=self.logprobs_k, paged=self._paged))
            self.__block_progs[key] = prog
        return prog

    def _pick_block_size(self, live_pos) -> int:
        """Adaptive block size for this dispatch. Base-block behavior when
        the ladder is off. With the ladder on: snap to the base block the
        moment an arrival waits (admission latency stays one base block),
        otherwise dispatch the current ladder rung and double it for next
        time. The window-headroom cap halves back down the ladder so a
        stream near its window edge doesn't buy a dispatch that is mostly
        clamped overrun writes."""
        base = self.block_size
        if self.block_size_max <= base:
            return base
        if self._arrivals or self._staging is not None:
            self._adaptive = base
            return base
        size = self._adaptive
        if self._adaptive < self.block_size_max:
            self._adaptive = min(self._adaptive * 2, self.block_size_max)
        headroom = self.max_seq - int(min(live_pos))
        while size > max(1, base) and size > headroom:
            size //= 2
        return max(size, base)

    def warm_blocks(self) -> None:
        """Compile every adaptive-ladder program against the live batch
        shapes OUTSIDE the serving window (sacrificial state copies are
        donated and discarded; the live state is untouched). Servers and
        benches call this once after set_prompts, for the same reason
        warm_admission exists: a ladder rung's first use must not pay XLA
        compilation mid-serving."""
        if not self.streams:
            raise RuntimeError("set_prompts first")
        size = self.block_size
        while size < self.block_size_max:
            size = min(size * 2, self.block_size_max)
            prog = self._block_prog(size)
            cache = jax.tree.map(lambda x: x.copy(), self.cache)
            out = prog(
                self.params, self._last_tokens, cache,
                jnp.asarray(self._pos), self._keys, self._history,
                self._hist_slot, jnp.asarray(self._index),
                *self._paged_args_warm(size),
            )
            jax.block_until_ready(out)

    def drain(self) -> None:
        """EMIT everything the device has already computed — buffered
        block rows first, then any in-flight lookahead block — without
        dispatching further work. The shutdown / measurement boundary:
        tokens are recorded against their streams and counted immediately
        (same `_emit` path as stepping); the Token rows land in the
        pending queue for any consumer still calling step()."""
        self._domain_stamp.check("BatchGenerator.drain")
        self._drain_buffered_rows()

    def _drain_buffered_rows(self) -> None:
        """Record every device-computed-but-unemitted row (buffered fused
        -block rows, then any in-flight lookahead block) into the pending
        queue — shared by drain(), the admission splice, the import
        attach, and export (all points where a slot's column is about to
        change meaning or the emitted state must be complete)."""
        while self._block_buf:
            self._pending_rows.append(
                self._emit_buffered(self._block_buf.popleft()))
        if self._inflight is not None:
            toks, lpv, lpi, _ = self._inflight
            self._inflight = None
            t0 = time.perf_counter()
            rows = self._host(toks)
            lp = ((self._host(lpv), self._host(lpi))
                  if lpv is not None else None)
            self._busy_s += time.perf_counter() - t0
            for i in range(rows.shape[0]):
                self._pending_rows.append(self._emit(
                    rows[i], lp=(lp[0][i], lp[1][i]) if lp else None))

    def _dispatch_block(self, size: int):
        """Dispatch one fused decode block (async): the device-side state
        (cache / history / feedback token futures) and the host-side
        pos/index advance immediately; the ``[size, B]`` token rows (and
        top-k logprob rows when enabled) return UN-fetched so the caller
        chooses when to pay the host round-trip (the lookahead path
        dispatches the next block first)."""
        with span("decode.dispatch", steps=size, batch=len(self.streams)), \
                self._prof.phase("dispatch"), self._sentinel.decode_phase():
            out = self._block_prog(size)(
                self.params, self._last_tokens, self.cache,
                jnp.asarray(self._pos), self._keys, self._history,
                self._hist_slot, jnp.asarray(self._index),
                *self._paged_args(size),
            )
            if self.logprobs_k:
                (toks, self.cache, self._history, self._hist_slot,
                 lpv, lpi) = out
            else:
                toks, self.cache, self._history, self._hist_slot = out
                lpv = lpi = None
        self._n_decode_dispatches += 1
        self._pos = self._pos + size
        self._index = self._index + size
        self._last_tokens = toks[-1].astype(jnp.int32)
        return toks, lpv, lpi

    def _step_decode(self):
        # Buffered fused-block rows are EARLIER tokens than anything a new
        # spec round would produce: drain them first, or a round that finds
        # proposals mid-drain would emit later tokens ahead of buffered
        # earlier ones and scramble per-stream order (r4 review repro).
        if self._block_buf:
            return self._emit_buffered(self._block_buf.popleft())
        if self._spec_k:
            row = self._spec_emit_or_round()
            if row is not None:
                return row

        # Capacity is per-stream: a finished stream's row keeps advancing
        # (its clamped writes touch only its own cache row, whose output is
        # discarded), so only LIVE streams gate block decode and exhaustion —
        # a long stream hitting its window must not kill shorter ones.
        live = [
            self._pos[i]
            for i, s in enumerate(self.streams)
            if s.active and not s.done
        ]
        if not live:
            return [None] * len(self.streams)
        # Fused-block eligibility is per-row, not batch-global: a stream
        # that fills its window inside the block only clamp-writes its OWN
        # cache row past the frontier (per-row dynamic_update_slice), and
        # _emit marks it done at the window-filling token so the overrun
        # outputs are discarded — one long stream near its edge must not
        # force every stream to single-step dispatches.
        #
        # Constrained streams (attached Guides) pin the WHOLE batch to
        # single-step masked dispatches: the DFA advance is host-side
        # between steps, so a fused block (or a lookahead dispatch) would
        # sample tokens 2..K against a stale mask row. The moment the last
        # constrained stream retires, block/lookahead dispatch resumes.
        constrained = self._guides_live()
        toks = lpv = lpi = None
        if self._inflight is not None:
            toks, lpv, lpi, _ = self._inflight  # consume pipelined block
            self._inflight = None
        elif not constrained:
            can_block = (self._decode_block is not None
                         or self.block_size_max > self.block_size)
            size = self._pick_block_size(live) if can_block else 1
            if size > 1:
                toks, lpv, lpi = self._dispatch_block(size)
        if toks is not None:
            t0 = time.perf_counter()
            size = len(toks)
            if (self._lookahead and not self._arrivals
                    and self._staging is None and not constrained):
                # pipeline the NEXT block before this one's host fetch:
                # EOS/retirement inside the fetched block only discards
                # per-row outputs (the standard overrun invariant)
                nsize = self._pick_block_size(
                    [self._pos[i] for i, s in enumerate(self.streams)
                     if s.active and not s.done]
                )
                if nsize > 1:
                    self._inflight = self._dispatch_block(nsize) + (nsize,)
            with self._prof.phase("sync"):
                rows = self._host(toks)  # [steps, B]
                lp_h = ((self._host(lpv), self._host(lpi))
                        if lpv is not None else None)
            dt = time.perf_counter() - t0
            self._busy_s += dt
            # per-token ms so the series is comparable across block sizes
            self._dispatch_hist.observe(dt * 1e3 / max(1, size))
            rec = obs_flight.recorder()
            if rec.enabled:
                rec.record(
                    kind="decode", total_ms=round(dt * 1e3, 3), steps=size,
                    batch=len(self.streams),
                )
            self._block_buf = deque(
                (rows[i],
                 (lp_h[0][i], lp_h[1][i]) if lp_h is not None else None)
                for i in range(rows.shape[0])
            )
            return self._emit_buffered(self._block_buf.popleft())

        if int(max(live)) >= self.max_seq:  # unreachable: _emit marks
            raise RuntimeError("KV cache exhausted")  # window-full streams done
        t0 = time.perf_counter()
        with span("decode.dispatch", steps=1, batch=len(self.streams)):
            args = (
                self.params, self._last_tokens, self.cache,
                jnp.asarray(self._pos), self._keys, self._history,
                self._hist_slot, jnp.asarray(self._index),
            )
            with self._prof.phase("dispatch"), \
                    self._sentinel.decode_phase():
                if constrained:
                    # gather-and-mask runs inside this compiled program;
                    # the per-slot row vector is the only per-step upload
                    out = self._decode_single_masked(
                        *args, self._mask_table,
                        jnp.asarray(self._mask_rows_np()),
                        *self._paged_args(1),
                    )
                else:
                    out = self._pick_decode(block=False)(
                        *args, *self._paged_args(1))
            if self.logprobs_k:
                (tok, self.cache, self._history, self._hist_slot,
                 lpv_d, lpi_d) = out
            else:
                tok, self.cache, self._history, self._hist_slot = out
                lpv_d = lpi_d = None
            # sync: dispatch is async, busy_s needs compute
            with self._prof.phase("sync"):
                row = self._host(tok)
                lp_h = ((self._host(lpv_d), self._host(lpi_d))
                        if lpv_d is not None else None)
        self._n_decode_dispatches += 1
        dt = time.perf_counter() - t0
        self._busy_s += dt
        self._dispatch_hist.observe(dt * 1e3)
        rec = obs_flight.recorder()
        if rec.enabled:
            rec.record(
                kind="decode", total_ms=round(dt * 1e3, 3), steps=1,
                batch=len(self.streams),
            )
        self._pos = self._pos + 1
        self._index = self._index + 1
        self._last_tokens = tok.astype(jnp.int32)
        return self._emit(row, lp=lp_h)

    def stats(self) -> dict:
        """Serving counters (the reference's worker ops/s + master tok/s
        observability, on the batch plane): dispatch counts, emitted
        tokens, dispatch-busy seconds vs wall clock, aggregate tok/s, and
        tokens-per-dispatch (the dispatch-amortization the fused block and
        admission interleave buy)."""
        wall = (time.perf_counter() - self._t_start
                if self._t_start is not None else 0.0)
        dispatches = self._n_decode_dispatches + self._n_admit_dispatches
        return {
            "streams_live": sum(
                1 for s in self.streams if s.active and not s.done
            ),
            "streams_done": sum(
                1 for s in self.streams if s.active and s.done
            ),
            "pending_admissions": self.pending_admissions(),
            "constrained_live": sum(
                1 for i in self._guides
                if self.streams[i].active and not self.streams[i].done
            ),
            "tokens_emitted": self._n_emitted,
            "decode_dispatches": self._n_decode_dispatches,
            "admit_dispatches": self._n_admit_dispatches,
            "prefix_hits": self._prefix_hits,
            "prefix_entries": (
                len(self._prefix_tree) if self._paged
                and self._prefix_tree is not None
                else len(self._prefix_store)
            ),
            "kv_layout": "paged" if self._paged else "slot",
            **({"kvpool": self._pagepool.stats(),
                "imports_pending": self.imports_pending()}
               if self._paged and self._pagepool is not None else {}),
            "spec_dispatches": self._n_spec_dispatches,
            "spec_chains": self._n_spec_chains,
            "tokens_per_dispatch": (
                round(self._n_emitted / dispatches, 2) if dispatches else None
            ),
            "dispatch_p50_ms": round(self._dispatch_hist.percentile(0.5), 3),
            "dispatch_p99_ms": round(self._dispatch_hist.percentile(0.99), 3),
            "busy_s": round(self._busy_s, 3),
            "wall_s": round(wall, 3),
            "aggregate_tok_s": (
                round(self._n_emitted / wall, 2) if wall > 0 else None
            ),
        }

    def generate(self, max_new_tokens: int) -> list[list[int]]:
        """Run all streams to EOS or ``max_new_tokens`` MORE tokens each
        (repeated calls continue where the last left off); returns
        per-stream generated ids (active streams only, in prompt order).
        With batched speculation the emission is ragged (a stream banks
        1..K+1 accepted tokens per dispatch), so the loop runs until every
        live stream has this call's quota instead of a fixed step count —
        identical behavior on the plain one-token-per-step path. A stream
        admitted into a slot mid-call starts its quota from zero."""
        start = {i: (s, len(s.generated))
                 for i, s in enumerate(self.streams)}

        def quota_met() -> bool:
            for i, s in enumerate(self.streams):
                if not s.active or s.done:
                    continue
                s0, b = start.get(i, (None, 0))
                base = b if s0 is s else 0
                if len(s.generated) - base < max_new_tokens:
                    return False
            return True

        # Worst-case steps per slow-stream token: draining another stream's
        # full K+1 bank costs up to spec_k+1 step() calls while the slow
        # stream gains one token — size the safety cap to that skew, not
        # just 2x (r4 review: a 2-stream spec_k=8 run could hit the old
        # 2x cap and silently under-deliver).
        per_tok = max(2, self._spec_k + 2)
        cap = per_tok * max_new_tokens * max(1, len(self.streams)) + 8
        for _ in range(cap):
            if quota_met():
                break
            self.step()
        out = []
        for i, s in enumerate(self.streams):
            if not s.active:
                continue
            s0, b = start.get(i, (None, 0))
            base = b if s0 is s else 0
            out.append(s.generated[: base + max_new_tokens])
        return out

    def texts(self) -> list[str | None]:
        """Each active stream's full generated text (None w/o tokenizer)."""
        return [
            self.tokenizer.decode(s.generated) if self.tokenizer else None
            for s in self.streams
            if s.active
        ]
