"""Generator over the single-program on-pod mesh pipeline.

This is the third execution path behind the Generator-trait surface
(`model/mod.rs:21-29`): ``LlamaGenerator`` runs all-local, the
``DistributedGenerator`` walks cross-host runners the way the reference
master walks Forwarders (llama.rs:88-119), and this one compiles the whole
per-token step over a ``(dp, stage, sp, tp)`` device mesh
(parallel/pipeline.py) so stage hops are ICI ``ppermute``s inside one XLA
program instead of per-token RPCs.

Use when all devices are visible to one process (a TPU slice): the
reference's layer-range semantics collapse into the stage axis
(parallel/mesh.py:MeshPlan.from_topology maps a uniform topology onto it).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cake_tpu.models.config import LlamaConfig
from cake_tpu.ops import sampling
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.parallel.mesh import (
    MeshPlan,
    init_cache_on_mesh,
    shard_params,
)
from cake_tpu.parallel.pipeline import (
    build_sharded_decode,
    build_sharded_prefill,
)
from cake_tpu.runtime.generator import GeneratorBase, Token, _bucket


class MeshGenerator(GeneratorBase):
    """Single-stream generator whose per-token step is one compiled program
    over a device mesh. ``params`` may live on host or a single device; they
    are sharded onto the mesh here."""

    def __init__(
        self,
        config: LlamaConfig,
        params,
        plan: MeshPlan | None = None,
        tokenizer=None,
        settings: SamplerSettings | None = None,
        max_seq: int | None = None,
        num_stages: int = 1,
        tp: int = 1,
        sp: int = 1,
        ep: int = 1,
        devices=None,
        block_size: int = 1,
        prefill_chunks: int = 1,
        kv_quant: str | None = None,
    ):
        """``block_size > 1`` runs K pipeline+sample steps inside the one
        compiled mesh program per dispatch (build_sharded_decode steps=K) and
        streams the buffered tokens. The sampling key schedule folds the
        absolute token index — the same schedule as the local and
        distributed paths — so one seed yields one stochastic stream
        regardless of sharding or block size (modulo the dp fold, identity
        at dp=1).

        ``prefill_chunks = M > 1`` (stages > 1, sp == 1) pipelines the
        prompt pass: M chunks stream through the stages concurrently
        (GPipe-style), ~stages× prefill/TTFT throughput, identical tokens."""
        super().__init__(config, tokenizer, settings, max_seq)
        if plan is None:
            plan = MeshPlan.build(
                config, num_stages=num_stages, tp=tp, dp=1, sp=sp, ep=ep,
                devices=devices,
            )
        if plan.dp != 1:
            raise ValueError(
                "MeshGenerator is single-stream; build the plan with dp=1"
            )
        if self.max_seq % plan.sp:
            raise ValueError(
                f"max_seq {self.max_seq} not divisible by sp {plan.sp}"
            )
        self.plan = plan
        self.block_size = max(1, block_size)
        self.prefill_chunks = max(1, prefill_chunks)
        if self.prefill_chunks > 1 and plan.sp != 1:
            raise ValueError("prefill_chunks (pipelined prefill) requires "
                             "sp == 1")
        # max_seq must divide into chunks: otherwise the chunk round-up of a
        # max_seq-capped bucket would push t_pad past the cache window and
        # clamp-write shifted KV rows (silently wrong logits)
        if self.max_seq % self.prefill_chunks:
            raise ValueError(
                f"max_seq {self.max_seq} not divisible by prefill_chunks "
                f"{self.prefill_chunks}"
            )
        self.kv_quant = kv_quant
        self.params = shard_params(params, plan.mesh)
        # allocated per-shard on its owner device (multi-host-valid: no
        # host zeros device_put to non-addressable shards)
        self.cache = init_cache_on_mesh(config, plan.mesh, batch=1,
                                        max_seq=self.max_seq, quant=kv_quant)
        self._prefill = build_sharded_prefill(
            config, plan, params_like=self.params,
            microbatch=self.prefill_chunks, kv_quant=kv_quant,
        )
        self._decode_single = build_sharded_decode(
            config, self.settings, plan, params_like=self.params,
            kv_quant=kv_quant,
        )
        self._decode_block = (
            build_sharded_decode(config, self.settings, plan,
                                 params_like=self.params,
                                 steps=self.block_size, kv_quant=kv_quant)
            if self.block_size > 1 else None
        )

    def next_token(self, index: int) -> Token:
        if index == 0:
            self._require_prompt()
            n = len(self._prompt_tokens)
            # Bucketed prefill lengths keep compile count O(log max_seq).
            # With sp the bucket must also divide into equal per-shard
            # chunks: ring attention + the chunked cache write
            # (ring.sp_chunked_cache_write) then cost prompt-proportional
            # FLOPs/traffic instead of window-proportional.
            t_pad = _bucket(n, self.max_seq)
            if t_pad % self.plan.sp:
                t_pad += self.plan.sp - t_pad % self.plan.sp
            if t_pad % self.prefill_chunks:
                t_pad += self.prefill_chunks - t_pad % self.prefill_chunks
            padded = self._prompt_tokens + [0] * (t_pad - n)
            tokens = jnp.asarray([padded], jnp.int32)
            logits, self.cache = self._prefill(
                self.params, tokens, self.cache,
                jnp.asarray([n - 1], jnp.int32),
            )
            step_key = jax.random.fold_in(self._key, 0)
            tok = sampling.sample_token(
                logits[0], step_key, self._history, self.settings
            )
            self._history, self._hist_slot = sampling.push_history(
                self._history, self._hist_slot, tok
            )
            self._pos = n
            tok_id = int(tok)
        else:
            return self._decode_next(index, self._run_block, self._run_single)
        return self._finish_token(tok_id)

    def _run_block(self, index: int) -> list[int]:
        toks, self.cache, history2d, self._hist_slot = self._decode_block(
            self.params,
            jnp.asarray([self._last_token], jnp.int32),
            self.cache,
            jnp.int32(self._pos),
            self._key,  # program folds fold_in(key, index0 + i) per step
            self._history[None, :],
            self._hist_slot,
            jnp.int32(index),
        )
        self._history = history2d[0]
        self._pos += self.block_size
        return [int(t[0]) for t in toks]

    def _run_single(self, index: int) -> int:
        tok, self.cache, history2d, self._hist_slot = self._decode_single(
            self.params,
            jnp.asarray([self._last_token], jnp.int32),
            self.cache,
            jnp.int32(self._pos),
            jax.random.fold_in(self._key, index),
            self._history[None, :],
            self._hist_slot,
        )
        self._history = history2d[0]
        self._pos += 1
        return int(tok[0])
