"""Wire transport binding: C++ framed-socket library with Python fallback.

The native library (`native/cake_wire.cc`) is the C++ equivalent of the
reference's Rust proto plane (framing magic + length + payload + size cap,
proto/mod.rs:4-7, message.rs:118-155) plus a CRC32 trailer. This module loads
it via ctypes (auto-building with g++ on first use) and exposes blocking
send/recv of ``(msg_type, payload bytes)`` frames. A pure-Python fallback
implements the identical frame format so the two interoperate; the native
path is the default, the fallback exists for environments without a
toolchain.
"""

from __future__ import annotations

import ctypes
import os
import socket
import struct
import subprocess
import threading
import time
import zlib
from pathlib import Path

from cake_tpu.obs import metrics as _metrics

MAGIC = 0x7CA4E701
MAX_PAYLOAD = 512 * 1024 * 1024
_HEADER = struct.Struct("<IBI")  # magic, msg_type, payload_len

_REPO_ROOT = Path(__file__).resolve().parents[2]
_SRC = _REPO_ROOT / "native" / "cake_wire.cc"
_SO = _REPO_ROOT / "native" / "libcakewire.so"
_BUILD_LOCK = threading.Lock()

_lib = None
_lib_tried = False
# Lock discipline, machine-checked by `make lint` (cakelint CK-LOCK):
# the lazy-loader globals may only be touched under the build lock.
_GUARDED_BY = {"_lib": "_BUILD_LOCK", "_lib_tried": "_BUILD_LOCK"}


def _build_native() -> bool:
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", str(_SO), str(_SRC)],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


def native_lib():
    """Load (building if needed) the native wire library, or None."""
    global _lib, _lib_tried
    with _BUILD_LOCK:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        stale = _SO.exists() and _SRC.exists() and (
            _SO.stat().st_mtime < _SRC.stat().st_mtime
        )
        if not _SO.exists() or stale:
            # (re)build only when the source is present; a prebuilt .so
            # shipped without sources is used as-is
            if not _SRC.exists() or not _build_native():
                return None
        try:
            lib = ctypes.CDLL(str(_SO))
        except OSError:
            # existing binary unloadable (e.g. built for another arch):
            # rebuild from source and retry once
            if not _SRC.exists() or not _build_native():
                return None
            try:
                lib = ctypes.CDLL(str(_SO))
            except OSError:
                return None
        lib.cw_connect.argtypes = [ctypes.c_char_p, ctypes.c_uint16, ctypes.c_int]
        lib.cw_connect.restype = ctypes.c_int
        lib.cw_listen.argtypes = [ctypes.c_char_p, ctypes.c_uint16, ctypes.c_int]
        lib.cw_listen.restype = ctypes.c_int
        lib.cw_accept.argtypes = [ctypes.c_int]
        lib.cw_accept.restype = ctypes.c_int
        lib.cw_local_port.argtypes = [ctypes.c_int]
        lib.cw_local_port.restype = ctypes.c_int
        lib.cw_close.argtypes = [ctypes.c_int]
        if hasattr(lib, "cw_set_timeout"):
            # absent only in a prebuilt pre-deadline .so shipped without
            # sources; recv deadlines then degrade to blocking reads
            lib.cw_set_timeout.argtypes = [ctypes.c_int, ctypes.c_int]
            lib.cw_set_timeout.restype = ctypes.c_int
        lib.cw_send_msg.argtypes = [
            ctypes.c_int, ctypes.c_uint8,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32,
        ]
        lib.cw_send_msg.restype = ctypes.c_int
        lib.cw_recv_msg.argtypes = [
            ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.cw_recv_msg.restype = ctypes.c_int
        lib.cw_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        _lib = lib
        return _lib


class WireError(Exception):
    pass


class PeerClosed(WireError):
    pass


class WireTimeout(WireError):
    """A recv/send deadline expired mid-exchange. The connection is
    unusable afterwards (the frame stream may be cut mid-frame); callers
    recover by reconnecting — which is exactly what the master's
    reconnect+replay machinery does with any WireError."""


# Frame-level traffic series, counted in this wrapper so the native and
# pure-Python framings share one set of numbers (payload bytes, not
# header/CRC overhead — comparable with the worker's per-op byte counters).
_FRAMES_OUT = _metrics.counter("wire.frames_out")
_FRAMES_IN = _metrics.counter("wire.frames_in")
_BYTES_OUT = _metrics.counter("wire.bytes_out")
_BYTES_IN = _metrics.counter("wire.bytes_in")
_CRC_FAILURES = _metrics.counter("wire.crc_failures")
# frame-size distribution (p50/p99 payload bytes): tells a tuner whether
# traffic is dominated by tiny control frames or tensor payloads
_FRAME_BYTES = _metrics.histogram("wire.frame_bytes",
                                  buckets=_metrics.BYTES_BUCKETS)

_ERRORS = {
    -1: "io error",
    -2: "peer closed",
    -3: "resolve failed",
    -4: "connect failed",
    -5: "bind failed",
    -6: "listen failed",
    -7: "payload exceeds 512 MiB cap",
    -8: "bad magic",
    -9: "crc mismatch",
    -10: "out of memory",
    -11: "recv deadline expired",
}

_TIMEOUTS = _metrics.counter("wire.timeouts")


def _raise(code: int):
    if code == -9:
        _CRC_FAILURES.inc()
    if code == -2:
        raise PeerClosed(_ERRORS[-2])
    if code == -11:
        _TIMEOUTS.inc()
        raise WireTimeout(_ERRORS[-11])
    raise WireError(_ERRORS.get(code, f"wire error {code}"))


def _set_keepalive(sock: socket.socket) -> None:
    """TCP keepalive on the Python transport (the native lib arms its own
    in cw_connect/cw_accept): a peer that vanished without a FIN must
    eventually fault the connection instead of pinning a blocked recv —
    and, worker-side, that connection's KV caches — forever."""
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
    for opt, val in (("TCP_KEEPIDLE", 60), ("TCP_KEEPINTVL", 10),
                     ("TCP_KEEPCNT", 3)):
        if hasattr(socket, opt):  # Linux; other platforms keep OS defaults
            try:
                sock.setsockopt(socket.IPPROTO_TCP, getattr(socket, opt), val)
            except OSError:
                pass


# recv(timeout=...) sentinel: "use the connection's default deadline"
# (None must stay expressible as an explicit block-forever)
_DEFAULT = object()


class Connection:
    """One framed duplex connection (native fd or Python socket)."""

    def __init__(self, fd: int | None = None, sock: socket.socket | None = None,
                 timeout_s: float | None = None):
        self._fd = fd
        self._sock = sock
        self._lib = native_lib() if fd is not None else None
        # Default recv/send deadline (seconds; None = block forever).
        # Outbound connections default this to their CONNECT timeout — a
        # peer that accepted the connection but then wedged (worker hung in
        # a driver call, half-open socket) faults instead of blocking the
        # caller forever (the seed's settimeout(None) hole). Accepted
        # connections keep None: a worker legitimately waits indefinitely
        # for the master's next request, and keepalive covers dead peers.
        self.timeout_s = timeout_s
        self._applied_s: float | None = None  # deadline currently on the fd
        # perf_counter stamped as each frame lands — the clock-offset
        # estimator's t1 (reading it inside recv() keeps Python-side
        # dispatch jitter out of the RTT the offset error is bounded by)
        self.last_recv_t = 0.0

    @property
    def is_native(self) -> bool:
        return self._fd is not None

    def _apply_timeout(self, t: float | None) -> None:
        """Arm deadline ``t`` on the fd if it differs from what's already
        set (one syscall per change, not per recv)."""
        if t == self._applied_s:
            return
        # only None disables the deadline; 0/negative clamp to a minimal
        # 1 ms one on BOTH transports (0 would mean "no timeout" to
        # SO_RCVTIMEO but non-blocking mode to settimeout — neither is
        # what a caller asking for a deadline meant)
        if self._fd is not None:
            if hasattr(self._lib, "cw_set_timeout"):
                ms = 0 if t is None else max(1, int(t * 1000))
                self._lib.cw_set_timeout(self._fd, ms)
        else:
            self._sock.settimeout(None if t is None else max(t, 1e-3))
        self._applied_s = t

    # -- send/recv ----------------------------------------------------------
    def send(self, msg_type: int, payload=b"") -> None:
        """Send one frame. ``payload`` is a bytes-like object or a sequence
        of them (the zero-copy path: protocol.encode_*_parts hand back
        memoryviews over tensor storage, and the Python transport passes
        them straight to ``sendmsg`` — a multi-MB activation is never
        copied into a contiguous frame)."""
        parts = (
            [memoryview(payload)]
            if isinstance(payload, (bytes, bytearray, memoryview))
            else [memoryview(p) for p in payload]
        )
        plen = sum(len(p) for p in parts)
        if plen > MAX_PAYLOAD:
            raise WireError(_ERRORS[-7])
        # a blocked send is the same failure domain as a blocked recv (a
        # blackholed peer stops draining and the socket buffer fills), so
        # the connection's default deadline bounds it too
        self._apply_timeout(self.timeout_s)
        if self._fd is not None:
            # the native ABI takes one contiguous buffer; join only here
            buf = None
            if plen:
                payload = parts[0] if len(parts) == 1 else b"".join(parts)
                buf = (ctypes.c_uint8 * plen).from_buffer_copy(payload)
            rc = self._lib.cw_send_msg(self._fd, msg_type, buf, plen)
            if rc < 0:
                _raise(rc)
        else:
            crc = zlib.crc32(bytes([msg_type]))
            for p in parts:
                crc = zlib.crc32(p, crc)
            header = _HEADER.pack(MAGIC, msg_type, plen)
            trailer = struct.pack("<I", crc)
            try:
                self._send_parts([memoryview(header), *parts,
                                  memoryview(trailer)])
            except TimeoutError:
                _raise(-11)
        # counted only after the frame went out whole, so the series never
        # exceeds what the peer could have seen (a failed mid-stream send
        # would otherwise skew bytes_out vs the peer's bytes_in in exactly
        # the recovery scenarios these counters exist to diagnose)
        _FRAMES_OUT.inc()
        _BYTES_OUT.inc(plen)
        _FRAME_BYTES.observe(plen)

    def _send_parts(self, parts: list) -> None:
        """Gather-write a buffer sequence (``sendmsg``), advancing across
        partial sends; falls back to sendall on sockets without sendmsg."""
        if not hasattr(self._sock, "sendmsg"):
            self._sock.sendall(b"".join(parts))
            return
        while parts:
            sent = self._sock.sendmsg(parts)
            while parts and sent >= len(parts[0]):
                sent -= len(parts[0])
                parts.pop(0)
            if parts and sent:
                parts[0] = parts[0][sent:]

    def recv(self, timeout=_DEFAULT) -> tuple[int, bytes]:
        """Receive one frame. ``timeout`` (seconds) is a QUIESCENCE
        deadline — SO_RCVTIMEO semantics, armed per socket read, so it
        fires when the peer goes silent that long (the wedged-peer case),
        not as a total-transfer bound for a slow-but-moving frame.
        Omitted it falls back to the connection's default deadline
        (``timeout_s``); ``None`` explicitly blocks forever. Expiry
        raises :class:`WireTimeout` and poisons the connection (the frame
        stream may be cut mid-frame) — reconnect to keep using the peer."""
        self._apply_timeout(self.timeout_s if timeout is _DEFAULT else timeout)
        if self._fd is not None:
            out = ctypes.POINTER(ctypes.c_uint8)()
            ln = ctypes.c_uint32()
            rc = self._lib.cw_recv_msg(self._fd, ctypes.byref(out), ctypes.byref(ln))
            if rc < 0:
                _raise(rc)
            self.last_recv_t = time.perf_counter()
            try:
                data = ctypes.string_at(out, ln.value) if ln.value else b""
            finally:
                if ln.value:
                    self._lib.cw_free(out)
            _FRAMES_IN.inc()
            _BYTES_IN.inc(len(data))
            return rc, data
        else:
            try:
                header = self._read_exact(_HEADER.size)
                magic, msg_type, plen = _HEADER.unpack(header)
                if magic != MAGIC:
                    _raise(-8)
                if plen > MAX_PAYLOAD:
                    _raise(-7)
                payload = self._read_exact(plen) if plen else b""
                (want_crc,) = struct.unpack("<I", self._read_exact(4))
            except TimeoutError:
                _raise(-11)
            self.last_recv_t = time.perf_counter()
            crc = zlib.crc32(bytes([msg_type]))
            crc = zlib.crc32(payload, crc)
            if crc != want_crc:
                _raise(-9)
            _FRAMES_IN.inc()
            _BYTES_IN.inc(len(payload))
            return msg_type, payload

    def _read_exact(self, n: int) -> bytes:
        chunks = []
        got = 0
        while got < n:
            chunk = self._sock.recv(n - got)
            if not chunk:
                raise PeerClosed(_ERRORS[-2])
            chunks.append(chunk)
            got += len(chunk)
        return b"".join(chunks)

    def close(self) -> None:
        if self._fd is not None:
            self._lib.cw_close(self._fd)
            self._fd = None
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def connect(host: str, port: int, timeout_ms: int = 10000,
            force_python: bool = False) -> Connection:
    """Connect with ``timeout_ms`` bounding the TCP connect AND serving as
    the connection's default per-recv deadline (a hung peer then faults as
    :class:`WireTimeout` instead of blocking forever); callers with slower
    exchanges pass a larger per-call ``recv(timeout=...)``."""
    default_s = timeout_ms / 1000 if timeout_ms and timeout_ms > 0 else None
    lib = None if force_python else native_lib()
    if lib is not None:
        fd = lib.cw_connect(host.encode(), port, timeout_ms)
        if fd >= 0:
            return Connection(fd=fd, timeout_s=default_s)
        _raise(fd)
    sock = socket.create_connection((host, port), timeout=timeout_ms / 1000)
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _set_keepalive(sock)
        sock.settimeout(None)
    except Exception:
        # option setup failing must not leak the connected fd
        sock.close()
        raise
    return Connection(sock=sock, timeout_s=default_s)


class Listener:
    """Framed-connection acceptor (native or Python)."""

    def __init__(self, addr: str = "0.0.0.0", port: int = 0,
                 force_python: bool = False):
        lib = None if force_python else native_lib()
        if lib is not None:
            fd = lib.cw_listen(addr.encode(), port, 16)
            if fd < 0:
                _raise(fd)
            self._fd, self._sock, self._lib = fd, None, lib
            self.port = lib.cw_local_port(fd)
        else:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            try:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind((addr, port))
                s.listen(16)
            except Exception:
                # a failed bind (port in use) must not leak the fd
                s.close()
                raise
            self._fd, self._sock, self._lib = None, s, None
            self.port = s.getsockname()[1]

    def accept(self) -> Connection:
        if self._fd is not None:
            fd = self._lib.cw_accept(self._fd)
            if fd < 0:
                _raise(fd)
            return Connection(fd=fd)
        conn, _ = self._sock.accept()
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _set_keepalive(conn)
        except Exception:
            conn.close()
            raise
        # accepted side keeps no default recv deadline: a server waits
        # indefinitely for the peer's next request; keepalive bounds the
        # dead-peer case
        return Connection(sock=conn)

    def close(self) -> None:
        if self._fd is not None:
            self._lib.cw_close(self._fd)
            self._fd = None
        if self._sock is not None:
            self._sock.close()
            self._sock = None
