"""Message schema over the wire transport.

Equivalent of the reference's `Message` enum + `RawTensor`
(proto/message.rs:11-76): Hello / WorkerInfo / SingleOp / Batch / Tensor —
plus an explicit Error message (the reference just drops the connection,
worker.rs:180,256-258). The reference serializes with the Rust-specific
``bitcode`` (chosen over gRPC for speed, message.rs:104-105); here the
payloads are a fixed little-endian binary layout for tensors (schema below)
and JSON for the small control structures — language-neutral, zero-copy on
the tensor bytes, no codegen.

Tensor payload layout (little-endian):
  u8 dtype_code | u8 ndim | u32 dims[ndim] | raw bytes (C-order)

On-pod activations never use this path (they ride ICI inside the compiled
pipeline program); this is the cross-host control/data plane between the
master CLI and TPU-VM workers, where the reference's TCP semantics survive.
"""

from __future__ import annotations

import dataclasses
import json
import platform
import struct
from enum import IntEnum

import numpy as np

from cake_tpu import __version__


class MsgType(IntEnum):
    HELLO = 1
    WORKER_INFO = 2
    SINGLE_OP = 3
    BATCH = 4
    TENSOR = 5
    ERROR = 6
    GOODBYE = 7


# dtype codes (u8). bf16 rides as raw uint16 payloads with its own code.
_DTYPES: list[tuple[int, str]] = [
    (0, "float32"),
    (1, "bfloat16"),
    (2, "float16"),
    (3, "int32"),
    (4, "int8"),
    (5, "uint8"),
    (6, "int64"),
]
_CODE_TO_NAME = {c: n for c, n in _DTYPES}
_NAME_TO_CODE = {n: c for c, n in _DTYPES}


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def encode_tensor(x) -> bytes:
    """numpy (or jax-convertible) array -> wire bytes."""
    arr = np.asarray(x)
    name = arr.dtype.name if arr.dtype.name in _NAME_TO_CODE else str(arr.dtype)
    if name not in _NAME_TO_CODE:
        raise ValueError(f"unsupported wire dtype {arr.dtype}")
    header = struct.pack("<BB", _NAME_TO_CODE[name], arr.ndim)
    dims = struct.pack(f"<{arr.ndim}I", *arr.shape)
    return header + dims + np.ascontiguousarray(arr).tobytes()


def decode_tensor(buf: bytes) -> np.ndarray:
    code, ndim = struct.unpack_from("<BB", buf, 0)
    if code not in _CODE_TO_NAME:
        raise ValueError(f"unknown dtype code {code}")
    dims = struct.unpack_from(f"<{ndim}I", buf, 2)
    off = 2 + 4 * ndim
    dt = _np_dtype(_CODE_TO_NAME[code])
    expect = int(np.prod(dims)) * dt.itemsize if ndim else dt.itemsize
    data = buf[off:]
    if len(data) != expect:
        raise ValueError(
            f"tensor payload size {len(data)} != expected {expect} for "
            f"shape {dims} {dt}"
        )
    return np.frombuffer(data, dtype=dt).reshape(dims)


@dataclasses.dataclass
class WorkerInfo:
    """Capability/identity exchange (proto/message.rs:37-53): version, os,
    arch, device kind, latency (filled by the client from the handshake RTT,
    client.rs:41-47), dtype, plus the layers this worker serves."""

    name: str
    version: str = __version__
    os: str = dataclasses.field(default_factory=platform.system)
    arch: str = dataclasses.field(default_factory=platform.machine)
    device: str = ""
    # ordinal of the serving device within the worker process (the reference
    # carries the CUDA ordinal as `device_idx`, proto/message.rs:37-53)
    device_idx: int = 0
    dtype: str = ""
    latency_ms: float = 0.0
    layers: list[str] = dataclasses.field(default_factory=list)
    # KV capacity of this worker's caches; the master rejects a mismatch at
    # handshake (a silently smaller worker cache would clamp KV writes once
    # pos exceeds it and corrupt generation).
    max_seq: int = 0

    def to_bytes(self) -> bytes:
        return json.dumps(dataclasses.asdict(self)).encode()

    @classmethod
    def from_bytes(cls, buf: bytes) -> "WorkerInfo":
        d = json.loads(buf.decode())
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    def __str__(self) -> str:
        return (
            f"{self.name}@{self.device or '?'}:{self.device_idx} "
            f"v{self.version} ({self.os}/{self.arch}, {self.dtype}, "
            f"latency {self.latency_ms:.1f}ms, {len(self.layers)} layers)"
        )


def encode_ops(x: np.ndarray, ops: list[tuple[str, int]]) -> bytes:
    """Batch payload: JSON op list (layer_name, index_pos) + tensor.

    The reference `Batch` carries ``Vec<(layer_name, index_pos, block_idx)>``
    (message.rs:57-76); block_idx is recoverable from layer_name so the wire
    format carries just (name, pos)."""
    meta = json.dumps(ops).encode()
    return struct.pack("<I", len(meta)) + meta + encode_tensor(x)


def decode_ops(buf: bytes) -> tuple[np.ndarray, list[tuple[str, int]]]:
    (mlen,) = struct.unpack_from("<I", buf, 0)
    ops = [tuple(o) for o in json.loads(buf[4 : 4 + mlen].decode())]
    x = decode_tensor(buf[4 + mlen :])
    return x, ops


class WorkerOpError(RuntimeError):
    """A worker-reported op failure (MsgType.ERROR reply). Deterministic
    model-side errors — distinct from transport failures (OSError /
    wire.WireError), which warrant reconnect+replay recovery; these do not
    (the same op would fail again after replay)."""


def encode_error(msg: str) -> bytes:
    return msg.encode()


def decode_error(buf: bytes) -> str:
    return buf.decode(errors="replace")
