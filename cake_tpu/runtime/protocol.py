"""Message schema over the wire transport.

Equivalent of the reference's `Message` enum + `RawTensor`
(proto/message.rs:11-76): Hello / WorkerInfo / SingleOp / Batch / Tensor —
plus an explicit Error message (the reference just drops the connection,
worker.rs:180,256-258). The reference serializes with the Rust-specific
``bitcode`` (chosen over gRPC for speed, message.rs:104-105); here the
payloads are a fixed little-endian binary layout for tensors (schema below)
and JSON for the small control structures — language-neutral, zero-copy on
the tensor bytes, no codegen.

Tensor payload layout (little-endian):
  u8 dtype_code | u8 ndim | u32 dims[ndim] | raw bytes (C-order)

On-pod activations never use this path (they ride ICI inside the compiled
pipeline program); this is the cross-host control/data plane between the
master CLI and TPU-VM workers, where the reference's TCP semantics survive.
"""

from __future__ import annotations

import dataclasses
import json
import platform
import struct
from enum import IntEnum

import numpy as np

from cake_tpu import __version__
from cake_tpu.obs import metrics as _metrics


class MsgType(IntEnum):
    HELLO = 1
    WORKER_INFO = 2
    SINGLE_OP = 3
    BATCH = 4
    TENSOR = 5
    ERROR = 6
    GOODBYE = 7
    # Cluster-observability plane (capability-gated: the master only sends
    # these to a worker whose WorkerInfo.caps advertised them; an old
    # worker never sees them and an old master never sends them).
    PING = 8  # clock-offset probe: echo payload + worker perf_counter
    STATS = 9  # registry/status snapshot for workers without a status port


# WorkerInfo.caps entries — what this peer's wire dialect understands
# beyond the seed protocol. Old peers (no field in the handshake JSON)
# default to none of them, so every extension stays opt-in per connection.
CAP_TRACE = "trace"  # OPS trace-context trailer + span-digest replies
CAP_PING = "ping"  # MsgType.PING clock exchange
CAP_STATS = "stats"  # MsgType.STATS snapshot requests
ALL_CAPS = (CAP_TRACE, CAP_PING, CAP_STATS)


# dtype codes (u8). bf16 rides as raw uint16 payloads with its own code.
_DTYPES: list[tuple[int, str]] = [
    (0, "float32"),
    (1, "bfloat16"),
    (2, "float16"),
    (3, "int32"),
    (4, "int8"),
    (5, "uint8"),
    (6, "int64"),
]
_CODE_TO_NAME = {c: n for c, n in _DTYPES}
_NAME_TO_CODE = {n: c for c, n in _DTYPES}


def _np_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _dtype_code(arr: np.ndarray) -> int:
    name = arr.dtype.name if arr.dtype.name in _NAME_TO_CODE else str(arr.dtype)
    if name not in _NAME_TO_CODE:
        raise ValueError(f"unsupported wire dtype {arr.dtype}")
    return _NAME_TO_CODE[name]


def _contig(x) -> np.ndarray:
    arr = np.asarray(x)
    # (ascontiguousarray would promote 0-d to 1-d; only copy when needed)
    return arr if arr.flags["C_CONTIGUOUS"] else np.ascontiguousarray(arr)


def _buf(arr: np.ndarray):
    """Zero-copy byte memoryview over a C-contiguous array's storage (the
    uint8 reinterpret handles dtypes like bfloat16 whose buffer format
    memoryview.cast cannot)."""
    return arr.reshape(-1).view(np.uint8).data


def encode_tensor_parts(x) -> list:
    """numpy (or jax-convertible) array -> [header bytes, data buffer].

    The data part is a memoryview over the array's own storage when it is
    already contiguous — callers that can scatter-gather (wire.Connection
    hands a buffer sequence to ``sendmsg``) ship multi-MB activations with
    zero payload copies; ``encode_tensor`` joins the parts once for callers
    that need one bytes object."""
    arr = _contig(x)
    header = struct.pack("<BB", _dtype_code(arr), arr.ndim) + struct.pack(
        f"<{arr.ndim}I", *arr.shape
    )
    return [header, _buf(arr)]


def encode_tensor(x) -> bytes:
    """numpy (or jax-convertible) array -> wire bytes (one copy: the join;
    the reference's serializer copies per-field, message.rs:104-105)."""
    return b"".join(encode_tensor_parts(x))


def decode_tensor(buf: bytes) -> np.ndarray:
    code, ndim = struct.unpack_from("<BB", buf, 0)
    if code not in _CODE_TO_NAME:
        raise ValueError(f"unknown dtype code {code}")
    dims = struct.unpack_from(f"<{ndim}I", buf, 2)
    off = 2 + 4 * ndim
    dt = _np_dtype(_CODE_TO_NAME[code])
    expect = int(np.prod(dims)) * dt.itemsize if ndim else dt.itemsize
    data = buf[off:]
    if len(data) != expect:
        raise ValueError(
            f"tensor payload size {len(data)} != expected {expect} for "
            f"shape {dims} {dt}"
        )
    return np.frombuffer(data, dtype=dt).reshape(dims)


# -- activation wire codec ---------------------------------------------------
#
# Petals (Borzunov et al., 2022) showed activation compression is the
# enabling trick for pipeline inference over slow links; the reference ships
# raw full-precision tensors every token (llama.rs:100-119). Here the master
# negotiates a per-connection codec at handshake (WorkerInfo.codecs) and the
# worker mirrors whatever codec the request rode in. Encodings are
# self-describing: `none` is the plain tensor layout above (first byte is a
# dtype code < 0x80, so it stays wire-compatible with pre-codec peers);
# compressed layouts open with a marker byte >= 0x80.
#
#   bf16: 0x81 | u8 orig_dtype | tensor(bfloat16)          (~2x on f32)
#   int8: 0x82 | u8 orig_dtype | u8 ndim | u32 dims[ndim]
#         | f32 scales[rows] | i8 q[rows, last_dim]        (~4x on f32)
#
# int8 uses per-row symmetric absmax scales (a row = one token's hidden
# vector for [B, T, H] activations). Integer dtypes pass through as `none`
# under every codec (lossless; quantizing ids would corrupt them).

CODECS = ("none", "bf16", "int8")
_BF16_MARK, _INT8_MARK = 0x81, 0x82


def check_codec(codec: str) -> str:
    """Validate a codec name (shared by the encoder, RemoteRunner, and
    Worker so the accepted set and the error live in one place)."""
    if codec not in CODECS:
        raise ValueError(f"unknown wire codec {codec!r} (know {CODECS})")
    return codec

# pre/post-compression payload bytes: the registry view of what the codec
# saves (flight records carry the per-call split via RemoteRunner.last_call)
_CODEC_RAW = _metrics.counter("wire.codec_bytes_raw")
_CODEC_ENC = _metrics.counter("wire.codec_bytes_encoded")


def encode_activation_parts(x, codec: str = "none") -> list:
    """Activation tensor -> buffer-sequence under ``codec`` (see module
    comment for layouts). Float inputs only compress; integer inputs ride
    the `none` layout regardless of codec."""
    check_codec(codec)
    arr = _contig(x)
    is_float = arr.dtype.kind == "f" or arr.dtype.name == "bfloat16"
    if codec == "none" or not is_float or (
        codec == "bf16" and arr.dtype.itemsize <= 2
    ):
        # 2-byte floats (bf16 itself, f16) gain nothing from the bf16
        # layout — same payload size, and an f16->bf16 cast would LOSE
        # mantissa bits; the none layout ships them verbatim
        parts = encode_tensor_parts(arr)
    elif codec == "bf16":
        import ml_dtypes

        orig = _dtype_code(arr)
        parts = [struct.pack("<BB", _BF16_MARK, orig)]
        parts += encode_tensor_parts(arr.astype(ml_dtypes.bfloat16))
    else:  # int8
        orig = _dtype_code(arr)
        f = np.asarray(arr, np.float32)
        rows = f.reshape(-1, f.shape[-1]) if f.ndim else f.reshape(1, 1)
        absmax = np.max(np.abs(rows), axis=1)
        scales = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
        q = np.clip(np.rint(rows / scales[:, None]), -127, 127).astype(
            np.int8
        )
        header = struct.pack("<BBB", _INT8_MARK, orig, arr.ndim)
        header += struct.pack(f"<{arr.ndim}I", *arr.shape)
        parts = [header, _buf(scales), _buf(q)]
    _CODEC_RAW.inc(arr.nbytes)
    _CODEC_ENC.inc(sum(len(p) for p in parts))
    return parts


def encode_activation(x, codec: str = "none") -> bytes:
    return b"".join(encode_activation_parts(x, codec))


def decode_activation(buf) -> tuple[np.ndarray, str]:
    """Self-describing inverse of :func:`encode_activation`. Returns the
    tensor (in its pre-compression dtype) and the codec it rode in, so a
    worker can mirror the master's choice in its reply."""
    buf = memoryview(buf)
    mark = buf[0]
    if mark < 0x80:
        return decode_tensor(buf), "none"
    if mark == _BF16_MARK:
        orig = _np_dtype(_CODE_TO_NAME[buf[1]])
        return decode_tensor(buf[2:]).astype(orig), "bf16"
    if mark == _INT8_MARK:
        orig_code, ndim = struct.unpack_from("<BB", buf, 1)
        dims = struct.unpack_from(f"<{ndim}I", buf, 3)
        off = 3 + 4 * ndim
        n_rows = int(np.prod(dims[:-1])) if ndim else 1
        last = dims[-1] if ndim else 1
        scales = np.frombuffer(buf, np.float32, count=n_rows, offset=off)
        q = np.frombuffer(buf, np.int8, offset=off + 4 * n_rows)
        if q.size != n_rows * last:
            raise ValueError(
                f"int8 activation payload {q.size} != expected "
                f"{n_rows * last} for shape {dims}"
            )
        x = (q.reshape(n_rows, last).astype(np.float32)
             * scales[:, None]).reshape(dims)
        return x.astype(_np_dtype(_CODE_TO_NAME[orig_code])), "int8"
    raise ValueError(f"unknown activation codec marker 0x{mark:02x}")


def _tensor_nbytes(buf) -> int:
    """Encoded length of the plain tensor layout at the head of ``buf``."""
    code, ndim = struct.unpack_from("<BB", buf, 0)
    if code not in _CODE_TO_NAME:
        raise ValueError(f"unknown dtype code {code}")
    dims = struct.unpack_from(f"<{ndim}I", buf, 2)
    n = int(np.prod(dims)) if ndim else 1
    return 2 + 4 * ndim + n * _np_dtype(_CODE_TO_NAME[code]).itemsize


def activation_nbytes(buf) -> int:
    """Byte length of the self-describing activation encoding at the head
    of ``buf`` — exactly what :func:`decode_activation` would consume. The
    seam that lets a frame carry an optional trailer AFTER the tensor
    (trace context on requests, span digests on replies) while the tensor
    layouts themselves stay byte-identical to pre-trailer peers."""
    buf = memoryview(buf)
    mark = buf[0]
    if mark < 0x80:
        return _tensor_nbytes(buf)
    if mark == _BF16_MARK:
        return 2 + _tensor_nbytes(buf[2:])
    if mark == _INT8_MARK:
        _, ndim = struct.unpack_from("<BB", buf, 1)
        dims = struct.unpack_from(f"<{ndim}I", buf, 3)
        n_rows = int(np.prod(dims[:-1])) if ndim else 1
        last = dims[-1] if ndim else 1
        return 3 + 4 * ndim + 4 * n_rows + n_rows * last
    raise ValueError(f"unknown activation codec marker 0x{mark:02x}")


def split_activation(buf) -> tuple[memoryview, dict | None]:
    """Split an activation payload into (tensor bytes, trailer dict). The
    trailer is whatever JSON follows the self-describing tensor encoding;
    a legacy frame has no leftover and yields ``None`` — the decode side
    needs no capability flag to stay compatible both directions."""
    buf = memoryview(buf)
    alen = activation_nbytes(buf)
    if len(buf) > alen:
        return buf[:alen], json.loads(bytes(buf[alen:]).decode())
    return buf, None


@dataclasses.dataclass
class WorkerInfo:
    """Capability/identity exchange (proto/message.rs:37-53): version, os,
    arch, device kind, latency (filled by the client from the handshake RTT,
    client.rs:41-47), dtype, plus the layers this worker serves."""

    name: str
    version: str = __version__
    os: str = dataclasses.field(default_factory=platform.system)
    arch: str = dataclasses.field(default_factory=platform.machine)
    device: str = ""
    # ordinal of the serving device within the worker process (the reference
    # carries the CUDA ordinal as `device_idx`, proto/message.rs:37-53)
    device_idx: int = 0
    dtype: str = ""
    latency_ms: float = 0.0
    layers: list[str] = dataclasses.field(default_factory=list)
    # KV capacity of this worker's caches; the master rejects a mismatch at
    # handshake (a silently smaller worker cache would clamp KV writes once
    # pos exceeds it and corrupt generation).
    max_seq: int = 0
    # Activation wire codecs this worker accepts (and will mirror in its
    # replies). Defaults to just "none" so a pre-codec peer — whose
    # handshake payload lacks the field — is never credited with
    # compression support it does not have.
    codecs: list[str] = dataclasses.field(default_factory=lambda: ["none"])
    # Wire-dialect extensions (CAP_*). Same old-peer rule as codecs: the
    # default is the empty set, so a peer is only ever sent PING/STATS or
    # trace trailers after it explicitly advertised them.
    caps: list[str] = dataclasses.field(default_factory=list)
    # Port of this worker's live status HTTP page (0 = none running). The
    # master's cluster scraper reaches it at the worker's connection host —
    # the fallback scrape path for a peer without CAP_STATS.
    status_port: int = 0

    def to_bytes(self) -> bytes:
        return json.dumps(dataclasses.asdict(self)).encode()

    @classmethod
    def from_bytes(cls, buf: bytes) -> "WorkerInfo":
        d = json.loads(buf.decode())
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    def __str__(self) -> str:
        return (
            f"{self.name}@{self.device or '?'}:{self.device_idx} "
            f"v{self.version} ({self.os}/{self.arch}, {self.dtype}, "
            f"latency {self.latency_ms:.1f}ms, {len(self.layers)} layers)"
        )


def encode_ops_parts(x, ops: list[tuple[str, int]], codec: str = "none",
                     trace_ctx: dict | None = None) -> list:
    """Batch payload as a buffer sequence: JSON op list (layer_name,
    index_pos) + codec-encoded activation tensor, plus an optional trace
    trailer.

    The reference `Batch` carries ``Vec<(layer_name, index_pos, block_idx)>``
    (message.rs:57-76); block_idx is recoverable from layer_name so the wire
    format carries just (name, pos).

    ``trace_ctx`` is the Dapper-style propagation record — ``{"tid":
    trace_id, "psid": parent_span_id, "seq": n, "pos": p}`` — appended as a
    JSON trailer after the self-describing tensor (CAP_TRACE peers only;
    with ``trace_ctx=None`` the frame is byte-identical to the legacy
    layout)."""
    meta = json.dumps(ops).encode()
    parts = [struct.pack("<I", len(meta)) + meta] + encode_activation_parts(
        x, codec
    )
    if trace_ctx is not None:
        parts.append(json.dumps({"tc": trace_ctx}).encode())
    return parts


def encode_ops(x: np.ndarray, ops: list[tuple[str, int]],
               codec: str = "none", trace_ctx: dict | None = None) -> bytes:
    return b"".join(encode_ops_parts(x, ops, codec, trace_ctx))


def decode_ops_traced(
    buf,
) -> tuple[np.ndarray, list[tuple[str, int]], str, dict | None]:
    """Inverse of :func:`encode_ops`, trailer included: returns
    ``(tensor, ops, codec, trailer)`` where the trailer is the parsed
    trace-context dict (``None`` on a legacy frame) and the codec name is
    what the request's tensor rode in (the worker mirrors it in the
    reply)."""
    buf = memoryview(buf)
    (mlen,) = struct.unpack_from("<I", buf, 0)
    ops = [tuple(o) for o in json.loads(bytes(buf[4 : 4 + mlen]).decode())]
    act, trailer = split_activation(buf[4 + mlen :])
    x, codec = decode_activation(act)
    return x, ops, codec, trailer


def decode_ops(buf) -> tuple[np.ndarray, list[tuple[str, int]], str]:
    """Trailer-blind :func:`decode_ops_traced` (the seed-era signature)."""
    x, ops, codec, _ = decode_ops_traced(buf)
    return x, ops, codec


class WorkerOpError(RuntimeError):
    """A worker-reported op failure (MsgType.ERROR reply). Deterministic
    model-side errors — distinct from transport failures (OSError /
    wire.WireError), which warrant reconnect+replay recovery; these do not
    (the same op would fail again after replay)."""


def encode_error(msg: str) -> bytes:
    return msg.encode()


def decode_error(buf: bytes) -> str:
    return buf.decode(errors="replace")
