"""N-gram speculative decoding (prompt-lookup): multi-token greedy decode.

A capability beyond the reference (whose decode loop is strictly one token
per step, `master.rs:36-48`): propose the next K tokens by matching the
context's trailing n-gram against its own history (prompt-lookup decoding —
no draft model), then *verify* all K in ONE model dispatch and accept the
longest correct prefix plus one bonus token. Greedy output is bit-identical
to plain decode by construction — the model's own (repeat-penalized) argmax
decides every emitted token; proposals only decide how many land per
dispatch.

Why this is TPU-shaped: single-token decode reads every weight byte from
HBM per token (weights-bound, ~85 tok/s for 8B int8 on v5e). Verification
feeds K+1 tokens through the same weights in one pass — the MXU loves the
wider matmuls and the weight read amortizes over every accepted token, so
acceptance rate converts directly into tok/s. On repetitive stretches
(code, quotes, structured text) prompt-lookup acceptance is high; worst
case costs one dispatch per token, like plain decode.

Greedy streams (``temperature == 0``) are bit-identical to plain decode.
Sampled streams (``temperature > 0``, the serving default) use REJECTION
SAMPLING (:func:`accept_sampled_fn`): each emitted token's conditional
distribution given the prefix is exactly the plain sampler's categorical —
distribution-preserving, not sample-path-preserving (a fixed seed yields a
different but identically-distributed stream than plain decode).
"""

from __future__ import annotations

import threading
from collections import deque
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models import llama
from cake_tpu.models.config import LlamaConfig
from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.ops import quant, sampling
from cake_tpu.ops.kvcache import KVCache
from cake_tpu.ops.norms import rms_norm
from cake_tpu.ops.rope import rope_tables
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.generator import LlamaGenerator
from cake_tpu.runtime.mesh_generator import MeshGenerator

# process-wide acceptance accounting: every speculative path (the host
# per-round loop, the fused chain, the single-stream mixin) reports its
# proposal/acceptance totals here, so one pair of counters and one EMA
# gauge describe speculation quality regardless of which engine ran it.
_ACCEPT_EMA_ALPHA = 0.2
_accept_lock = threading.Lock()
_accept_ema: float | None = None


def record_acceptance(proposed: int, accepted: int) -> None:
    """Fold one dispatch's speculation outcome into the process counters:
    ``spec.proposed`` / ``spec.accepted`` plus the ``spec.accept_rate_ema``
    gauge (EMA over dispatches, not tokens — a smoothed answer to "is
    speculation paying for itself right now"). No-op when nothing was
    proposed, so pure-fallback steps don't drag the EMA toward zero."""
    global _accept_ema
    if proposed <= 0:
        return
    obs_metrics.counter("spec.proposed").inc(int(proposed))
    obs_metrics.counter("spec.accepted").inc(int(accepted))
    rate = min(1.0, max(0.0, accepted / proposed))
    with _accept_lock:
        _accept_ema = (rate if _accept_ema is None else
                       _ACCEPT_EMA_ALPHA * rate
                       + (1.0 - _ACCEPT_EMA_ALPHA) * _accept_ema)
        obs_metrics.gauge("spec.accept_rate_ema").set(_accept_ema)


def ngram_propose(context: list[int], n_max: int, k: int) -> list[int]:
    """Propose up to ``k`` continuation tokens by finding the most recent
    earlier occurrence of the context's trailing n-gram (longest n first)
    and copying what followed it. Returns [] when nothing matches."""
    L = len(context)
    if L < 2 or k < 1:
        return []
    arr = np.asarray(context, np.int64)
    for n in range(min(n_max, L - 1), 0, -1):
        pat = arr[L - n:]
        # candidate starts 0..L-1-n: pattern ends before the final position,
        # so a continuation token always exists inside the context
        windows = np.lib.stride_tricks.sliding_window_view(arr[: L - 1], n)
        hits = np.nonzero((windows == pat).all(axis=1))[0]
        if hits.size:
            j = int(hits[-1])
            return arr[j + n: j + n + k].tolist()
    return []


def _verify_forward(params, tokens, cache: KVCache, pos, cos, sin,
                    config: LlamaConfig):
    """The verification forward shared by :func:`verify_fn` (host loop) and
    :func:`spec_rounds_fn` (fused) — ONE definition so the fused path can
    never drift from the host-loop oracle the bit-identity tests pin."""
    x = llama.embed_tokens(params, tokens, config)
    x, cache = llama.forward_layers(params["layers"], x, cache, cos, sin,
                                    pos, config)
    x = rms_norm(x, params["norm_f"], config.rms_norm_eps,
                   offset=config.rms_norm_offset)
    logits = quant.dense(x[0], params["lm_head"]).astype(jnp.float32)
    return logits, cache


def verify_fn(params, tokens, cache: KVCache, pos, config: LlamaConfig):
    """Forward ``tokens [1, T]`` from position ``pos`` returning logits at
    EVERY position (``[T, vocab] f32``) — the speculation-verification pass.
    KV for all T slots is written; slots past the accepted frontier hold
    rejected garbage that later steps overwrite before it becomes
    attendable (the same invariant as bucketed-prefill padding)."""
    cos, sin = rope_tables(config.head_dim, cache.max_seq, config.rope_theta,
                           scaling=config.rope_scaling)
    return _verify_forward(params, tokens, cache, pos, cos, sin, config)


def accept_fn(
    logits,  # [T, vocab] f32 (T = K + 1)
    proposals,  # [K] int32, -1-padded
    history,
    hist_slot,
    eos_ids,  # [E] int32 (-1-padded when fewer)
    settings: SamplerSettings,
):
    """Greedy accept scan. Row ``i``'s (repeat-penalized) argmax ``g_i`` is
    emitted while the stream is alive; the stream stays alive while each
    ``g_i`` equals its proposal and is not EOS. Returns
    ``(tokens [T], count, history, hist_slot)`` — the first ``count``
    tokens are exactly what plain greedy decode would have produced, with
    history advanced by exactly those tokens."""
    k = proposals.shape[0]
    dummy_key = jax.random.PRNGKey(0)  # unused at temperature 0

    def body(carry, i):
        alive, count, history, hist_slot = carry
        g = sampling.sample_token(logits[i], dummy_key, history, settings)
        nh, ns = sampling.push_history(history, hist_slot, g)
        history = jnp.where(alive, nh, history)
        hist_slot = jnp.where(alive, ns, hist_slot)
        count = count + alive.astype(jnp.int32)
        is_eos = (g == eos_ids).any()
        matched = jnp.where(i < k, g == proposals[jnp.minimum(i, k - 1)],
                            False)
        alive = alive & matched & ~is_eos
        return (alive, count, history, hist_slot), g

    (_, count, history, hist_slot), toks = jax.lax.scan(
        body,
        (jnp.asarray(True), jnp.int32(0), history, hist_slot),
        jnp.arange(logits.shape[0], dtype=jnp.int32),
    )
    return toks, count, history, hist_slot


def accept_sampled_fn(
    logits,  # [T, vocab] f32 (T = K + 1)
    proposals,  # [K] int32, -1-padded
    history,
    hist_slot,
    eos_ids,  # [E] int32 (-1-padded when fewer)
    round_key,  # PRNG key for this verification round
    settings: SamplerSettings,
):
    """Rejection-sampling accept scan for ``temperature > 0``.

    The prompt-lookup draft is DETERMINISTIC (q is a point mass on the
    proposal), so the standard speculative-sampling rule (Leviathan et al.;
    Chen et al.) reduces cleanly: accept proposal ``x`` with probability
    ``p(x)`` (p = the plain sampler's penalized/temperature-scaled/top-k/
    top-p categorical, via ``sampling.processed_logits``); on rejection,
    sample the replacement from the residual ``norm(max(p - q, 0))`` — p
    with the proposal's mass zeroed. If all K proposals are accepted, the
    bonus row samples from its p directly. Per emitted token the
    conditional distribution given the prefix is exactly p: acceptance
    contributes ``p(x)·1[y=x]`` and rejection ``(1-p(x))·p(y)/(1-p(x))``
    for ``y != x``.

    Returns ``(tokens [T], count, history, hist_slot)`` like
    :func:`accept_fn`; the stream stops at the first rejection, EOS, or the
    bonus token. A -1 pad row never accepts (it behaves as "no proposal":
    sample from full p and stop)."""
    k = proposals.shape[0]
    keys = jax.random.split(round_key, logits.shape[0])

    def body(carry, i):
        alive, count, history, hist_slot = carry
        lg = sampling.processed_logits(logits[i], history, settings)
        ku, kr = jax.random.split(keys[i])
        is_bonus = i >= k
        prop = proposals[jnp.minimum(i, k - 1)]
        p_prop = jax.nn.softmax(lg)[jnp.maximum(prop, 0)]
        accept = (~is_bonus) & (prop >= 0) & (
            jax.random.uniform(ku) < p_prop
        )
        # residual: p with the rejected proposal removed, renormalized
        lg_res = jnp.where(
            jnp.arange(lg.shape[0], dtype=jnp.int32) == prop,
            jnp.float32(-1e30), lg,
        )
        g_rej = jax.random.categorical(kr, lg_res).astype(jnp.int32)
        g_bonus = jax.random.categorical(kr, lg).astype(jnp.int32)
        g = jnp.where(accept, prop, jnp.where(is_bonus, g_bonus, g_rej))
        nh, ns = sampling.push_history(history, hist_slot, g)
        history = jnp.where(alive, nh, history)
        hist_slot = jnp.where(alive, ns, hist_slot)
        count = count + alive.astype(jnp.int32)
        is_eos = (g == eos_ids).any()
        # a rejection/bonus row emits its sample and ends the round
        alive = alive & accept & ~is_eos
        return (alive, count, history, hist_slot), g

    (_, count, history, hist_slot), toks = jax.lax.scan(
        body,
        (jnp.asarray(True), jnp.int32(0), history, hist_slot),
        jnp.arange(logits.shape[0], dtype=jnp.int32),
    )
    return toks, count, history, hist_slot


def accept_fn_rows(logits, proposals, history, hist_slot, eos_ids,
                   settings: SamplerSettings):
    """Batched greedy accept: vmap of :func:`accept_fn` over serving rows.
    ``logits [B, T, V]``, ``proposals [B, K]`` (-1-padded), per-row
    history/hist_slot. Returns ``(tokens [B, T], count [B], history,
    hist_slot)``."""
    return jax.vmap(
        lambda l, p, h, s: accept_fn(l, p, h, s, eos_ids, settings)
    )(logits, proposals, history, hist_slot)


def accept_sampled_fn_rows(logits, proposals, history, hist_slot, eos_ids,
                           round_keys, settings: SamplerSettings):
    """Batched rejection-sampling accept: vmap of
    :func:`accept_sampled_fn` over serving rows with per-row round keys
    (``[B, 2] uint32``)."""
    return jax.vmap(
        lambda l, p, h, s, k: accept_sampled_fn(l, p, h, s, eos_ids, k,
                                                settings)
    )(logits, proposals, history, hist_slot, round_keys)


def ngram_propose_device(ctx, pos, *, n_max: int, k: int):
    """Device twin of :func:`ngram_propose`: ``ctx [S] int32`` holds the
    stream's tokens at slots ``0..pos-1`` (later slots are garbage — every
    read below is masked by ``pos``), ``pos`` is a traced int32. Returns
    ``[k] int32`` proposals, -1-padded, matching the host version's
    ``padded`` array bit-for-bit: same longest-n-first / most-recent-hit
    tie-breaking, same end-of-context clamp.

    Vectorization: for each static shift ``d``, ``shifted_d[j] = ctx[j+d]``
    (a static slice + pad), so "window at j matches the trailing n-gram"
    is an AND of n elementwise compares — no gather over windows. n_max is
    tiny (3 by default): the whole propose costs a few S-length VPU ops,
    which is noise next to the verification forward it precedes."""
    S = ctx.shape[0]
    iota = jnp.arange(S, dtype=jnp.int32)
    shifted = [
        jnp.concatenate(
            [ctx[d:], jnp.full((d,), -2, ctx.dtype)]) if d else ctx
        for d in range(n_max)
    ]
    best_j = jnp.int32(-1)
    best_n = jnp.int32(0)
    # ascending n: a longer match overwrites a shorter one, reproducing the
    # host's longest-n-first preference
    for n in range(1, n_max + 1):
        match = iota <= pos - 1 - n  # window ends before the final token
        for d in range(n):
            pat_d = ctx[jnp.maximum(pos - n + d, 0)]
            match = match & (shifted[d] == pat_d)
        j_n = jnp.max(jnp.where(match, iota, -1))
        found = (j_n >= 0) & (pos >= n + 1)
        best_j = jnp.where(found, j_n, best_j)
        best_n = jnp.where(found, jnp.int32(n), best_n)
    start = best_j + best_n
    idx = start + jnp.arange(k, dtype=jnp.int32)
    props = jnp.take(ctx, idx, mode="clip")
    return jnp.where((best_j >= 0) & (idx < pos), props, jnp.int32(-1))


def spec_rounds_fn(
    params,
    last_tok,  # [] int32 — the token feeding position pos
    ctx,  # [S] int32 stream context (slots 0..pos valid, ctx[pos]=last)
    pos,  # [] int32
    cache: KVCache,
    history,
    hist_slot,
    base_key,  # PRNG key (ignored under greedy)
    config: LlamaConfig,
    settings: SamplerSettings,
    eos_ids,  # [E] int32
    k: int,
    n_max: int,
    rounds: int,
):
    """``rounds`` propose→verify→accept rounds fused into ONE program.

    The host loop in :class:`SpeculativeMixin` pays a full host↔device
    round trip per round (the accepted-count sync) — on a tunneled device
    that latency, not the forward, dominates (measured r4: 7.5 tok/s spec8
    vs 84 plain on v5e). Here the n-gram propose runs on device
    (:func:`ngram_propose_device`), so consecutive rounds chain inside one
    ``lax.scan`` and the host syncs once per ``rounds``.

    Per round: propose from ``ctx``, forward ``[last, proposals] [1, K+1]``
    from ``pos`` (same KV-garbage-overwrite invariant as :func:`verify_fn`),
    accept via the greedy or rejection-sampling scan, append the emitted
    tokens to ``ctx``, advance ``pos``. A round that hits EOS freezes the
    carry (``done``): later rounds still compute (scan bodies always run)
    but write nothing. Greedy emissions are bit-identical to the host loop
    and therefore to plain decode; sampled rounds derive the same
    ``fold_in(fold_in(key, 0x5BEC), pos)`` round keys as the host loop.

    Returns ``(tokens [rounds, K+1], counts [rounds], last, ctx, pos,
    cache, history, hist_slot)`` — row ``r``'s first ``counts[r]`` tokens
    are that round's emissions. The caller must guarantee
    ``pos + rounds*(K+1) <= max_seq`` (the scan writes K+1 KV slots per
    round unconditionally)."""
    cos, sin = rope_tables(config.head_dim, cache.max_seq, config.rope_theta,
                           scaling=config.rope_scaling)
    greedy = settings.greedy

    def round_body(carry, _):
        last, ctx, pos, cache, history, hist_slot, done = carry
        props = ngram_propose_device(ctx, pos + 1, n_max=n_max, k=k)
        fed = jnp.concatenate([last[None], jnp.maximum(props, 0)])[None, :]
        logits, cache = _verify_forward(params, fed, cache, pos, cos, sin,
                                        config)
        if greedy:
            toks, count, h2, s2 = accept_fn(
                logits, props, history, hist_slot, eos_ids, settings)
        else:
            round_key = jax.random.fold_in(
                jax.random.fold_in(base_key, 0x5BEC), pos)
            toks, count, h2, s2 = accept_sampled_fn(
                logits, props, history, hist_slot, eos_ids, round_key,
                settings)
        count = jnp.where(done, 0, count)
        history = jax.tree.map(
            lambda new, old: jnp.where(done, old, new), h2, history)
        hist_slot = jnp.where(done, hist_slot, s2)
        # append emissions at pos+1..pos+T: ctx[pos] holds the token that
        # FED this round (the context convention is "slots 0..pos valid,
        # ctx[pos] = last"), so g_0 — the token at stream index pos+1 —
        # lands at pos+1. Junk rows beyond count (or a frozen round's
        # whole row) land entirely in the invalid region (> new pos) and
        # every later read is masked. The caller's headroom contract
        # (pos + rounds*(K+1) < S) rules out start-index clamping.
        ctx = jax.lax.dynamic_update_slice(ctx, toks, (pos + 1,))
        new_last = toks[jnp.maximum(count - 1, 0)]
        last = jnp.where(done, last, new_last)
        emitted_eos = (
            (toks[:, None] == eos_ids[None, :]).any(-1)
            & (jnp.arange(toks.shape[0]) < count)
        ).any()
        pos = pos + count
        done = done | emitted_eos
        return (last, ctx, pos, cache, history, hist_slot, done), (
            toks, count)

    (last, ctx, pos, cache, history, hist_slot, _), (tokens, counts) = (
        jax.lax.scan(
            round_body,
            (last_tok, ctx, pos, cache, history, hist_slot,
             jnp.asarray(False)),
            None,
            length=rounds,
        )
    )
    return tokens, counts, last, ctx, pos, cache, history, hist_slot


def spec_replay_fn(
    params,
    corpus,  # [S] int32 — the REAL token stream being replayed
    pos,  # [] int32: corpus[0..pos-1] in the KV cache; corpus[pos] is the
    #     last "emitted" token, NOT yet cached — this round's fed[0]
    #     writes its KV at `pos` (callers prefill corpus[:P], pass pos=P)
    cache: KVCache,
    acc,  # [] f32 logits checksum carry (see below)
    config: LlamaConfig,
    k: int,
    n_max: int,
    rounds: int,
):
    """``rounds`` TEACHER-FORCED propose→verify rounds fused into one
    program — the honest companion to :func:`spec_rounds_fn`'s synthetic
    self-repeating stream (r4 verdict: "no measured row on realistic text
    exists").

    The decoded stream is forced to the corpus: each round proposes with
    the same device n-gram lookup production uses
    (:func:`ngram_propose_device` over the replayed prefix), runs the REAL
    ``[1, K+1]`` verification forward (same cost as live speculation), and
    accepts the run where proposals match the corpus's actual next tokens
    — so tokens/dispatch and the acceptance rate measure the proposer
    against real text statistics while tok/s includes the true verify
    FLOPs/bytes. What it does not measure: the model's own agreement with
    its proposals (that needs trained weights; with random bench weights a
    live run degenerates to noise — the forced replay is the honest
    alternative, and is labeled as such in the bench row).

    ``acc`` accumulates a logits checksum; without it the teacher-forced
    accept never reads the logits and XLA would dead-code-eliminate the
    lm_head (and with it the bench's verify cost). Caller guarantees
    ``pos + rounds*(k+1) < min(len(corpus), max_seq)``.

    Returns ``(counts [rounds], pos, cache, acc)``.
    """
    cos, sin = rope_tables(config.head_dim, cache.max_seq, config.rope_theta,
                           scaling=config.rope_scaling)

    def round_body(carry, _):
        pos, cache, acc = carry
        props = ngram_propose_device(corpus, pos + 1, n_max=n_max, k=k)
        last = corpus[pos]
        fed = jnp.concatenate([last[None], jnp.maximum(props, 0)])[None, :]
        logits, cache = _verify_forward(params, fed, cache, pos, cos, sin,
                                        config)
        # teacher-forced accept: the "model output" at slot i is the
        # corpus's true next token; the run survives while proposals match
        # (-1 pads never match) — same run-length semantics as accept_fn.
        truth = jax.lax.dynamic_slice(corpus, (pos + 1,), (k,))
        lead = jnp.cumprod((props == truth).astype(jnp.int32))
        count = 1 + lead.sum()
        acc = acc + logits.sum()  # forces the lm_head to materialize
        return (pos + count, cache, acc), count

    (pos, cache, acc), counts = jax.lax.scan(
        round_body, (pos, cache, acc), None, length=rounds,
    )
    return counts, pos, cache, acc


class SpeculativeMixin:
    """The speculation loop, shared by the single-chip and mesh
    generators. Subclasses build ``self._verify`` (a compiled
    ``(params, tokens [1, T], cache, pos) -> (logits [T, vocab], cache)``
    program) in their constructors and inherit a ``GeneratorBase``-family
    ``next_token`` used for the prefill step and the no-proposal
    fallback."""

    def _verify_dispatch(self, fed: np.ndarray, pos: int) -> jax.Array:
        logits, self.cache = self._verify(
            self.params, jnp.asarray(fed), self.cache, jnp.int32(pos)
        )
        return logits

    def _spec_init(self, spec_k: int, spec_ngram: int,
                   spec_rounds: int = 1) -> None:
        self.spec_k = int(spec_k)
        self.spec_ngram = int(spec_ngram)
        self.spec_rounds = int(spec_rounds)
        if self.spec_k < 1:
            raise ValueError("spec_k must be >= 1")
        if self.spec_rounds < 1:
            raise ValueError("spec_rounds must be >= 1")
        eos = sorted(self._eos_ids) or [-1]
        self._eos_arr = jnp.asarray(eos, jnp.int32)
        # greedy: exact match accept (bit-identical streams); sampled:
        # rejection sampling (distribution-identical streams)
        accept = accept_fn if self.settings.greedy else accept_sampled_fn
        self._accept = jax.jit(partial(accept, settings=self.settings))
        # fused multi-round program (subclasses that support it assign
        # _spec_block after calling this); the device-side ctx buffer is
        # rebuilt lazily whenever a non-fused path advanced the stream
        self._spec_block = None
        self._ctx = None
        self._ctx_synced_pos = -1
        self.dispatches = 0
        self.rounds = 0
        self.emitted = 0

    def _on_new_prompt(self) -> None:
        """A fresh prompt invalidates the device-side ctx buffer: without
        this, a new stream whose prefill position happens to equal the old
        stream's last synced position would silently propose from the OLD
        stream's tokens (correctness survives — verification gates every
        token — but acceptance collapses)."""
        super()._on_new_prompt()
        self._ctx = None
        self._ctx_synced_pos = -1

    def _dispatch_fused(self):
        """One fused multi-round dispatch (:func:`spec_rounds_fn`): sync
        with the device once, harvest every round's emissions."""
        if self._ctx_synced_pos != self._pos or self._ctx is None:
            context = self._prompt_tokens + self._generated
            buf = np.zeros((self.max_seq,), np.int32)
            buf[: len(context)] = context
            self._ctx = jnp.asarray(buf)
        tokens, counts, _, ctx, _, cache, history, hist_slot = (
            self._spec_block(
                self.params, jnp.int32(self._last_token), self._ctx,
                jnp.int32(self._pos), self.cache, self._history,
                self._hist_slot, self._key,
            )
        )
        self.cache = cache
        self._ctx = ctx
        self._history, self._hist_slot = history, hist_slot
        # one combined fetch: two np.asarray calls would pay a second
        # tunnel round trip per dispatch
        counts_np, toks_np = jax.device_get((counts, tokens))
        emitted: list[int] = []
        for r in range(counts_np.shape[0]):
            emitted.extend(toks_np[r, : int(counts_np[r])].tolist())
        self.dispatches += 1
        self.rounds += int((counts_np > 0).sum())
        self.emitted += len(emitted)
        # device proposer: per-round proposal lengths stay on device, so
        # proposed is the K-per-live-round upper bound (see batch chain)
        record_acceptance(
            self.spec_k * int((counts_np > 0).sum()),
            int(np.maximum(counts_np - 1, 0).sum()))
        self._pos += len(emitted)
        self._ctx_synced_pos = self._pos
        self._block_buf = deque(emitted[1:])
        return self._finish_token(emitted[0])

    def next_token(self, index: int):
        if index == 0 or self._block_buf:
            tok = super().next_token(index)
            if index == 0:
                self.dispatches += 1
                self.rounds += 1
                self.emitted += 1
            return tok
        self._check_capacity()
        if (
            self._spec_block is not None
            and self._pos + self.spec_rounds * (self.spec_k + 1)
            < self.max_seq
        ):
            return self._dispatch_fused()
        context = self._prompt_tokens + self._generated
        proposal = ngram_propose(context, self.spec_ngram, self.spec_k)
        if not proposal or self._pos + self.spec_k + 1 > self.max_seq:
            self.dispatches += 1
            self.rounds += 1
            self.emitted += 1
            return super().next_token(index)

        fed = np.full((1, self.spec_k + 1), 0, np.int32)
        fed[0, 0] = self._last_token
        fed[0, 1: 1 + len(proposal)] = proposal
        padded = np.full((self.spec_k,), -1, np.int32)
        padded[: len(proposal)] = proposal
        logits = self._verify_dispatch(fed, self._pos)
        if self.settings.greedy:
            toks, count, self._history, self._hist_slot = self._accept(
                logits, jnp.asarray(padded), self._history, self._hist_slot,
                self._eos_arr,
            )
        else:
            # One fresh key per round: _pos strictly increases between
            # dispatches, so round keys never repeat within a stream. The
            # round key lives in its own fold domain (0x5bec) — the plain
            # single-step fallback samples with fold_in(self._key, index)
            # (generator.py), and reusing that exact derivation here would
            # correlate a round's draws with a fallback step's.
            round_key = jax.random.fold_in(
                jax.random.fold_in(self._key, 0x5BEC), self._pos
            )
            toks, count, self._history, self._hist_slot = self._accept(
                logits, jnp.asarray(padded), self._history, self._hist_slot,
                self._eos_arr, round_key,
            )
        n = int(count)
        emitted = np.asarray(toks[:n]).tolist()
        self.dispatches += 1
        self.rounds += 1
        self.emitted += n
        record_acceptance(len(proposal), n - 1)
        # cache holds KV for the fed tokens at pos..pos+K; the accepted
        # region pos..pos+n-1 is [last, g_0..g_{n-2}] — correct by the
        # match condition. The next round feeds g_{n-1} at pos+n.
        self._pos += n
        self._block_buf = deque(emitted[1:])
        return self._finish_token(emitted[0])


class SpeculativeGenerator(SpeculativeMixin, LlamaGenerator):
    """Single-stream generator with prompt-lookup speculation.

    ``spec_k`` tokens are proposed per round (n-grams up to ``spec_ngram``
    long); each round is one verification dispatch emitting 1..K+1 tokens.
    When no proposal exists (or the window tail is near), falls back to the
    plain single-step program. ``dispatches``/``emitted`` counters expose
    the speedup structure (tokens-per-dispatch > 1 is the win).

    Greedy streams are bit-identical to plain decode; ``temperature > 0``
    streams are distribution-identical via rejection sampling
    (:func:`accept_sampled_fn`), so speculation composes with the serving
    default sampler."""

    def __init__(
        self,
        config: LlamaConfig,
        params,
        tokenizer=None,
        settings: SamplerSettings | None = None,
        max_seq: int | None = None,
        kv_quant: str | None = None,
        spec_k: int = 8,
        spec_ngram: int = 3,
        spec_rounds: int = 8,
    ):
        settings = settings or SamplerSettings(temperature=0.0)
        super().__init__(config, params, tokenizer=tokenizer,
                         settings=settings, max_seq=max_seq,
                         kv_quant=kv_quant, block_size=1)
        self._spec_init(spec_k, spec_ngram, spec_rounds)
        self._verify = jax.jit(partial(verify_fn, config=config),
                               donate_argnames=("cache",))
        # fused multi-round program: propose on device, sync once per
        # spec_rounds rounds (spec_rounds=1 keeps the per-round host loop,
        # which is also the reference oracle in tests)
        if self.spec_rounds > 1:
            self._spec_block = jax.jit(
                partial(
                    spec_rounds_fn,
                    config=config,
                    settings=self.settings,
                    eos_ids=self._eos_arr,
                    k=self.spec_k,
                    n_max=self.spec_ngram,
                    rounds=self.spec_rounds,
                ),
                donate_argnames=("ctx", "cache"),
            )


class MeshSpeculativeGenerator(SpeculativeMixin, MeshGenerator):
    """Prompt-lookup speculation over the single-program mesh pipeline:
    the verification pass runs as ONE compiled program across the
    (stage, tp) mesh (``parallel.pipeline.build_sharded_verify``), so
    multi-chip decode also lands 1..K+1 tokens per dispatch. Same
    exactness contract as the single-chip variant: greedy bit-identical,
    sampled distribution-identical (rejection sampling)."""

    def __init__(
        self,
        config: LlamaConfig,
        params,
        plan=None,
        tokenizer=None,
        settings: SamplerSettings | None = None,
        max_seq: int | None = None,
        num_stages: int = 1,
        tp: int = 1,
        sp: int = 1,
        ep: int = 1,
        devices=None,
        kv_quant: str | None = None,
        spec_k: int = 8,
        spec_ngram: int = 3,
        prefill_chunks: int = 1,
    ):
        from cake_tpu.parallel.pipeline import build_sharded_verify

        settings = settings or SamplerSettings(temperature=0.0)
        # sp > 1 (r5): the verification pass runs chunk-replicated over
        # the sequence-sharded cache (build_sharded_verify's sp path), so
        # single-stream speculation composes with the long-context plane.
        super().__init__(config, params, plan=plan, tokenizer=tokenizer,
                         settings=settings, max_seq=max_seq,
                         num_stages=num_stages, tp=tp, sp=sp, ep=ep,
                         devices=devices, block_size=1, kv_quant=kv_quant,
                         prefill_chunks=prefill_chunks)
        self._spec_init(spec_k, spec_ngram)
        self._verify = build_sharded_verify(
            config, self.plan, params_like=self.params, kv_quant=kv_quant
        )
