"""N-gram speculative decoding (prompt-lookup): multi-token greedy decode.

A capability beyond the reference (whose decode loop is strictly one token
per step, `master.rs:36-48`): propose the next K tokens by matching the
context's trailing n-gram against its own history (prompt-lookup decoding —
no draft model), then *verify* all K in ONE model dispatch and accept the
longest correct prefix plus one bonus token. Greedy output is bit-identical
to plain decode by construction — the model's own (repeat-penalized) argmax
decides every emitted token; proposals only decide how many land per
dispatch.

Why this is TPU-shaped: single-token decode reads every weight byte from
HBM per token (weights-bound, ~85 tok/s for 8B int8 on v5e). Verification
feeds K+1 tokens through the same weights in one pass — the MXU loves the
wider matmuls and the weight read amortizes over every accepted token, so
acceptance rate converts directly into tok/s. On repetitive stretches
(code, quotes, structured text) prompt-lookup acceptance is high; worst
case costs one dispatch per token, like plain decode.

Greedy streams (``temperature == 0``) are bit-identical to plain decode.
Sampled streams (``temperature > 0``, the serving default) use REJECTION
SAMPLING (:func:`accept_sampled_fn`): each emitted token's conditional
distribution given the prefix is exactly the plain sampler's categorical —
distribution-preserving, not sample-path-preserving (a fixed seed yields a
different but identically-distributed stream than plain decode).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from cake_tpu.models import llama
from cake_tpu.models.config import LlamaConfig
from cake_tpu.ops import quant, sampling
from cake_tpu.ops.kvcache import KVCache
from cake_tpu.ops.norms import rms_norm
from cake_tpu.ops.rope import rope_tables
from cake_tpu.ops.sampling import SamplerSettings
from cake_tpu.runtime.generator import LlamaGenerator
from cake_tpu.runtime.mesh_generator import MeshGenerator


def ngram_propose(context: list[int], n_max: int, k: int) -> list[int]:
    """Propose up to ``k`` continuation tokens by finding the most recent
    earlier occurrence of the context's trailing n-gram (longest n first)
    and copying what followed it. Returns [] when nothing matches."""
    L = len(context)
    if L < 2 or k < 1:
        return []
    arr = np.asarray(context, np.int64)
    for n in range(min(n_max, L - 1), 0, -1):
        pat = arr[L - n:]
        # candidate starts 0..L-1-n: pattern ends before the final position,
        # so a continuation token always exists inside the context
        windows = np.lib.stride_tricks.sliding_window_view(arr[: L - 1], n)
        hits = np.nonzero((windows == pat).all(axis=1))[0]
        if hits.size:
            j = int(hits[-1])
            return arr[j + n: j + n + k].tolist()
    return []


def verify_fn(params, tokens, cache: KVCache, pos, config: LlamaConfig):
    """Forward ``tokens [1, T]`` from position ``pos`` returning logits at
    EVERY position (``[T, vocab] f32``) — the speculation-verification pass.
    KV for all T slots is written; slots past the accepted frontier hold
    rejected garbage that later steps overwrite before it becomes
    attendable (the same invariant as bucketed-prefill padding)."""
    cos, sin = rope_tables(config.head_dim, cache.max_seq, config.rope_theta,
                           scaling=config.rope_scaling)
    x = params["embed"][tokens].astype(config.jax_dtype)
    x, cache = llama.forward_layers(params["layers"], x, cache, cos, sin,
                                    pos, config)
    x = rms_norm(x, params["norm_f"], config.rms_norm_eps)
    logits = quant.dense(x[0], params["lm_head"]).astype(jnp.float32)
    return logits, cache


def accept_fn(
    logits,  # [T, vocab] f32 (T = K + 1)
    proposals,  # [K] int32, -1-padded
    history,
    hist_slot,
    eos_ids,  # [E] int32 (-1-padded when fewer)
    settings: SamplerSettings,
):
    """Greedy accept scan. Row ``i``'s (repeat-penalized) argmax ``g_i`` is
    emitted while the stream is alive; the stream stays alive while each
    ``g_i`` equals its proposal and is not EOS. Returns
    ``(tokens [T], count, history, hist_slot)`` — the first ``count``
    tokens are exactly what plain greedy decode would have produced, with
    history advanced by exactly those tokens."""
    k = proposals.shape[0]
    dummy_key = jax.random.PRNGKey(0)  # unused at temperature 0

    def body(carry, i):
        alive, count, history, hist_slot = carry
        g = sampling.sample_token(logits[i], dummy_key, history, settings)
        nh, ns = sampling.push_history(history, hist_slot, g)
        history = jnp.where(alive, nh, history)
        hist_slot = jnp.where(alive, ns, hist_slot)
        count = count + alive.astype(jnp.int32)
        is_eos = (g == eos_ids).any()
        matched = jnp.where(i < k, g == proposals[jnp.minimum(i, k - 1)],
                            False)
        alive = alive & matched & ~is_eos
        return (alive, count, history, hist_slot), g

    (_, count, history, hist_slot), toks = jax.lax.scan(
        body,
        (jnp.asarray(True), jnp.int32(0), history, hist_slot),
        jnp.arange(logits.shape[0], dtype=jnp.int32),
    )
    return toks, count, history, hist_slot


def accept_sampled_fn(
    logits,  # [T, vocab] f32 (T = K + 1)
    proposals,  # [K] int32, -1-padded
    history,
    hist_slot,
    eos_ids,  # [E] int32 (-1-padded when fewer)
    round_key,  # PRNG key for this verification round
    settings: SamplerSettings,
):
    """Rejection-sampling accept scan for ``temperature > 0``.

    The prompt-lookup draft is DETERMINISTIC (q is a point mass on the
    proposal), so the standard speculative-sampling rule (Leviathan et al.;
    Chen et al.) reduces cleanly: accept proposal ``x`` with probability
    ``p(x)`` (p = the plain sampler's penalized/temperature-scaled/top-k/
    top-p categorical, via ``sampling.processed_logits``); on rejection,
    sample the replacement from the residual ``norm(max(p - q, 0))`` — p
    with the proposal's mass zeroed. If all K proposals are accepted, the
    bonus row samples from its p directly. Per emitted token the
    conditional distribution given the prefix is exactly p: acceptance
    contributes ``p(x)·1[y=x]`` and rejection ``(1-p(x))·p(y)/(1-p(x))``
    for ``y != x``.

    Returns ``(tokens [T], count, history, hist_slot)`` like
    :func:`accept_fn`; the stream stops at the first rejection, EOS, or the
    bonus token. A -1 pad row never accepts (it behaves as "no proposal":
    sample from full p and stop)."""
    k = proposals.shape[0]
    keys = jax.random.split(round_key, logits.shape[0])

    def body(carry, i):
        alive, count, history, hist_slot = carry
        lg = sampling.processed_logits(logits[i], history, settings)
        ku, kr = jax.random.split(keys[i])
        is_bonus = i >= k
        prop = proposals[jnp.minimum(i, k - 1)]
        p_prop = jax.nn.softmax(lg)[jnp.maximum(prop, 0)]
        accept = (~is_bonus) & (prop >= 0) & (
            jax.random.uniform(ku) < p_prop
        )
        # residual: p with the rejected proposal removed, renormalized
        lg_res = jnp.where(
            jnp.arange(lg.shape[0], dtype=jnp.int32) == prop,
            jnp.float32(-1e30), lg,
        )
        g_rej = jax.random.categorical(kr, lg_res).astype(jnp.int32)
        g_bonus = jax.random.categorical(kr, lg).astype(jnp.int32)
        g = jnp.where(accept, prop, jnp.where(is_bonus, g_bonus, g_rej))
        nh, ns = sampling.push_history(history, hist_slot, g)
        history = jnp.where(alive, nh, history)
        hist_slot = jnp.where(alive, ns, hist_slot)
        count = count + alive.astype(jnp.int32)
        is_eos = (g == eos_ids).any()
        # a rejection/bonus row emits its sample and ends the round
        alive = alive & accept & ~is_eos
        return (alive, count, history, hist_slot), g

    (_, count, history, hist_slot), toks = jax.lax.scan(
        body,
        (jnp.asarray(True), jnp.int32(0), history, hist_slot),
        jnp.arange(logits.shape[0], dtype=jnp.int32),
    )
    return toks, count, history, hist_slot


def accept_fn_rows(logits, proposals, history, hist_slot, eos_ids,
                   settings: SamplerSettings):
    """Batched greedy accept: vmap of :func:`accept_fn` over serving rows.
    ``logits [B, T, V]``, ``proposals [B, K]`` (-1-padded), per-row
    history/hist_slot. Returns ``(tokens [B, T], count [B], history,
    hist_slot)``."""
    return jax.vmap(
        lambda l, p, h, s: accept_fn(l, p, h, s, eos_ids, settings)
    )(logits, proposals, history, hist_slot)


def accept_sampled_fn_rows(logits, proposals, history, hist_slot, eos_ids,
                           round_keys, settings: SamplerSettings):
    """Batched rejection-sampling accept: vmap of
    :func:`accept_sampled_fn` over serving rows with per-row round keys
    (``[B, 2] uint32``)."""
    return jax.vmap(
        lambda l, p, h, s, k: accept_sampled_fn(l, p, h, s, eos_ids, k,
                                                settings)
    )(logits, proposals, history, hist_slot, round_keys)


class SpeculativeMixin:
    """The speculation loop, shared by the single-chip and mesh
    generators. Subclasses build ``self._verify`` (a compiled
    ``(params, tokens [1, T], cache, pos) -> (logits [T, vocab], cache)``
    program) in their constructors and inherit a ``GeneratorBase``-family
    ``next_token`` used for the prefill step and the no-proposal
    fallback."""

    def _verify_dispatch(self, fed: np.ndarray, pos: int) -> jax.Array:
        logits, self.cache = self._verify(
            self.params, jnp.asarray(fed), self.cache, jnp.int32(pos)
        )
        return logits

    def _spec_init(self, spec_k: int, spec_ngram: int) -> None:
        self.spec_k = int(spec_k)
        self.spec_ngram = int(spec_ngram)
        if self.spec_k < 1:
            raise ValueError("spec_k must be >= 1")
        eos = sorted(self._eos_ids) or [-1]
        self._eos_arr = jnp.asarray(eos, jnp.int32)
        # greedy: exact match accept (bit-identical streams); sampled:
        # rejection sampling (distribution-identical streams)
        accept = accept_fn if self.settings.greedy else accept_sampled_fn
        self._accept = jax.jit(partial(accept, settings=self.settings))
        self.dispatches = 0
        self.emitted = 0

    def next_token(self, index: int):
        if index == 0 or self._block_buf:
            tok = super().next_token(index)
            if index == 0:
                self.dispatches += 1
                self.emitted += 1
            return tok
        self._check_capacity()
        context = self._prompt_tokens + self._generated
        proposal = ngram_propose(context, self.spec_ngram, self.spec_k)
        if not proposal or self._pos + self.spec_k + 1 > self.max_seq:
            self.dispatches += 1
            self.emitted += 1
            return super().next_token(index)

        fed = np.full((1, self.spec_k + 1), 0, np.int32)
        fed[0, 0] = self._last_token
        fed[0, 1: 1 + len(proposal)] = proposal
        padded = np.full((self.spec_k,), -1, np.int32)
        padded[: len(proposal)] = proposal
        logits = self._verify_dispatch(fed, self._pos)
        if self.settings.greedy:
            toks, count, self._history, self._hist_slot = self._accept(
                logits, jnp.asarray(padded), self._history, self._hist_slot,
                self._eos_arr,
            )
        else:
            # One fresh key per round: _pos strictly increases between
            # dispatches, so round keys never repeat within a stream. The
            # round key lives in its own fold domain (0x5bec) — the plain
            # single-step fallback samples with fold_in(self._key, index)
            # (generator.py), and reusing that exact derivation here would
            # correlate a round's draws with a fallback step's.
            round_key = jax.random.fold_in(
                jax.random.fold_in(self._key, 0x5BEC), self._pos
            )
            toks, count, self._history, self._hist_slot = self._accept(
                logits, jnp.asarray(padded), self._history, self._hist_slot,
                self._eos_arr, round_key,
            )
        n = int(count)
        emitted = np.asarray(toks[:n]).tolist()
        self.dispatches += 1
        self.emitted += n
        # cache holds KV for the fed tokens at pos..pos+K; the accepted
        # region pos..pos+n-1 is [last, g_0..g_{n-2}] — correct by the
        # match condition. The next round feeds g_{n-1} at pos+n.
        self._pos += n
        self._block_buf = emitted[1:]
        return self._finish_token(emitted[0])


class SpeculativeGenerator(SpeculativeMixin, LlamaGenerator):
    """Single-stream generator with prompt-lookup speculation.

    ``spec_k`` tokens are proposed per round (n-grams up to ``spec_ngram``
    long); each round is one verification dispatch emitting 1..K+1 tokens.
    When no proposal exists (or the window tail is near), falls back to the
    plain single-step program. ``dispatches``/``emitted`` counters expose
    the speedup structure (tokens-per-dispatch > 1 is the win).

    Greedy streams are bit-identical to plain decode; ``temperature > 0``
    streams are distribution-identical via rejection sampling
    (:func:`accept_sampled_fn`), so speculation composes with the serving
    default sampler."""

    def __init__(
        self,
        config: LlamaConfig,
        params,
        tokenizer=None,
        settings: SamplerSettings | None = None,
        max_seq: int | None = None,
        kv_quant: str | None = None,
        spec_k: int = 8,
        spec_ngram: int = 3,
    ):
        settings = settings or SamplerSettings(temperature=0.0)
        super().__init__(config, params, tokenizer=tokenizer,
                         settings=settings, max_seq=max_seq,
                         kv_quant=kv_quant, block_size=1)
        self._spec_init(spec_k, spec_ngram)
        self._verify = jax.jit(partial(verify_fn, config=config),
                               donate_argnames=("cache",))


class MeshSpeculativeGenerator(SpeculativeMixin, MeshGenerator):
    """Prompt-lookup speculation over the single-program mesh pipeline:
    the verification pass runs as ONE compiled program across the
    (stage, tp) mesh (``parallel.pipeline.build_sharded_verify``), so
    multi-chip decode also lands 1..K+1 tokens per dispatch. Same
    exactness contract as the single-chip variant: greedy bit-identical,
    sampled distribution-identical (rejection sampling)."""

    def __init__(
        self,
        config: LlamaConfig,
        params,
        plan=None,
        tokenizer=None,
        settings: SamplerSettings | None = None,
        max_seq: int | None = None,
        num_stages: int = 1,
        tp: int = 1,
        devices=None,
        kv_quant: str | None = None,
        spec_k: int = 8,
        spec_ngram: int = 3,
        prefill_chunks: int = 1,
    ):
        from cake_tpu.parallel.pipeline import build_sharded_verify

        settings = settings or SamplerSettings(temperature=0.0)
        super().__init__(config, params, plan=plan, tokenizer=tokenizer,
                         settings=settings, max_seq=max_seq,
                         num_stages=num_stages, tp=tp, sp=1,
                         devices=devices, block_size=1, kv_quant=kv_quant,
                         prefill_chunks=prefill_chunks)
        self._spec_init(spec_k, spec_ngram)
        self._verify = build_sharded_verify(
            config, self.plan, params_like=self.params, kv_quant=kv_quant
        )
