"""cake-tpu command line.

Equivalent of the reference CLI (`cake-cli/src/main.rs` + the clap Args in
`cake-core/src/lib.rs:15-64`): same flag surface and defaults — --model,
--topology, --prompt, --seed (299792458), -n/--sample-len (100),
--temperature (1.0), --top-p, --top-k, --repeat-penalty (1.1),
--repeat-last-n (128), --dtype, --mode master|worker, --name, --address
(127.0.0.1:10128). TPU additions: --max-seq (the reference hard-caps 4096),
--stages/--tp for the on-pod mesh pipeline instead of TCP workers.

Usage:
  python -m cake_tpu.cli --model /path/to/llama --prompt "..."          # local
  python -m cake_tpu.cli --mode worker --name w1 --model ... --topology t.yml
  python -m cake_tpu.cli --model ... --topology t.yml --prompt "..."    # master
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import time
from pathlib import Path

log = logging.getLogger("cake_tpu.cli")


def _quant_spec(s: str) -> str:
    """argparse validator for --quantize (int8 | int4 | int4:gN)."""
    from cake_tpu.ops.quant import parse_quant_spec

    try:
        parse_quant_spec(s)
    except ValueError as e:
        raise argparse.ArgumentTypeError(str(e))
    return s


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cake-tpu",
        description="TPU-native distributed single-stream LLM inference",
    )
    p.add_argument("--model", default=None,
                   help="checkpoint directory (config.json + safetensors); "
                        "required in every mode except gateway (a gateway "
                        "holds no model — its backends do)")
    p.add_argument("--fetch", default=None, metavar="SRC",
                   help="populate --model first from hf://org/name[@rev] or "
                        "a local dir (idempotent; unlike the reference's "
                        "forced hub re-download, cake/mod.rs:88-96)")
    p.add_argument("--refetch", action="store_true",
                   help="with --fetch: re-copy/re-download even if --model "
                        "already holds a complete checkpoint")
    p.add_argument("--mode", choices=["master", "worker", "serve",
                                      "gateway"],
                   default="master",
                   help="master: one-shot generation (default); worker: "
                        "serve topology-assigned layers over the wire; "
                        "serve: network-facing request serving — an HTTP "
                        "API (POST /v1/completions with SSE streaming, "
                        "/v1/models, /healthz, plus the / + /metrics "
                        "status surface) over the continuous-batching "
                        "engine, with admission queueing, backpressure, "
                        "cancellation, and graceful SIGTERM drain; "
                        "gateway: route the same API across a fleet of "
                        "serve replicas (--backends) with health-checked "
                        "load-aware routing, transparent failover, and "
                        "SSE pass-through")
    p.add_argument("--name", default=None, help="worker name in the topology")
    p.add_argument("--address", default="127.0.0.1:10128",
                   help="worker bind address")
    p.add_argument("--topology", default=None, help="topology YAML path")
    p.add_argument("--status-port", type=int, default=None,
                   dest="status_port", metavar="PORT",
                   help="serve a live status page over HTTP (0 = ephemeral "
                        "port): worker mode exposes identity/layer/traffic "
                        "JSON on / (the headless equivalent of the "
                        "reference's worker GUI), master mode its own "
                        "registry incl. the merged cluster.* series; both "
                        "serve Prometheus text on /metrics")
    p.add_argument("--status-bind", default="127.0.0.1", dest="status_bind",
                   metavar="ADDR",
                   help="interface for --status-port (default 127.0.0.1: "
                        "the page exposes identity, layer assignment, and "
                        "traffic counters, so it stays host-local unless "
                        "you opt in; 0.0.0.0 serves every interface — do "
                        "that only on a trusted network, e.g. for a remote "
                        "master's cluster scraper or a Prometheus host)")
    p.add_argument("--prompt", default="Why is the sky blue?")
    p.add_argument("--prompt-ids", default=None, dest="prompt_ids",
                   help="comma-separated token ids (bypasses the tokenizer)")
    p.add_argument("--prompts-file", default=None, dest="prompts_file",
                   help="serve N prompts concurrently (one text prompt per "
                        "line, or comma-separated token-id lists with "
                        "--prompts-ids) over the batched mesh pipeline")
    p.add_argument("--prompts-ids", action="store_true", dest="prompts_ids",
                   help="treat every --prompts-file line as comma-separated "
                        "token ids (explicit per-file mode: a text prompt "
                        "that happens to look numeric, like '1, 2, 3', is "
                        "never silently id-parsed)")
    p.add_argument("--dp", type=int, default=1,
                   help="data-parallel width for --prompts-file serving")
    p.add_argument("--seed", type=int, default=299792458)
    p.add_argument("-n", "--sample-len", type=int, default=100, dest="sample_len")
    p.add_argument("--temperature", type=float, default=1.0)
    p.add_argument("--top-p", type=float, default=None, dest="top_p")
    p.add_argument("--top-k", type=int, default=None, dest="top_k")
    p.add_argument("--repeat-penalty", type=float, default=1.1,
                   dest="repeat_penalty")
    p.add_argument("--repeat-last-n", type=int, default=128,
                   dest="repeat_last_n")
    p.add_argument("--dtype", choices=["bf16", "f16", "f32"], default="bf16",
                   help="f16 maps to bf16 on TPU")
    p.add_argument("--quantize", type=_quant_spec, default=None,
                   metavar="{int8,int4,int4:gN}",
                   help="quantize linear weights on load (per-channel "
                        "symmetric; int4 is packed two-per-byte; int4:gN "
                        "uses N-row group-wise scales, the accuracy tier)")
    p.add_argument("--kv-quant", choices=["int8"], default=None,
                   dest="kv_quant",
                   help="store the KV cache as int8 + per-slot scales "
                        "(half the cache HBM — roughly doubles servable "
                        "batch x window, or doubles the --sp long-context "
                        "window; local and mesh paths)")
    p.add_argument("--kv-layout", choices=["slot", "paged"], default="slot",
                   dest="kv_layout",
                   help="KV cache layout for the batched serving engine: "
                        "'slot' (per-stream contiguous rows; default) or "
                        "'paged' (pooled fixed-size pages addressed through "
                        "per-stream page tables, with copy-on-write "
                        "shared-prefix pages — cake_tpu/kvpool; admission/"
                        "retirement touch page tables, not cache tensors). "
                        "--mode serve and --prompts-file batch runs")
    p.add_argument("--kv-page-size", type=int, default=None,
                   dest="kv_page_size", metavar="N",
                   help="--kv-layout paged: tokens per KV page (must divide "
                        "the window; default 16)")
    p.add_argument("--kv-pool-pages", type=int, default=None,
                   dest="kv_pool_pages", metavar="N",
                   help="--kv-layout paged: total pool pages (power of two, "
                        ">= batch x window/page_size + 1; default sized "
                        "from the batch plus prefix-tree headroom)")
    p.add_argument("--decode-block", type=int, default=None,
                   dest="decode_block",
                   help="fused decode steps per dispatch (all-local and mesh "
                        "paths; 1 = one program per token; default 8)")
    p.add_argument("--lookahead", action="store_true",
                   help="dispatch decode block N+1 from the device-side "
                        "feedback token BEFORE fetching block N's tokens to "
                        "the host — hides readback/detok/emission behind "
                        "device compute (all-local fused-block path and "
                        "--prompts-file serving; token streams are "
                        "bit-identical to the non-lookahead path)")
    p.add_argument("--wire-codec", choices=["none", "bf16", "int8"],
                   default=None, dest="wire_codec",
                   help="activation encoding for cross-host worker hops "
                        "(negotiated at handshake). Master: the codec every "
                        "remote segment uses (default none). Worker: "
                        "restrict what this worker accepts/mirrors "
                        "(default: all). bf16 ~2x fewer bytes on f32 runs; "
                        "int8 (per-row absmax scales) ~4x — both perturb "
                        "low-order logit bits like --kv-quant does")
    p.add_argument("--speculate", type=int, default=0, metavar="K",
                   help="n-gram speculative decoding: propose K tokens per "
                        "round from the context's own n-grams and verify "
                        "them in one dispatch (greedy streams bit-exact; "
                        "sampled streams distribution-exact via rejection "
                        "sampling; local, mesh --stages/--tp, and "
                        "--prompts-file serving paths — serving verifies "
                        "every stream's proposals per-row in one batched "
                        "pass. NOTE: with temperature > 0 serving rounds "
                        "always run the K+1-wide verify (skipping on other "
                        "streams' proposals would break per-stream "
                        "reproducibility), so sampled speculation only "
                        "pays off on repetitive/structured streams)")
    p.add_argument("--max-seq", type=int, default=None, dest="max_seq")
    p.add_argument("--window", type=int, default=None,
                   help="override the attention sliding window (tokens): "
                        "narrow a Mistral-family window, give any model "
                        "one, or 0 to disable the checkpoint's window")
    p.add_argument("--stages", type=int, default=1,
                   help="on-pod pipeline stages (mesh, not TCP)")
    p.add_argument("--tp", type=int, default=1, help="tensor-parallel width")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel width (ring attention prefill)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel width (MoE families: the expert "
                        "stacks shard over this mesh axis)")
    p.add_argument("--prefill-chunks", type=int, default=1,
                   dest="prefill_chunks",
                   help="pipeline the prompt pass through the stages in M "
                        "chunks (GPipe-style overlap; stages>1, sp=1)")
    p.add_argument("--device", type=int, default=None,
                   help="device ordinal (reference --device GPU ordinal, "
                        "lib.rs:17-19; here an index into jax.devices())")
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="multi-host pod: jax.distributed coordinator "
                        "address (same command on every host; pairs with "
                        "--num-processes/--process-id, or auto-resolved on "
                        "Cloud TPU)")
    p.add_argument("--num-processes", type=int, default=None,
                   dest="num_processes")
    p.add_argument("--process-id", type=int, default=None, dest="process_id")
    p.add_argument("--cpu", action="store_true", help="force CPU backend")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="write a jax.profiler trace of generation to DIR")
    # -- observability (cake_tpu/obs): spans, metrics, flight records ------
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="record runtime spans (prefill, decode.step, "
                        "decode.segment, wire.send/recv, ...) and write a "
                        "Chrome trace-event JSON on exit — load it in "
                        "Perfetto or chrome://tracing; with --profile the "
                        "spans also pass through to the XLA profile as "
                        "jax.profiler TraceAnnotations")
    p.add_argument("--metrics-out", default=None, dest="metrics_out",
                   metavar="PATH",
                   help="dump the metrics registry (counters, gauges, "
                        "latency histograms with p50/p99) as JSON on exit")
    p.add_argument("--flight-log", default=None, dest="flight_log",
                   metavar="PATH",
                   help="append flight-recorder JSON lines to PATH: one per "
                        "token on the per-token paths (kind, per-segment "
                        "ms, wire bytes, serialize/sample ms, recovery "
                        "events), one per dispatch on fused-block/batched "
                        "paths (with steps/batch fields)")
    p.add_argument("--cluster-report", default=None, dest="cluster_report",
                   metavar="PATH",
                   help="master+topology runs: write an end-of-run JSON "
                        "cluster report — per-worker segment forward "
                        "p50/p99, RTT and clock offset (ping-estimated), "
                        "byte/op counters, straggler flags, plus the "
                        "master's own per-segment stats")
    p.add_argument("--prof-sample", type=int, default=None,
                   dest="prof_sample", metavar="N",
                   help="engine profiling plane (cake_tpu/obs/prof): stamp "
                        "a full per-phase step breakdown every Nth engine "
                        "step (default 64 via CAKE_PROF_SAMPLE; 0 disables "
                        "sampling entirely, 1 stamps every step). The "
                        "report is served live at GET /debug/prof and "
                        "folded into --trace timelines as prof.* spans")
    p.add_argument("--top", action="store_true",
                   help="master+topology runs: live ANSI cluster panel on "
                        "stderr while generating (per-worker p50/p99, RTT, "
                        "offset, straggler flags; plain escape-code "
                        "refresh, no curses; the token stream on stdout "
                        "stays clean)")
    # -- failure domain (runtime/retry, testing/chaos) ----------------------
    p.add_argument("--recover-deadline", type=float, default=None,
                   dest="recover_deadline", metavar="S",
                   help="master+topology runs: per-replica budget (seconds, "
                        "default 30) for a mid-stream reconnect — retried "
                        "with jittered exponential backoff, so a worker "
                        "restarting for a few seconds no longer kills the "
                        "stream; when a segment's topology entry lists "
                        "replica addresses, expiry fails over to the next "
                        "one and the context replay rebuilds its KV")
    p.add_argument("--connect-retries", type=int, default=0,
                   dest="connect_retries", metavar="N",
                   help="master+topology runs: retry each worker's INITIAL "
                        "handshake up to N times with backoff instead of "
                        "failing on the first refused connect — the master "
                        "can start before its workers (default 0: fail "
                        "fast)")
    p.add_argument("--op-timeout", type=float, default=None,
                   dest="op_timeout", metavar="S",
                   help="master+topology runs: per-op recv deadline "
                        "(seconds) on every forward/STATS/PING exchange; a "
                        "wedged worker then faults into reconnect+replay "
                        "instead of hanging the decode loop forever. "
                        "Default scales with segment size (120 + 2s/layer "
                        "— generous: it catches wedged peers, not slow "
                        "ones)")
    p.add_argument("--chaos", default=None, metavar="SPEC",
                   help="DEV: put a fault-injecting proxy "
                        "(cake_tpu.testing.chaos) in front of every worker "
                        "link. SPEC is comma-separated "
                        "kind[@[r]FRAME][=PARAM] directives — kill, "
                        "truncate, corrupt, stall (PARAM ms), blackhole, "
                        "refuse (PARAM conns) — applied to successive "
                        "connections per link, or seed=N for a "
                        "seed-reproducible random schedule. E.g. "
                        "--chaos kill@7 kills each link after its 7th "
                        "request frame; --chaos seed=1337 reproduces "
                        "exactly the run that failed under seed 1337")
    p.add_argument("--straggler-factor", type=float, default=2.0,
                   dest="straggler_factor", metavar="F",
                   help="flag a worker as straggler when its segment "
                        "forward p99 exceeds the median of its peers' "
                        "p99s by this factor (cluster report / --top / "
                        "cluster.* gauges; default 2.0)")
    # -- request serving (--mode serve: cake_tpu/serve) ---------------------
    p.add_argument("--serve-port", type=int, default=None, dest="serve_port",
                   metavar="PORT",
                   help="--mode serve: HTTP port for the serving API "
                        "(default 8080; 0 = ephemeral). The same port "
                        "serves / + /metrics, so one scrape sees traffic "
                        "and observability")
    p.add_argument("--serve-bind", default=None, dest="serve_bind",
                   metavar="ADDR",
                   help="--mode serve: bind interface (default 127.0.0.1 "
                        "— serving beyond the host is an explicit "
                        "decision, same policy as --status-bind)")
    p.add_argument("--max-concurrent", type=int, default=None,
                   dest="max_concurrent", metavar="N",
                   help="--mode serve: concurrently decoding streams — "
                        "the engine's batch slots (default 8; a "
                        "host-addressed --topology serializes at 1, the "
                        "single-stream wire path)")
    p.add_argument("--queue-depth", type=int, default=None,
                   dest="queue_depth", metavar="N",
                   help="--mode serve: bounded admission queue; a submit "
                        "past the bound answers 429 with a Retry-After "
                        "derived from observed tokens/sec (default 64)")
    p.add_argument("--request-timeout", type=float, default=None,
                   dest="request_timeout", metavar="S",
                   help="--mode serve: per-request deadline from arrival "
                        "(seconds, default 300): expired requests are "
                        "refused while queued (504) or retired mid-stream "
                        "(finish_reason 'timeout'), freeing the slot")
    p.add_argument("--serve-logprobs", type=int, default=0,
                   dest="serve_logprobs", metavar="K",
                   help="--mode serve: per-token top-K logprob capacity — "
                        "the decode programs also return the top-K "
                        "log-softmax, so requests may ask 'logprobs': N "
                        "for any N <= K (default 0: refused with 400; "
                        "needs the batched mesh engine)")
    p.add_argument("--role", choices=["mixed", "prefill", "decode"],
                   default="mixed",
                   help="--mode serve: replica tier (cake_tpu/disagg) — "
                        "mixed (default) runs the classic everything-"
                        "replica; prefill runs bucketed prefill only and "
                        "ships the finished KV pages to a decode replica "
                        "over the transfer channel; decode imports pages "
                        "and runs only the steady-state batched step "
                        "(both need --kv-layout paged)")
    p.add_argument("--transfer-port", type=int, default=None,
                   dest="transfer_port", metavar="PORT",
                   help="--mode serve: KV transfer-channel listener port "
                        "(0 = ephemeral; advertised on /healthz as "
                        "transfer_port so the gateway's tier map finds "
                        "it). Defaults to ephemeral for --role decode; "
                        "setting it on a mixed replica lets it accept "
                        "imports too (session resume without a tier "
                        "split)")
    p.add_argument("--transfer-codec", choices=["none", "bf16", "int8"],
                   default="none", dest="transfer_codec",
                   help="--mode serve: per-page codec for exported KV "
                        "snapshots (the --wire-codec path; default "
                        "none). Round trips are bit-identical whenever "
                        "the codec is lossless for the cache dtype — "
                        "none always, bf16 on a bf16 cache, int8 on an "
                        "int8-quantized pool")
    p.add_argument("--sched-policy", choices=["slo", "fifo"],
                   default="slo", dest="sched_policy",
                   help="--mode serve: admission policy (ISSUE 20) — "
                        "slo (default): priority classes ('class': "
                        "interactive|batch on /v1/completions), "
                        "preemption with host-RAM KV spill, per-tenant "
                        "fairness; fifo: strict arrival order, no "
                        "preemption (the single-tenant baseline)")
    p.add_argument("--spill-mb", type=float, default=64.0,
                   dest="spill_mb", metavar="MB",
                   help="--mode serve: host-RAM budget for preempted "
                        "stream snapshots (default 64; 0 disables "
                        "preemption — class ordering still applies). "
                        "Spilling needs the paged engine "
                        "(--kv-layout paged)")
    p.add_argument("--fairness-factor", type=float, default=2.0,
                   dest="fairness_factor", metavar="X",
                   help="--mode serve: a tenant is over budget when its "
                        "share of recent tokens exceeds X times its "
                        "fair share (default 2.0) — over-budget "
                        "tenants queue behind in-budget arrivals and "
                        "are preferred preemption victims ('tenant' "
                        "body field, defaults to the request class)")
    p.add_argument("--slo-ttft-ms", type=float, default=None,
                   dest="slo_ttft_ms", metavar="MS",
                   help="--mode serve/gateway: per-request time-to-first-"
                        "token SLO target. Completed requests are judged "
                        "good/bad against it (slo.good/slo.bad counters, "
                        "slo.burn_short/slo.burn_long burn-rate gauges on "
                        "/metrics and /healthz; per-request verdict on "
                        "GET /v1/requests/<id>)")
    p.add_argument("--slo-tpot-ms", type=float, default=None,
                   dest="slo_tpot_ms", metavar="MS",
                   help="--mode serve/gateway: per-request mean time-per-"
                        "output-token SLO target (same accounting as "
                        "--slo-ttft-ms; a request must meet BOTH set "
                        "targets to count good)")
    # -- routing gateway (--mode gateway: cake_tpu/gateway) ------------------
    p.add_argument("--backends", default=None, metavar="HOST:PORT,...",
                   help="--mode gateway: comma-separated serve-replica "
                        "addresses the gateway routes across (each runs "
                        "--mode serve; the gateway health-checks their "
                        "/healthz and proxies /v1/completions, /v1/models "
                        "to the fleet). These are STATIC SEED members; "
                        "replicas started with --register-with join "
                        "dynamically, so an empty --backends is fine")
    p.add_argument("--register-with", default=None, dest="register_with",
                   metavar="URL",
                   help="--mode serve: announce this replica to a gateway "
                        "(POST <URL>/v1/fleet/register) and heartbeat-"
                        "renew the membership lease at the cadence the "
                        "gateway asks for; SIGTERM deregisters FIRST, so "
                        "the gateway stops routing here before the drain "
                        "starts answering 503")
    p.add_argument("--lease-ttl", type=float, default=10.0,
                   dest="lease_ttl", metavar="S",
                   help="--mode gateway: registration lease TTL for "
                        "dynamically registered replicas (default 10). A "
                        "missed renewal demotes through the probe "
                        "hysteresis — never an instant delete — and only "
                        "a long-expired, non-UP member is garbage-"
                        "collected")
    p.add_argument("--admit-wait", type=float, default=0.5,
                   dest="admit_wait", metavar="S",
                   help="--mode gateway: when EVERY routable backend is "
                        "saturated, how long an interactive request may "
                        "queue at the front door for a slot to free "
                        "before being shed with a fleet-derived "
                        "Retry-After (default 0.5; 0 = always shed; "
                        "batch-class requests never queue)")
    p.add_argument("--admit-queue", type=int, default=32,
                   dest="admit_queue", metavar="N",
                   help="--mode gateway: how many saturated-fleet "
                        "requests may queue at once (default 32; past "
                        "that, shed immediately — a bounded queue, not "
                        "buffer bloat)")
    p.add_argument("--route-policy", choices=["p2c", "round_robin",
                                              "prefix"],
                   default="p2c", dest="route_policy",
                   help="--mode gateway: routing policy — p2c "
                        "(power-of-two-choices on the live /healthz load "
                        "signal; default), round_robin, or prefix "
                        "(prefix-affinity: same-prefix prompts land on "
                        "the replica whose engine prefix store already "
                        "holds their KV, p2c fallback when it is "
                        "saturated)")
    p.add_argument("--probe-interval", type=float, default=2.0,
                   dest="probe_interval", metavar="S",
                   help="--mode gateway: seconds between /healthz probe "
                        "passes (default 2.0); DOWN backends re-probe on "
                        "a jittered backoff instead (the circuit "
                        "breaker)")
    p.add_argument("--gateway-prefix-block", type=int, default=64,
                   dest="gateway_prefix_block", metavar="N",
                   help="--mode gateway: prefix-affinity alignment — the "
                        "routing key is the FIRST N tokens of the prompt "
                        "(characters for a text prompt), so prompts "
                        "sharing a system prefix route together whatever "
                        "their tail length; prompts shorter than N get "
                        "no preference (default 64, matching the "
                        "engine's prefix_block)")
    p.add_argument("--logit-bias", default=None, dest="logit_bias",
                   metavar="ID:BIAS[,ID:BIAS...]",
                   help="static token-id logit biases compiled into the "
                        "sampler (all modes; serve requests passing "
                        "logit_bias must match these values exactly)")
    p.add_argument("--log-level", default="info", dest="log_level",
                   choices=["debug", "info", "warning", "error"],
                   help="root log level for this process (master or worker "
                        "subprocess alike; -v forces debug)")
    p.add_argument("-v", "--verbose", action="store_true")
    return p


_DTYPES = {"bf16": "bfloat16", "f16": "bfloat16", "f32": "float32"}


def _load_config(args):
    from cake_tpu.models.config import LlamaConfig

    cfg_path = Path(args.model) / "config.json"
    if not cfg_path.exists():
        sys.exit(f"error: {cfg_path} not found")
    overrides = {"dtype": _DTYPES[args.dtype]}
    if args.max_seq:
        overrides["max_seq_len"] = args.max_seq
    if getattr(args, "window", None) is not None:
        # 0 disables the checkpoint's window; N narrows (or grants) one
        overrides["sliding_window"] = args.window or None
    config = LlamaConfig.from_hf_json(cfg_path, **overrides)
    if config.sliding_window and getattr(args, "sp", 1) > 1:
        sys.exit("error: sliding-window attention (this checkpoint's "
                 "family) does not compose with --sp; run with --sp 1")
    if getattr(args, "ep", 1) > 1 and not config.num_local_experts:
        sys.exit("error: --ep requires an MoE checkpoint "
                 "(num_local_experts > 0 in config.json)")
    return config


def _mesh_params(args, config, plan):
    """Load checkpoint params onto the mesh, direct-to-mesh (each shard's
    bytes only — the reference worker's own-blocks-only contract,
    worker.rs:85-98 — including int8 MoE expert stacks)."""
    from cake_tpu.utils.sharded_load import load_llama_params_on_mesh

    try:
        return load_llama_params_on_mesh(
            args.model, config, plan.mesh, quantize=args.quantize,
            tie_word_embeddings=config.tie_word_embeddings)
    except NotImplementedError as e:  # e.g. int4 MoE: clean exit, no trace
        sys.exit(f"error: {e}")


def _load_tokenizer(model_dir: str):
    tok_path = Path(model_dir) / "tokenizer.json"
    if tok_path.exists():
        try:
            from tokenizers import Tokenizer

            return Tokenizer.from_file(str(tok_path))
        except Exception as e:
            log.warning("tokenizer load failed: %s", e)
    return None


def _settings(args):
    from cake_tpu.ops.sampling import SamplerSettings

    bias: tuple = ()
    if getattr(args, "logit_bias", None):
        try:
            bias = tuple(sorted(
                (int(tok), float(b))
                for tok, _, b in (pair.partition(":")
                                  for pair in args.logit_bias.split(","))
            ))
        except ValueError:
            sys.exit("error: --logit-bias wants ID:BIAS[,ID:BIAS...] "
                     f"(got {args.logit_bias!r})")
    return SamplerSettings(
        temperature=args.temperature,
        top_k=args.top_k,
        top_p=args.top_p,
        repeat_penalty=args.repeat_penalty,
        repeat_last_n=args.repeat_last_n,
        seed=args.seed,
        logit_bias=bias,
    )


def _failure_domain_flags(args) -> list[str]:
    """Names of the worker-link failure-domain flags the user actually set
    — they only mean something on a host-addressed topology master."""
    out = []
    if args.recover_deadline is not None:
        out.append("--recover-deadline")
    if args.connect_retries:
        out.append("--connect-retries")
    if args.op_timeout is not None:
        out.append("--op-timeout")
    if args.chaos:
        out.append("--chaos")
    return out


def run_worker(args) -> int:
    from cake_tpu.parallel.topology import Topology
    from cake_tpu.runtime.worker import Worker
    from cake_tpu.utils.memory import memory_report
    from cake_tpu.utils.weights import load_llama_params

    if not args.name:
        sys.exit("error: --mode worker requires --name")
    if not args.topology:
        sys.exit("error: --mode worker requires --topology")
    if args.cluster_report or args.top:
        sys.exit("error: --cluster-report/--top are master-side aggregation "
                 "views; pass them to the master process (they would "
                 "otherwise be silently ignored in worker mode)")
    if _failure_domain_flags(args):
        sys.exit("error: --recover-deadline/--connect-retries/--op-timeout/"
                 "--chaos drive the master's side of the worker links; pass "
                 "them to the master process (they would otherwise be "
                 "silently ignored in worker mode)")
    config = _load_config(args)
    topology = Topology.from_path(args.topology)

    def loader(lo, hi):
        return load_llama_params(
            args.model, config.num_hidden_layers, dtype=config.dtype,
            layer_range=(lo, hi), include_embed=False, include_head=False,
            quantize=args.quantize,
        )["layers"]

    worker = Worker(args.name, config, topology, loader,
                    address=args.address, max_seq=args.max_seq,
                    kv_quant=args.kv_quant, wire_codec=args.wire_codec)
    if args.status_port is not None:
        worker.start_status_server(args.status_port, bind=args.status_bind)
    log.info("worker ready (%s)", memory_report())
    try:
        worker.serve_forever()
    except KeyboardInterrupt:
        worker.shutdown()
    return 0


def run_serve(args) -> int:
    """Concurrent multi-prompt serving over the batched mesh pipeline
    (--prompts-file): capability the single-request reference does not have
    (SURVEY.md §0)."""
    from cake_tpu.runtime.batch_generator import BatchGenerator
    from cake_tpu.utils.memory import memory_report
    from cake_tpu.utils.weights import load_llama_params

    if args.topology:
        sys.exit("error: --prompts-file serving runs the mesh pipeline; "
                 "--topology (cross-host workers) is not supported here")
    # Reject flags this path would otherwise silently ignore (run_master
    # gives the same treatment to its invalid combinations). --sp composes
    # with serving since r4 (the KV window shards across the sp axis —
    # many long streams per chip set) except with --speculate, whose
    # verification programs are the sp == 1 path.
    if args.sp > 1 and args.speculate:
        sys.exit("error: --speculate requires --sp 1 on the serving path")
    if args.prefill_chunks > 1:
        sys.exit("error: --prefill-chunks is not supported with "
                 "--prompts-file serving")
    # "none" is the documented default — a semantic no-op, not a request
    # for compression; only a compressing codec is misplaced here
    if args.wire_codec not in (None, "none"):
        sys.exit("error: --wire-codec applies to cross-host worker hops "
                 "(master/worker --topology runs); serving rides the mesh")
    if args.lookahead and args.decode_block == 1:
        sys.exit("error: --lookahead needs fused blocks to pipeline; it "
                 "requires --decode-block > 1 (it would otherwise be "
                 "silently ignored)")
    if args.cluster_report or args.top:
        sys.exit("error: --cluster-report/--top aggregate across cross-host "
                 "workers (master/worker --topology runs); serving rides "
                 "the mesh")
    flags = _failure_domain_flags(args)
    if flags:
        sys.exit(f"error: {'/'.join(flags)} apply to cross-host worker "
                 "links (master/worker --topology runs); serving rides "
                 "the mesh")
    config = _load_config(args)
    tokenizer = _load_tokenizer(args.model)
    settings = _settings(args)

    prompts: list = []
    with open(args.prompts_file) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            if args.prompts_ids:
                toks = [t.strip() for t in line.split(",")]
                if not all(t.isdigit() for t in toks):
                    sys.exit(f"error: --prompts-ids line is not a "
                             f"comma-separated id list: {line!r}")
                prompts.append([int(t) for t in toks])
            elif tokenizer is None:
                sys.exit("error: text prompts require a tokenizer.json; "
                         "pass --prompts-ids with comma-separated token ids "
                         "per line")
            else:
                prompts.append(line)
    if not prompts:
        sys.exit(f"error: no prompts in {args.prompts_file}")

    t0 = time.perf_counter()
    from cake_tpu.parallel.mesh import MeshPlan

    try:
        plan = MeshPlan.build(config, num_stages=args.stages, tp=args.tp,
                              dp=args.dp, sp=args.sp, ep=args.ep)
    except ValueError as e:
        sys.exit(f"error: {e}")
    params = _mesh_params(args, config, plan)
    # --decode-block composes with --speculate here: spec rounds replace
    # block dispatches while proposals/window allow, and the fused block
    # remains the fallback (e.g. a stream at its window edge)
    try:
        gen = BatchGenerator(config, params, plan=plan, tokenizer=tokenizer,
                             settings=settings, max_seq=args.max_seq,
                             block_size=(args.decode_block
                                         if args.decode_block is not None
                                         else 8),
                             lookahead=args.lookahead,
                             kv_quant=args.kv_quant, spec_k=args.speculate,
                             **_kv_layout_kwargs(args))
    except ValueError as e:  # e.g. --max-seq not divisible by --sp
        sys.exit(f"error: {e}")
    gen.set_prompts(prompts)
    log.info("model loaded in %.1fs (%s); serving %d streams",
             time.perf_counter() - t0, memory_report(), len(prompts))
    t_gen0 = time.perf_counter()
    outs = gen.generate(args.sample_len)
    dt = time.perf_counter() - t_gen0
    total = sum(len(o) for o in outs)
    for i, o in enumerate(outs):
        # decode the quota-truncated ids, not gen.texts(): ragged
        # speculation can bank tokens past -n, and printed text must agree
        # with the token counts the log reports
        if tokenizer is not None:
            print(f"[{i}] {tokenizer.decode(o)}")
        else:
            print(f"[{i}] {','.join(map(str, o))}")
    log.info("%d streams, %d tokens, %.2f tok/s aggregate — %s",
             len(outs), total, total / dt, memory_report())
    st = gen.stats()
    log.info("serving stats: %d decode + %d admission dispatches, "
             "%.2f tokens/dispatch, busy %.2fs of %.2fs wall",
             st["decode_dispatches"], st["admit_dispatches"],
             st["tokens_per_dispatch"] or 0.0, st["busy_s"], st["wall_s"])
    return 0


def _kv_layout_kwargs(args) -> dict:
    """BatchGenerator kwargs for the --kv-layout flags (defaults stay the
    engine's own when the user did not set them)."""
    kw = {"kv_layout": args.kv_layout}
    if args.kv_page_size is not None:
        kw["kv_page_size"] = args.kv_page_size
    if args.kv_pool_pages is not None:
        kw["kv_pool_pages"] = args.kv_pool_pages
    return kw


def _serve_flags(args) -> list[str]:
    """Names of the --mode serve flags the user actually set — they mean
    nothing on the one-shot master/worker paths."""
    out = []
    if args.serve_port is not None:
        out.append("--serve-port")
    if args.serve_bind is not None:
        out.append("--serve-bind")
    if args.max_concurrent is not None:
        out.append("--max-concurrent")
    if args.queue_depth is not None:
        out.append("--queue-depth")
    if args.request_timeout is not None:
        out.append("--request-timeout")
    if args.serve_logprobs:
        out.append("--serve-logprobs")
    if args.role != "mixed":
        out.append("--role")
    if args.transfer_port is not None:
        out.append("--transfer-port")
    if args.transfer_codec != "none":
        out.append("--transfer-codec")
    if args.register_with is not None:
        out.append("--register-with")
    if args.slo_ttft_ms is not None:
        out.append("--slo-ttft-ms")
    if args.slo_tpot_ms is not None:
        out.append("--slo-tpot-ms")
    if args.sched_policy != "slo":
        out.append("--sched-policy")
    if args.spill_mb != 64.0:
        out.append("--spill-mb")
    if args.fairness_factor != 2.0:
        out.append("--fairness-factor")
    return out


def _slo_tracker(args):
    """SLO accounting shared by serve and gateway (obs/reqtrace): built
    only when a target is set, so untargeted runs pay nothing."""
    if args.slo_ttft_ms is None and args.slo_tpot_ms is None:
        return None
    from cake_tpu.obs.reqtrace import SloPolicy, SloTracker

    return SloTracker(SloPolicy(ttft_ms=args.slo_ttft_ms,
                                tpot_ms=args.slo_tpot_ms))


def run_http_serve(args) -> int:
    """--mode serve: the network-facing request-serving plane
    (cake_tpu/serve) — an HTTP API + SLO-aware scheduler over the
    continuous-batching engine. Runs over every execution path the
    one-shot master supports: all-local and mesh (--stages/--tp/--sp/--ep
    or a device-indexed topology) ride BatchGenerator; a host-addressed
    --topology rides the single-stream wire master behind a one-slot
    engine adapter (requests serialize, every failure-domain knob still
    applies)."""
    import signal
    import threading

    from cake_tpu import __version__, obs
    from cake_tpu.obs import metrics as obs_metrics
    from cake_tpu.serve.api import start_api_server
    from cake_tpu.serve.scheduler import Scheduler
    from cake_tpu.utils.memory import memory_report

    serve_port = args.serve_port if args.serve_port is not None else 8080
    serve_bind = args.serve_bind or "127.0.0.1"
    max_concurrent = (args.max_concurrent
                      if args.max_concurrent is not None else 8)
    queue_depth = args.queue_depth if args.queue_depth is not None else 64
    request_timeout = (args.request_timeout
                       if args.request_timeout is not None else 300.0)
    if max_concurrent < 1:
        sys.exit("error: --max-concurrent must be >= 1")
    if queue_depth < 1:
        sys.exit("error: --queue-depth must be >= 1")
    if request_timeout <= 0:
        sys.exit("error: --request-timeout must exceed 0 (every request "
                 "needs a deadline; raise it instead of disabling it)")
    if args.prompts_file or args.prompt_ids:
        sys.exit("error: --mode serve takes prompts over HTTP "
                 "(POST /v1/completions); --prompts-file/--prompt-ids "
                 "belong to the one-shot paths")
    if args.cluster_report or args.top:
        sys.exit("error: --cluster-report/--top report on a one-shot "
                 "master run; --mode serve exposes the same data live on "
                 "/ and /metrics instead (they would otherwise be "
                 "silently ignored)")
    if args.prefill_chunks > 1:
        sys.exit("error: --prefill-chunks is not supported with --mode "
                 "serve (arrivals prefill chunk-by-chunk through the "
                 "admission path instead; it would otherwise be silently "
                 "ignored)")
    if args.role != "mixed" and args.kv_layout != "paged":
        sys.exit(f"error: --role {args.role} moves KV between replicas "
                 "as pool pages; it requires --kv-layout paged")
    if args.role == "prefill" and args.transfer_port is not None:
        sys.exit("error: --transfer-port opens the IMPORT listener; a "
                 "prefill replica only exports (its targets arrive "
                 "per-request from the gateway)")

    config = _load_config(args)
    tokenizer = _load_tokenizer(args.model)
    settings = _settings(args)
    t0 = time.perf_counter()

    # topology: device-indexed drives the mesh plan, host-addressed the
    # cross-host wire path (same split as run_master)
    topology = None
    topo_mesh = False
    if args.topology:
        from cake_tpu.parallel.topology import Topology

        topology = Topology.from_path(args.topology)
        with_dev = [n.name for n in topology if n.device is not None]
        without = [n.name for n in topology if n.device is None]
        if with_dev and without:
            sys.exit(
                f"error: topology mixes mesh nodes (device: {with_dev}) "
                f"with host-addressed workers ({without}); a deployment is "
                "one or the other"
            )
        topo_mesh = bool(with_dev)

    if topology is not None and not topo_mesh:
        # host-addressed workers: the single-stream wire master behind the
        # one-slot engine adapter. Concurrency serializes at 1.
        from cake_tpu.serve.engine import SingleStreamEngine

        if args.stages > 1 or args.tp > 1 or args.sp > 1 or args.ep > 1:
            sys.exit("error: --stages/--tp/--sp/--ep (single-program mesh) "
                     "and a host-addressed --topology are mutually "
                     "exclusive in serve mode too")
        if args.speculate:
            sys.exit("error: --speculate is not supported on the "
                     "host-topology serve path")
        if args.decode_block is not None or args.lookahead:
            sys.exit("error: --decode-block/--lookahead need the batched "
                     "mesh engine; the host-topology serve path "
                     "single-steps the wire master (they would otherwise "
                     "be silently ignored)")
        if args.serve_logprobs:
            sys.exit("error: --serve-logprobs needs the batched mesh "
                     "engine; the host-topology serve path has no "
                     "logprob outputs (it would otherwise be silently "
                     "ignored)")
        if args.kv_layout == "paged":
            sys.exit("error: --kv-layout paged rides the batched mesh "
                     "engine; a host-addressed --topology serve runs "
                     "the single-stream wire master")
        if max_concurrent > 1:
            log.warning("--max-concurrent %d: a host-addressed --topology "
                        "serves over the single-stream wire master; "
                        "requests serialize through 1 slot",
                        max_concurrent)
        engine = SingleStreamEngine(_build_distributed_gen(
            args, config, topology, tokenizer, settings))
        warm_len = None
    else:
        from cake_tpu.parallel.mesh import MeshPlan
        from cake_tpu.runtime.batch_generator import BatchGenerator

        flags = _failure_domain_flags(args)
        if flags:
            sys.exit(f"error: {'/'.join(flags)} apply to cross-host worker "
                     "links (a host-addressed --topology); this serve "
                     "deployment rides the mesh")
        if args.wire_codec not in (None, "none"):
            sys.exit("error: --wire-codec applies to cross-host worker "
                     "hops; this serve deployment rides the mesh")
        if args.sp > 1 and args.speculate:
            sys.exit("error: --speculate requires --sp 1 on the serving "
                     "path")
        if args.lookahead and args.decode_block == 1:
            sys.exit("error: --lookahead needs fused blocks to pipeline; "
                     "it requires --decode-block > 1")
        try:
            if topo_mesh:
                plan = MeshPlan.from_topology(config, topology, tp=args.tp,
                                              sp=args.sp, ep=args.ep)
            else:
                plan = MeshPlan.build(config, num_stages=args.stages,
                                      tp=args.tp, dp=args.dp, sp=args.sp,
                                      ep=args.ep)
        except ValueError as e:
            sys.exit(f"error: {e}")
        params = _mesh_params(args, config, plan)
        try:
            engine = BatchGenerator(
                config, params, plan=plan, tokenizer=tokenizer,
                settings=settings, max_seq=args.max_seq,
                block_size=(args.decode_block
                            if args.decode_block is not None else 8),
                lookahead=args.lookahead, kv_quant=args.kv_quant,
                spec_k=args.speculate, logprobs=args.serve_logprobs,
                **_kv_layout_kwargs(args))
        except ValueError as e:
            sys.exit(f"error: {e}")
        # compile the admission path outside the serving window (requests
        # of any length share the chunked program for this bucket)
        warm_len = min(64, engine.max_seq // 2)

    try:
        scheduler = Scheduler(engine, queue_depth=queue_depth,
                              request_timeout_s=request_timeout,
                              role=args.role,
                              transfer_codec=args.transfer_codec,
                              slo=_slo_tracker(args),
                              sched_policy=args.sched_policy,
                              spill_mb=args.spill_mb,
                              fairness_factor=args.fairness_factor)
    except ValueError as e:
        sys.exit(f"error: {e}")
    # warm the masked (constrained-decoding) program too when requests
    # could carry response_format — i.e. whenever a tokenizer is loaded
    # (grammars compile against the vocab's decoded strings)
    scheduler.start(max_concurrent=max_concurrent, warm_prompt_len=warm_len,
                    warm_constrain=tokenizer is not None)

    # KV transfer listener (cake_tpu/disagg): a decode replica always
    # accepts imports (ephemeral port unless pinned); a mixed replica
    # only when --transfer-port asked for one (session suspend/resume
    # without a tier split). Its port rides /healthz so the gateway's
    # tier map discovers it.
    xfer_server = None
    if args.role == "decode" or args.transfer_port is not None:
        from cake_tpu.disagg import TransferServer

        xfer_server = TransferServer(scheduler, bind=serve_bind,
                                     port=args.transfer_port or 0).start()
        scheduler.transfer_port = xfer_server.port
        log.info("KV transfer channel on %s:%d (--role %s)", serve_bind,
                 xfer_server.port, args.role)

    def serve_status():
        return {
            "role": "serve",
            "version": __version__,
            "model": str(args.model),
            "scheduler": scheduler.stats(),
            "metrics": obs_metrics.registry().snapshot(),
        }

    # graceful drain: SIGTERM/SIGINT — or a gateway-driven
    # POST /v1/fleet/drain (rolling restart) — stop admission, in-flight
    # streams finish or migrate, artifacts flush
    stop = threading.Event()

    server = start_api_server(scheduler, status_fn=serve_status,
                              bind=serve_bind, port=serve_port,
                              model_id=Path(args.model).name or "cake-tpu",
                              on_drain=stop.set)
    registrar = None
    if args.register_with:
        from cake_tpu.serve.register import Registrar

        registrar = Registrar(
            args.register_with, f"{serve_bind}:{server.port}",
            role=args.role,
            transfer_port=xfer_server.port if xfer_server else 0).start()
        log.info("registering with gateway %s as %s:%d",
                 args.register_with, serve_bind, server.port)
    status_httpd = None
    if args.status_port is not None:
        # optional standalone status page (byte-identical surface; the API
        # port already serves / + /metrics)
        from cake_tpu.obs import statusd

        status_httpd, bound = statusd.start_status_server(
            serve_status, bind=args.status_bind, port=args.status_port)
        log.info("status page on http://%s:%d/", args.status_bind, bound)
    log.info("model loaded in %.1fs (%s); serving on http://%s:%d/ "
             "(%d slots, queue %d, %ss deadline)",
             time.perf_counter() - t0, memory_report(), serve_bind,
             server.port, scheduler.max_concurrent, queue_depth,
             request_timeout)

    def _on_signal(signum, frame):
        log.info("signal %d: draining (no new admissions; in-flight "
                 "streams finish)", signum)
        stop.set()

    for signum in (signal.SIGTERM, signal.SIGINT):
        signal.signal(signum, _on_signal)
    try:
        stop.wait()
    finally:
        # deregister BEFORE the drain starts answering 503s: the
        # gateway pins this member DRAINING immediately, so the probe
        # race window (up to one --probe-interval) can't route a
        # request into the exit
        if registrar is not None:
            registrar.deregister()
        server.drain(timeout_s=request_timeout)
        if xfer_server is not None:
            xfer_server.stop()
        if status_httpd is not None:
            status_httpd.shutdown()
            status_httpd.server_close()
        scheduler.close()
        obs.flush_artifacts()
        log.info("drained; bye")
    return 0


def _gateway_flags(args) -> list[str]:
    """Names of the --mode gateway flags the user actually set — they
    mean nothing on the single-process modes."""
    out = []
    if args.backends is not None:
        out.append("--backends")
    if args.route_policy != "p2c":
        out.append("--route-policy")
    if args.probe_interval != 2.0:
        out.append("--probe-interval")
    if args.gateway_prefix_block != 64:
        out.append("--gateway-prefix-block")
    if args.lease_ttl != 10.0:
        out.append("--lease-ttl")
    if args.admit_wait != 0.5:
        out.append("--admit-wait")
    if args.admit_queue != 32:
        out.append("--admit-queue")
    return out


def run_gateway(args) -> int:
    """--mode gateway: the multi-replica routing front door
    (cake_tpu/gateway) — health-checked, load-aware routing of the
    serving API across a fleet of --mode serve replicas. The gateway
    holds no model and touches no accelerator: it is pure fleet plumbing
    (probes, policy, proxy), so one host can front many."""
    import signal
    import threading

    from cake_tpu import __version__, obs
    from cake_tpu.gateway.api import parse_backends, start_gateway
    from cake_tpu.gateway.health import HealthMonitor
    from cake_tpu.gateway.policy import make_policy
    from cake_tpu.obs import metrics as obs_metrics

    if args.model:
        sys.exit("error: --model belongs to the serving/generation modes; "
                 "a gateway holds no model — point --backends at --mode "
                 "serve replicas instead")
    if args.topology:
        sys.exit("error: --topology describes a model deployment; the "
                 "gateway's fleet is --backends (each backend may itself "
                 "run a --topology)")
    if args.prompts_file or args.prompt_ids:
        sys.exit("error: --mode gateway takes requests over HTTP "
                 "(POST /v1/completions); --prompts-file/--prompt-ids "
                 "belong to the one-shot paths")
    if args.cluster_report or args.top:
        sys.exit("error: --cluster-report/--top aggregate a master's "
                 "workers; the gateway exposes its fleet view on / and "
                 "/metrics instead")
    flags = _failure_domain_flags(args)
    if flags:
        sys.exit(f"error: {'/'.join(flags)} drive a master's worker "
                 "links; the gateway's failure handling is built in "
                 "(probes, breaker, transparent retry)")
    engine_flags = [f for f, on in (
        ("--max-concurrent", args.max_concurrent is not None),
        ("--queue-depth", args.queue_depth is not None),
        ("--serve-logprobs", bool(args.serve_logprobs)),
        ("--role", args.role != "mixed"),
        ("--transfer-port", args.transfer_port is not None),
        ("--transfer-codec", args.transfer_codec != "none"),
        ("--register-with", args.register_with is not None),
    ) if on]
    if engine_flags:
        sys.exit(f"error: {'/'.join(engine_flags)} configure a serve "
                 "replica's engine; pass them to the --mode serve "
                 "processes behind --backends")
    if args.probe_interval <= 0:
        sys.exit("error: --probe-interval must exceed 0")
    if args.gateway_prefix_block < 1:
        sys.exit("error: --gateway-prefix-block must be >= 1")
    if args.request_timeout is not None and args.request_timeout <= 0:
        sys.exit("error: --request-timeout must exceed 0")
    if args.lease_ttl <= 0:
        sys.exit("error: --lease-ttl must exceed 0")
    if args.admit_wait < 0:
        sys.exit("error: --admit-wait must be >= 0")
    if args.admit_queue < 1:
        sys.exit("error: --admit-queue must be >= 1")

    serve_port = args.serve_port if args.serve_port is not None else 8080
    serve_bind = args.serve_bind or "127.0.0.1"
    request_timeout = (args.request_timeout
                       if args.request_timeout is not None else 300.0)
    try:
        backends = parse_backends(args.backends) if args.backends else []
    except ValueError as e:
        sys.exit(f"error: {e}")
    # an empty --backends is a valid start state: the fleet forms (or
    # RE-forms, after a gateway restart) from replica self-registrations
    monitor = HealthMonitor(backends, probe_interval=args.probe_interval,
                            lease_ttl_s=args.lease_ttl, allow_empty=True)
    policy = make_policy(args.route_policy,
                         prefix_block=args.gateway_prefix_block)
    monitor.start()

    def gateway_status():
        return {
            "role": "gateway",
            "version": __version__,
            "policy": args.route_policy,
            "backends": monitor.describe(),
            "metrics": obs_metrics.registry().snapshot(),
        }

    server = start_gateway(monitor, policy, bind=serve_bind,
                           port=serve_port,
                           prefix_block=args.gateway_prefix_block,
                           read_timeout=request_timeout,
                           status_fn=gateway_status,
                           slo=_slo_tracker(args),
                           admit_wait_s=args.admit_wait,
                           admit_queue=args.admit_queue)
    status_httpd = None
    if args.status_port is not None:
        from cake_tpu.obs import statusd

        status_httpd, bound = statusd.start_status_server(
            gateway_status, bind=args.status_bind, port=args.status_port)
        log.info("status page on http://%s:%d/", args.status_bind, bound)
    up = len(monitor.routable())
    log.info("gateway on http://%s:%d/ — %d backend(s), %d up, "
             "policy %s, probe every %gs",
             serve_bind, server.port, len(backends), up,
             args.route_policy, args.probe_interval)
    if not up:
        log.warning("no backend answered the initial probe; serving 503 "
                    "until one comes up")

    stop = threading.Event()

    def _on_signal(signum, frame):
        log.info("signal %d: draining (no new admissions; in-flight "
                 "proxied streams finish)", signum)
        stop.set()

    for signum in (signal.SIGTERM, signal.SIGINT):
        signal.signal(signum, _on_signal)
    try:
        stop.wait()
    finally:
        server.drain(timeout_s=request_timeout)
        if status_httpd is not None:
            status_httpd.shutdown()
            status_httpd.server_close()
        monitor.stop()
        obs.flush_artifacts()
        log.info("drained; bye")
    return 0


def _build_distributed_gen(args, config, topology, tokenizer, settings):
    """Cross-host master over a host-addressed topology (shared by the
    one-shot master and --mode serve's single-stream engine path): head
    params + per-segment loaders, optional --chaos proxy wiring, runner
    handshakes with the failure-domain knobs."""
    from cake_tpu.runtime.master import DistributedGenerator, build_runners
    from cake_tpu.utils.weights import load_llama_params

    if args.kv_quant:
        sys.exit("error: --kv-quant on the master applies to the local "
                 "and mesh paths; pass it to each worker process "
                 "instead (workers own their layers' caches)")
    head = load_llama_params(
        args.model, config.num_hidden_layers, dtype=config.dtype,
        layer_range=(0, 0), quantize=args.quantize,
    )

    def loader(lo, hi):
        return load_llama_params(
            args.model, config.num_hidden_layers, dtype=config.dtype,
            layer_range=(lo, hi), include_embed=False, include_head=False,
            quantize=args.quantize,
        )["layers"]

    if args.chaos:
        # DEV fault injection: one frame-aware chaos proxy per worker
        # address, each running the same seeded/explicit schedule, and
        # the topology rewired through them — any failure mode is
        # reproducible from the spec (or its seed) alone.
        from cake_tpu.testing import chaos as chaos_mod

        try:
            faults = chaos_mod.parse_spec(args.chaos)
        except ValueError as e:
            sys.exit(f"error: bad --chaos spec: {e}")
        log.warning("chaos enabled: %s — faults WILL be injected on "
                    "every worker link",
                    ", ".join(str(f) for f in faults))
        for node in topology:
            wrapped = []
            for a in (node.hosts or ([node.host] if node.host else [])):
                host, _, port = a.partition(":")
                proxy = chaos_mod.ChaosProxy(
                    host, int(port or 10128), faults).start()
                wrapped.append(proxy.addr)
                log.info("chaos proxy %s -> %s", proxy.addr, a)
            if wrapped:
                node.hosts = wrapped
                node.host = wrapped[0]

    try:
        runners = build_runners(config, topology, loader,
                                max_seq=args.max_seq,
                                wire_codec=args.wire_codec or "none",
                                op_timeout_s=args.op_timeout,
                                connect_retries=args.connect_retries,
                                recover_deadline_s=args.recover_deadline)
    except RuntimeError as e:  # e.g. worker rejects the codec
        sys.exit(f"error: {e}")
    return DistributedGenerator(config, head, runners, tokenizer=tokenizer,
                                settings=settings, max_seq=args.max_seq)


def run_master(args) -> int:
    from cake_tpu.utils.memory import memory_report
    from cake_tpu.utils.weights import load_llama_params

    config = _load_config(args)
    tokenizer = _load_tokenizer(args.model)
    settings = _settings(args)

    t0 = time.perf_counter()
    # One config plane drives both deployments (the reference's contract,
    # topology.rs:41-84): a topology whose nodes carry mesh `device:` indices
    # selects the single-program mesh pipeline (stage count and layer ranges
    # from the YAML via MeshPlan.from_topology); host-addressed nodes select
    # the cross-host master/worker runtime.
    topology = None
    topo_mesh = False
    if args.topology:
        from cake_tpu.parallel.topology import Topology

        topology = Topology.from_path(args.topology)
        with_dev = [n.name for n in topology if n.device is not None]
        without = [n.name for n in topology if n.device is None]
        if with_dev and without:
            sys.exit(
                f"error: topology mixes mesh nodes (device: {with_dev}) "
                f"with host-addressed workers ({without}); a deployment is "
                "one or the other"
            )
        topo_mesh = bool(with_dev)
    use_mesh = (args.stages > 1 or args.tp > 1 or args.sp > 1
                or args.ep > 1 or topo_mesh)
    if args.speculate and (args.sp > 1 or args.topology):
        sys.exit("error: --speculate runs the local or mesh (stages/tp) "
                 "paths; it is not supported with --sp or --topology (it "
                 "would otherwise be silently ignored)")
    if args.speculate and args.decode_block is not None:
        sys.exit("error: --decode-block does not compose with --speculate "
                 "(speculative rounds replace fused-block dispatches; the "
                 "flag would otherwise be silently ignored)")
    if args.wire_codec not in (None, "none") and (
        use_mesh or not args.topology
    ):
        # explicit "none" is the default spelled out — harmless anywhere
        sys.exit("error: --wire-codec applies to cross-host worker hops; "
                 "it needs a host-addressed --topology (it would otherwise "
                 "be silently ignored)")
    if (args.cluster_report or args.top) and (use_mesh or not args.topology):
        sys.exit("error: --cluster-report/--top aggregate across cross-host "
                 "workers; they need a host-addressed --topology (they "
                 "would otherwise be silently ignored)")
    _fd_flags = _failure_domain_flags(args)
    if _fd_flags and (use_mesh or not args.topology):
        sys.exit(f"error: {'/'.join(_fd_flags)} drive cross-host worker "
                 "links; they need a host-addressed --topology (they "
                 "would otherwise be silently ignored)")
    if args.straggler_factor <= 1.0:
        sys.exit("error: --straggler-factor must exceed 1.0 (a worker at "
                 "the median is not a straggler)")
    if args.op_timeout is not None and args.op_timeout <= 0:
        sys.exit("error: --op-timeout must exceed 0 (omit the flag for the "
                 "segment-scaled default; there is no 'no deadline' mode — "
                 "that is the hung-peer hole this flag closes)")
    if args.recover_deadline is not None and args.recover_deadline <= 0:
        sys.exit("error: --recover-deadline must exceed 0")
    if args.lookahead:
        # lookahead needs the fused-block programs (all-local path here,
        # BatchGenerator on the serving path); reject combinations that
        # would silently ignore it
        if args.speculate:
            sys.exit("error: --lookahead does not compose with --speculate "
                     "(the spec plane needs the host between dispatches)")
        if use_mesh or args.topology:
            sys.exit("error: --lookahead runs the all-local fused-block "
                     "path (or --prompts-file serving); it is not "
                     "supported with --stages/--tp/--sp or --topology")
        if args.decode_block == 1:
            sys.exit("error: --lookahead needs fused blocks to pipeline; "
                     "it requires --decode-block > 1 (it would otherwise "
                     "be silently ignored)")
    decode_block = args.decode_block if args.decode_block is not None else 8
    if args.prefill_chunks > 1:
        # Overlap needs stages to overlap across, and the sp plane owns
        # long-context prefill — reject combinations that would silently do
        # nothing (stages=1) or die in a traceback (sp>1). A device-indexed
        # topology resolves its stage count later; MeshGenerator/the
        # builders re-validate and the error is surfaced below.
        if args.sp > 1:
            sys.exit("error: --prefill-chunks requires --sp 1 (ring "
                     "attention is the sequence-parallel prefill plane)")
        if not (args.stages > 1 or topo_mesh):
            sys.exit(
                "error: --prefill-chunks pipelines the prompt across mesh "
                "stages; it requires --stages > 1 (or a device-indexed "
                "topology), otherwise it would be silently ignored"
            )
    if topo_mesh and args.stages > 1:
        sys.exit(
            "error: --stages conflicts with a device-indexed topology "
            "(the stage count comes from the topology's device entries)"
        )
    if use_mesh and topology is not None and not topo_mesh:
        sys.exit(
            "error: --stages/--tp/--sp (single-program mesh) and a "
            "host-addressed --topology (cross-host workers) are mutually "
            "exclusive; give topology nodes `device:` indices to drive the "
            "mesh from YAML"
        )
    if use_mesh:
        from cake_tpu.runtime.mesh_generator import MeshGenerator

        from cake_tpu.parallel.mesh import MeshPlan

        try:
            if topo_mesh:
                plan = MeshPlan.from_topology(config, topology, tp=args.tp,
                                              sp=args.sp, ep=args.ep)
                log.info("mesh plan from topology: %d stages x tp=%d x sp=%d"
                         " x ep=%d",
                         plan.num_stages, plan.tp, plan.sp, plan.ep)
            else:
                plan = MeshPlan.build(config, num_stages=args.stages,
                                      tp=args.tp, dp=1, sp=args.sp,
                                      ep=args.ep)
        except ValueError as e:
            sys.exit(f"error: {e}")
        params = _mesh_params(args, config, plan)
        try:
            if args.speculate:
                from cake_tpu.runtime.speculative import (
                    MeshSpeculativeGenerator,
                )

                gen = MeshSpeculativeGenerator(
                    config, params, plan=plan, tokenizer=tokenizer,
                    settings=settings, max_seq=args.max_seq,
                    kv_quant=args.kv_quant, spec_k=args.speculate,
                    prefill_chunks=args.prefill_chunks)
            else:
                gen = MeshGenerator(config, params, plan=plan,
                                    tokenizer=tokenizer, settings=settings,
                                    max_seq=args.max_seq,
                                    block_size=decode_block,
                                    prefill_chunks=args.prefill_chunks,
                                    kv_quant=args.kv_quant)
        except ValueError as e:
            sys.exit(f"error: {e}")
    elif args.topology:
        gen = _build_distributed_gen(args, config, topology, tokenizer,
                                     settings)
    else:
        params = load_llama_params(args.model, config.num_hidden_layers,
                                   dtype=config.dtype, quantize=args.quantize)
        if args.speculate:
            from cake_tpu.runtime.speculative import SpeculativeGenerator

            try:
                gen = SpeculativeGenerator(
                    config, params, tokenizer=tokenizer, settings=settings,
                    max_seq=args.max_seq, kv_quant=args.kv_quant,
                    spec_k=args.speculate)
            except ValueError as e:
                sys.exit(f"error: {e}")
        else:
            from cake_tpu.runtime.generator import LlamaGenerator

            gen = LlamaGenerator(config, params, tokenizer=tokenizer,
                                 settings=settings, max_seq=args.max_seq,
                                 block_size=decode_block,
                                 kv_quant=args.kv_quant,
                                 lookahead=args.lookahead)
    log.info("model loaded in %.1fs (%s)", time.perf_counter() - t0,
             memory_report())

    # Master-side status surface (satellite of the worker's): same handler
    # shape, but this registry also carries the merged cluster.* series
    # once the scraper has run — one Prometheus scrape sees the cluster.
    status_httpd = None
    if args.status_port is not None:
        from cake_tpu import __version__
        from cake_tpu.obs import metrics as obs_metrics
        from cake_tpu.obs import statusd

        def master_status():
            st = {
                "role": "master",
                "version": __version__,
                "model": str(args.model),
                "metrics": obs_metrics.registry().snapshot(),
            }
            if hasattr(gen, "runner_stats"):
                st["segments"] = gen.runner_stats()
            return st

        status_httpd, bound = statusd.start_status_server(
            master_status, bind=args.status_bind, port=args.status_port)
        log.info("master status page on http://%s:%d/", args.status_bind,
                 bound)

    top_view = None
    if args.top:
        from cake_tpu.obs.top import Top

        top_view = Top(gen.cluster_scraper(args.straggler_factor))
        top_view.start()

    if args.prompt_ids:
        gen.set_prompt([int(t) for t in args.prompt_ids.split(",")])
    else:
        if tokenizer is None:
            sys.exit(
                "error: no tokenizer.json in the model dir; pass --prompt-ids"
            )
        gen.set_prompt(args.prompt)
        print(args.prompt, end="", flush=True)
    t_gen0 = time.perf_counter()
    n_tokens = 0
    gen_error = None
    gen_ids: list[int] = []
    if args.profile:
        import jax.profiler

        jax.profiler.start_trace(args.profile)
    try:
        for i in range(args.sample_len):
            try:
                tok = gen.next_token(i)
            except Exception as e:
                # end the run with a clean newline instead of a traceback
                # (reference: cake-cli/main.rs:51-55)
                gen_error = e
                break
            n_tokens += 1
            gen_ids.append(tok.id)
            if tok.text:
                print(tok.text, end="", flush=True)
            if i == 0:
                t_warm = time.perf_counter()  # exclude warm-up (master.rs:37-40)
            if tok.is_end_of_stream:
                break
    finally:
        if args.profile:
            jax.profiler.stop_trace()
            log.info("profiler trace written to %s", args.profile)
        if top_view is not None:
            top_view.stop()
    rest = gen.last()
    if rest:
        print(rest, end="")
    if tokenizer is None and gen_ids:
        # id-only runs (no tokenizer.json) still stream SOMETHING observable
        print(",".join(map(str, gen_ids)), end="")
    print()
    if n_tokens > 1:
        dt = time.perf_counter() - t_warm
        log.info("%d tokens, %.2f tok/s (excl. warm-up; TTFT %.2fs) — %s",
                 n_tokens, (n_tokens - 1) / dt,
                 t_warm - t_gen0, memory_report())
    if hasattr(gen, "runner_stats"):
        for s in gen.runner_stats():
            # link fields are each optional: a legacy peer has only the
            # handshake RTT fallback (no clock offset), a local segment
            # neither
            extra = "".join(
                f", {label} {s[key]} ms"
                for key, label in (("handshake_ms", "handshake"),
                                   ("rtt_ms", "rtt"),
                                   ("clock_offset_ms", "clock offset"))
                if key in s
            )
            log.info("segment %s @ %s: %d calls, %.2f ms avg "
                     "(p50 %.2f / p99 %.2f)%s",
                     s["layers"], s["ident"], s["calls"], s["avg_ms"],
                     s.get("p50_ms", 0.0), s.get("p99_ms", 0.0), extra)
    if args.cluster_report:
        # one final scrape while the worker connections are still open
        # (the STATS path rides them); written before close() by design
        import json as _json

        try:
            report = gen.cluster_report(args.straggler_factor)
            with open(args.cluster_report, "w") as f:
                _json.dump(report, f, indent=1)
                f.write("\n")
            log.info("cluster report written to %s", args.cluster_report)
            for name in report.get("stragglers", []):
                log.warning("straggler worker: %s", name)
        except OSError as e:
            log.error("could not write cluster report to %s: %s",
                      args.cluster_report, e)
    if status_httpd is not None:
        status_httpd.shutdown()
        status_httpd.server_close()
    if hasattr(gen, "close"):
        gen.close()
    if gen_error is not None:
        log.error("generation ended early: %s", gen_error)
        return 1
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from cake_tpu import obs

    if args.mode != "gateway" and not args.model:
        sys.exit("error: --model is required (only --mode gateway runs "
                 "without a checkpoint)")
    if args.mode == "gateway" and args.fetch:
        sys.exit("error: --fetch populates --model, and a gateway holds "
                 "no model; fetch on the --mode serve replicas instead")
    obs.setup_logging("debug" if args.verbose else args.log_level)
    if args.trace:
        # --profile already captures an XLA trace; passing spans through as
        # TraceAnnotations lines the two timelines up in one Perfetto view
        obs.tracer().start(xla_annotations=bool(args.profile))
    if args.prof_sample is not None:
        from cake_tpu.obs import prof as _prof

        _prof.profiler().set_sample(args.prof_sample)
    if args.flight_log:
        try:
            obs.flight.recorder().enable(path=args.flight_log)
        except OSError as e:
            # fail before loading the model, not after a full run
            sys.exit(f"error: cannot open --flight-log {args.flight_log}: {e}")
    if args.flight_log or args.metrics_out:
        # durability: a SIGTERM/SIGINT'd run still lands the flight-log
        # tail and a metrics snapshot (the clean-exit writes below only
        # cover runs that reach them)
        obs.install_flush_handlers(metrics_out=args.metrics_out)
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    if args.process_id is not None and not (args.coordinator
                                            or args.num_processes):
        sys.exit("error: --process-id requires --coordinator and/or "
                 "--num-processes (it would otherwise be silently ignored)")
    if (args.coordinator or args.num_processes
            or int(os.environ.get("CAKE_NUM_PROCESSES", "1")) > 1):
        from cake_tpu.parallel.distributed import initialize

        initialize(coordinator=args.coordinator,
                   num_processes=args.num_processes,
                   process_id=args.process_id)
    if args.device is not None:
        import jax

        devices = jax.devices()
        if not 0 <= args.device < len(devices):
            sys.exit(
                f"error: --device {args.device} out of range "
                f"(have {len(devices)} devices)"
            )
        jax.config.update("jax_default_device", devices[args.device])
    if args.fetch:
        from cake_tpu.utils.fetch import fetch_checkpoint

        try:
            fetch_checkpoint(args.fetch, args.model, force=args.refetch)
        except Exception as e:
            sys.exit(f"error: fetch from {args.fetch} failed: {e}")
    if args.kv_layout != "paged" and (args.kv_page_size is not None
                                      or args.kv_pool_pages is not None):
        sys.exit("error: --kv-page-size/--kv-pool-pages configure the "
                 "paged KV pool; they require --kv-layout paged")
    if args.kv_layout == "paged" and (
            args.mode in ("worker", "gateway")
            or (args.mode == "master" and not args.prompts_file)):
        sys.exit("error: --kv-layout paged applies to the batched serving "
                 "engine; it requires --mode serve or a --prompts-file "
                 "batch run (it would otherwise be silently ignored)")
    if args.mode not in ("serve", "gateway") and _serve_flags(args):
        sys.exit(f"error: {'/'.join(_serve_flags(args))} configure the "
                 "HTTP serving plane; they require --mode serve or "
                 "--mode gateway (they would otherwise be silently "
                 "ignored)")
    if args.mode != "gateway" and _gateway_flags(args):
        sys.exit(f"error: {'/'.join(_gateway_flags(args))} configure the "
                 "routing gateway; they require --mode gateway (they "
                 "would otherwise be silently ignored)")
    try:
        if args.mode == "worker":
            return run_worker(args)
        if args.mode == "serve":
            return run_http_serve(args)
        if args.mode == "gateway":
            return run_gateway(args)
        if args.prompts_file:
            return run_serve(args)
        return run_master(args)
    finally:
        # observability outputs land even on an early error/KeyboardInterrupt
        # — and a failing artifact write must never mask the run's own
        # outcome or the other artifacts
        if args.trace:
            obs.tracer().stop()
            try:
                obs.tracer().write_chrome_trace(args.trace)
                log.info("chrome trace written to %s", args.trace)
                if obs.tracer().dropped:
                    log.warning(
                        "trace buffer filled: %d span(s) dropped — the "
                        "timeline in %s is truncated",
                        obs.tracer().dropped, args.trace,
                    )
            except OSError as e:
                log.error("could not write trace to %s: %s", args.trace, e)
        if args.metrics_out:
            try:
                obs.registry().dump_json(args.metrics_out)
                log.info("metrics snapshot written to %s", args.metrics_out)
            except OSError as e:
                log.error("could not write metrics to %s: %s",
                          args.metrics_out, e)
        if args.flight_log:
            obs.flight.recorder().close()


if __name__ == "__main__":
    sys.exit(main())
