"""Per-request serving state: encode, stream, measure.

A :class:`Session` is one HTTP request's life in the serving plane — its
prompt (text through the engine's tokenizer, or a ``prompt_ids`` escape
hatch mirroring the CLI's ``--prompt-ids``), its token budget and arrival
deadline, the queue the scheduler fans its tokens into, and its own
latency record (TTFT = submit to first token, TPOT = inter-token gap).

Latencies feed the registry histograms below, so serving traffic shows up
everywhere the obs layer already looks: ``/metrics`` Prometheus text,
``--metrics-out`` snapshots, and — via a per-request flight record tagged
``kind="serve.request"`` — ``--flight-log``/``--trace`` artifacts and the
cluster views built on them.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import uuid

from cake_tpu.obs import flight as obs_flight
from cake_tpu.obs import metrics as obs_metrics

# Process-global serving instruments (get-or-create: the scheduler and the
# API handler share these series without import-order coupling).
TTFT_MS = obs_metrics.histogram("serve.ttft_ms")
TPOT_MS = obs_metrics.histogram("serve.tpot_ms")
QUEUE_DEPTH = obs_metrics.gauge("serve.queue_depth")
REJECTED = obs_metrics.counter("serve.rejected")
CANCELLED = obs_metrics.counter("serve.cancelled")
TIMEOUTS = obs_metrics.counter("serve.timeouts")
COMPLETED = obs_metrics.counter("serve.completed")


def sse_event(data) -> bytes:
    """One Server-Sent-Events frame: ``data: <json>\\n\\n`` (strings pass
    through raw — the ``[DONE]`` sentinel is not JSON)."""
    payload = data if isinstance(data, str) else json.dumps(data)
    return f"data: {payload}\n\n".encode()


class Session:
    """One request's serving state. Built by the API layer, admitted and
    advanced by the scheduler's engine thread (the only writer of token
    events), drained by the API handler thread via :attr:`events`."""

    def __init__(self, prompt_ids: list[int], max_tokens: int,
                 stream: bool = True, timeout_s: float | None = None,
                 request_id: str | None = None):
        self.id = request_id or uuid.uuid4().hex[:12]
        self.prompt_ids = list(prompt_ids)
        self.max_tokens = int(max_tokens)
        self.stream = bool(stream)
        self.timeout_s = timeout_s
        # scheduler-owned identity/state
        self.stream_id: int | None = None  # engine stream id once admitted
        self.finish_reason: str | None = None
        self.generated: list[int] = []
        # handler -> scheduler: the client went away (write failed); the
        # engine thread retires the stream at its next loop pass
        self.cancelled = threading.Event()
        # scheduler -> handler: ("token", id, text) | ("done", reason,
        # usage, tail_text) | ("error", http_status, message)
        self.events: queue.Queue = queue.Queue()
        now = time.perf_counter()
        self.t_submit = now
        self.deadline = now + timeout_s if timeout_s else None
        self._t_last: float | None = None
        self.ttft_ms: float | None = None
        self._tpot_sum_ms = 0.0

    # -- engine-thread side ---------------------------------------------------
    def on_token(self, tok_id: int, text: str | None) -> None:
        """Record one emitted token (engine thread): latency samples land
        in the registry, the event lands in the handler's queue."""
        now = time.perf_counter()
        if self._t_last is None:
            self.ttft_ms = (now - self.t_submit) * 1e3
            TTFT_MS.observe(self.ttft_ms)
        else:
            gap_ms = (now - self._t_last) * 1e3
            self._tpot_sum_ms += gap_ms
            TPOT_MS.observe(gap_ms)
        self._t_last = now
        self.generated.append(tok_id)
        self.events.put(("token", tok_id, text))

    def finish(self, reason: str, tail_text: str | None = None) -> None:
        """Close the session (engine thread): one terminal event carrying
        the usage stats, plus the flight record that makes the request
        visible to --flight-log/--trace consumers."""
        self.finish_reason = reason
        if reason in ("stop", "length"):
            # cancelled/timed-out requests land in their own counters;
            # completed means the request actually got its tokens
            COMPLETED.inc()
        rec = obs_flight.recorder()
        if rec.enabled:
            rec.record(kind="serve.request", request=self.id,
                       prompt_tokens=len(self.prompt_ids),
                       completion_tokens=len(self.generated),
                       ttft_ms=round(self.ttft_ms, 3)
                       if self.ttft_ms is not None else None,
                       tpot_ms=round(self.tpot_ms, 3)
                       if self.tpot_ms is not None else None,
                       reason=reason)
        self.events.put(("done", reason, self.usage(), tail_text))

    def fail(self, status: int, message: str) -> None:
        """Reject/abort the session with an HTTP-statused error event."""
        self.finish_reason = "error"
        self.events.put(("error", status, message))

    # -- stats ----------------------------------------------------------------
    @property
    def tpot_ms(self) -> float | None:
        n = len(self.generated) - 1
        return self._tpot_sum_ms / n if n > 0 else None

    def usage(self) -> dict:
        u = {
            "prompt_tokens": len(self.prompt_ids),
            "completion_tokens": len(self.generated),
            "total_tokens": len(self.prompt_ids) + len(self.generated),
        }
        if self.ttft_ms is not None:
            u["ttft_ms"] = round(self.ttft_ms, 3)
        if self.tpot_ms is not None:
            u["tpot_ms"] = round(self.tpot_ms, 3)
        return u
