"""Per-request serving state: encode, stream, measure, constrain.

A :class:`Session` is one HTTP request's life in the serving plane — its
prompt (text through the engine's tokenizer, or a ``prompt_ids`` escape
hatch mirroring the CLI's ``--prompt-ids``), its token budget and arrival
deadline, the queue the scheduler fans its tokens into, and its own
latency record (TTFT = submit to first token, TPOT = inter-token gap).

Structured-generation state lives here too (ISSUE 8):

- ``guide`` — the constrain.Guide the scheduler hands to the engine at
  admission (grammar-constrained decoding);
- ``stop`` — server-side stop strings, matched on the *emitted text
  stream* with holdback: token events whose text could still be the
  prefix of a stop string are withheld from the event queue until the
  match resolves, so a stop string (or any prefix of one that ends up
  matching) never reaches an SSE client, even split across chunk
  boundaries. A match truncates exactly at the match start (text-level;
  a token straddling the boundary contributes its pre-match text via the
  terminal event's tail) and finishes the request with reason "stop"
  (``serve.stop_matches``);
- ``logprobs`` — top-N per-token logprobs accumulated for the SSE events
  and the final usage block.

Latencies feed the registry histograms below, so serving traffic shows up
everywhere the obs layer already looks: ``/metrics`` Prometheus text,
``--metrics-out`` snapshots, and — via a per-request flight record tagged
``kind="serve.request"`` — ``--flight-log``/``--trace`` artifacts and the
cluster views built on them.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import uuid

from cake_tpu.obs import flight as obs_flight
from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.obs import reqtrace as obs_reqtrace

# Priority classes (ISSUE 20), highest first: the scheduler admits (and
# preempts) by CLASSES.index — "interactive" jumps "batch" in the
# admission queue, and a saturated engine spills a batch victim's KV to
# host RAM for an interactive arrival. The serve API validates the
# request's "class" against this tuple (400 on anything else); "tenant"
# defaults to the class and keys the fairness accountant.
CLASSES = ("interactive", "batch")

# Process-global serving instruments (get-or-create: the scheduler and the
# API handler share these series without import-order coupling).
TTFT_MS = obs_metrics.histogram("serve.ttft_ms")
TPOT_MS = obs_metrics.histogram("serve.tpot_ms")
QUEUE_DEPTH = obs_metrics.gauge("serve.queue_depth")
REJECTED = obs_metrics.counter("serve.rejected")
CANCELLED = obs_metrics.counter("serve.cancelled")
TIMEOUTS = obs_metrics.counter("serve.timeouts")
COMPLETED = obs_metrics.counter("serve.completed")
STOP_MATCHES = obs_metrics.counter("serve.stop_matches")

# finish reasons that mean "the request got its output" (vs rejected /
# cancelled / timed out): EOS, stop string, token/window budget, grammar
# dead end
_COMPLETED_REASONS = ("eos", "stop", "length", "constraint")


def sse_event(data) -> bytes:
    """One Server-Sent-Events frame: ``data: <json>\\n\\n`` (strings pass
    through raw — the ``[DONE]`` sentinel is not JSON)."""
    payload = data if isinstance(data, str) else json.dumps(data)
    return f"data: {payload}\n\n".encode()


class Session:
    """One request's serving state. Built by the API layer, admitted and
    advanced by the scheduler's engine thread (the only writer of token
    events), drained by the API handler thread via :attr:`events`."""

    # cakelint CK-THREAD: thread-safe by construction — the engine
    # thread produces (on_token/finish/fail), a handler thread consumes
    # (events.get); all shared state rides the Queue/Event internals
    _THREAD_DOMAIN = "any"

    def __init__(self, prompt_ids: list[int], max_tokens: int,
                 stream: bool = True, timeout_s: float | None = None,
                 request_id: str | None = None,
                 stop: list[str] | None = None, logprobs: int = 0,
                 guide=None, cls: str = "interactive",
                 tenant: str | None = None):
        self.id = request_id or uuid.uuid4().hex[:12]
        self.prompt_ids = list(prompt_ids)
        self.max_tokens = int(max_tokens)
        self.stream = bool(stream)
        self.timeout_s = timeout_s
        # SLO-aware scheduling (ISSUE 20): priority class + fairness
        # tenant. The scheduler admits by class rank and accounts token
        # rates by tenant; per-class latency variants land alongside the
        # aggregate histograms so a batch flood cannot hide interactive
        # tail latency in the blended series.
        self.cls = cls if cls in CLASSES else "interactive"
        self.tenant = tenant or self.cls
        # structured generation
        self.stop = list(stop or [])
        self.logprobs = max(0, int(logprobs))
        self.guide = guide
        self.stop_hit = False
        self.stop_tail: str | None = None  # pre-match remainder text
        self._held: list[tuple[int, str, list | None]] = []
        self._held_text = ""
        self.logprob_rows: list[list] | None = [] if self.logprobs else None
        # disagg plane (cake_tpu/disagg): a handoff session prefills and
        # ships its KV instead of streaming tokens (``handoff`` = the
        # target parsed from the request's ``_disagg`` extension); a
        # resume session continues an imported stream (``resume_xfer`` =
        # the transfer id from ``_resume``)
        self.handoff: dict | None = None
        self.resume_xfer: str | None = None
        # fleet drain migration (ISSUE 19): the original parsed request
        # body, kept so a drain can re-home this session to a sibling
        # replica (the resume request re-sends the same parameters)
        self.raw_body: dict | None = None
        # scheduler-owned identity/state
        self.stream_id: int | None = None  # engine stream id once admitted
        self.finish_reason: str | None = None
        self.generated: list[int] = []
        # handler -> scheduler: the client went away (write failed); the
        # engine thread retires the stream at its next loop pass
        self.cancelled = threading.Event()
        # scheduler -> handler: ("token", id, text, logprobs) |
        # ("done", reason, usage, tail_text) | ("error", status, message)
        self.events: queue.Queue = queue.Queue()
        now = time.perf_counter()
        self.t_submit = now
        self.deadline = now + timeout_s if timeout_s else None
        self._t_last: float | None = None
        self.ttft_ms: float | None = None
        self._tpot_sum_ms = 0.0
        # request-scoped trace context + SLO tracker (set by the API
        # layer; None for directly-constructed sessions — every hook
        # below is guarded, so bare Sessions keep working)
        self.reqtrace: obs_reqtrace.ReqTrace | None = None
        self.slo: obs_reqtrace.SloTracker | None = None
        self.t_submit_unix = time.time()
        self.t_admit_unix: float | None = None
        self._t_first_unix: float | None = None

    # -- engine-thread side ---------------------------------------------------
    def on_token(self, tok_id: int, text: str | None,
                 logprobs: list | None = None) -> None:
        """Record one emitted token (engine thread): latency samples land
        in the registry, the event lands in the handler's queue — unless
        stop strings are configured, in which case events ride the
        holdback buffer until they provably cannot be part of a match."""
        if self.stop_hit:
            return  # tokens past a stop match are discarded
        now = time.perf_counter()
        if self._t_last is None:
            self.ttft_ms = (now - self.t_submit) * 1e3
            TTFT_MS.observe(self.ttft_ms)
            obs_metrics.histogram(
                f"serve.ttft_ms.{self.cls}").observe(self.ttft_ms)
            self._t_first_unix = time.time()
            ctx = self.reqtrace
            if ctx is not None:
                if self.t_admit_unix is not None:
                    # admission -> first token: the prefill (+ queued
                    # decode) leg, as one request-attributed span
                    ctx.add_span("engine.prefill", self.t_admit_unix,
                                 (self._t_first_unix
                                  - self.t_admit_unix) * 1e3,
                                 request=self.id)
                ctx.event("decode.first_token", request=self.id,
                          ttft_ms=round(self.ttft_ms, 3))
        else:
            gap_ms = (now - self._t_last) * 1e3
            self._tpot_sum_ms += gap_ms
            TPOT_MS.observe(gap_ms)
            obs_metrics.histogram(
                f"serve.tpot_ms.{self.cls}").observe(gap_ms)
        self._t_last = now
        self.generated.append(tok_id)
        top = logprobs[: self.logprobs] if (self.logprobs and logprobs) \
            else None
        if self.logprob_rows is not None:
            self.logprob_rows.append(top or [])
        if not self.stop:
            self.events.put(("token", tok_id, text, top))
            return
        self._held.append((tok_id, text or "", top))
        self._held_text += text or ""
        match = self._earliest_stop(self._held_text)
        if match is not None:
            self._commit_stop(match)
            return
        # flush everything that can no longer participate in a match
        self._flush_held(len(self._held_text) - self._hold_len())

    def _earliest_stop(self, text: str) -> int | None:
        best = None
        for s in self.stop:
            i = text.find(s)
            if i >= 0 and (best is None or i < best):
                best = i
        return best

    def _hold_len(self) -> int:
        """Longest suffix of the held text that is a prefix of some stop
        string — the exact amount that must stay withheld."""
        t = self._held_text
        best = 0
        for s in self.stop:
            for k in range(min(len(s) - 1, len(t)), best, -1):
                if t.endswith(s[:k]):
                    best = k
                    break
        return best

    def _flush_held(self, upto_chars: int, final: bool = False) -> int:
        """Release held events whose text lies entirely before char
        position ``upto_chars``; returns the number of chars released.
        Zero-width events (detok withheld the text) sitting exactly at
        the boundary stay held unless ``final`` — their text will arrive
        attributed to a LATER token, which may yet complete a stop match,
        and a released token id leaks that text."""
        flushed = 0
        pos = 0
        for tid, txt, top in self._held:
            end = pos + len(txt)
            if end > upto_chars or (not final and not txt
                                    and pos >= upto_chars):
                break
            self.events.put(("token", tid, txt or None, top))
            flushed += 1
            pos = end
        self._held = self._held[flushed:]
        self._held_text = self._held_text[pos:]
        return pos

    def _commit_stop(self, match_at: int) -> None:
        """A stop string matched at held-text offset ``match_at``: flush
        the fully-before tokens, keep the straddling token's pre-match
        text as the terminal tail, drop everything else (ids included —
        they are the stop string)."""
        self.stop_hit = True
        STOP_MATCHES.inc()
        released = self._flush_held(match_at)
        self.stop_tail = self._held_text[:match_at - released] or None
        dropped = len(self._held)
        if dropped:
            del self.generated[-dropped:]
            if self.logprob_rows is not None:
                del self.logprob_rows[-dropped:]
        self._held = []
        self._held_text = ""

    def finish(self, reason: str, tail_text: str | None = None) -> None:
        """Close the session (engine thread): one terminal event carrying
        the usage stats, plus the flight record that makes the request
        visible to --flight-log/--trace consumers. With stop strings
        configured, the detok tail is scanned too — a stop string whose
        final characters only surface at the flush must still match, and
        must still not leak."""
        if self.stop_hit:
            reason, tail_text = "stop", self.stop_tail
        elif self.stop:
            held_len = len(self._held_text)
            combined = self._held_text + (tail_text or "")
            match = self._earliest_stop(combined)
            if match is None:
                self._flush_held(held_len, final=True)
            elif match >= held_len:
                # the match lies in the detok tail: every held token is
                # legit output, the tail truncates at the match start
                self.stop_hit = True
                STOP_MATCHES.inc()
                self._flush_held(held_len, final=True)
                reason = "stop"
                tail_text = (tail_text or "")[: match - held_len] or None
            else:
                self._commit_stop(match)
                reason = "stop"
                tail_text = self.stop_tail
        self.finish_reason = reason
        if reason in _COMPLETED_REASONS:
            # cancelled/timed-out requests land in their own counters;
            # completed means the request actually got its tokens
            COMPLETED.inc()
        verdict = None
        if self.slo is not None and reason in _COMPLETED_REASONS:
            # SLO is judged on requests that got their output; rejects
            # and cancels have their own counters and no latency story
            verdict = self.slo.observe(self.ttft_ms, self.tpot_ms)
        ctx = self.reqtrace
        if ctx is not None:
            ctx.request_id = self.id
            if verdict is not None:
                ctx.slo = verdict
            if self._t_first_unix is not None and self.generated:
                ctx.add_span("session.emit", self._t_first_unix,
                             (time.time() - self._t_first_unix) * 1e3,
                             request=self.id, reason=reason,
                             tokens=len(self.generated))
            obs_reqtrace.request_log().put(ctx)
        rec = obs_flight.recorder()
        if rec.enabled:
            rec.record(kind="serve.request", request=self.id,
                       prompt_tokens=len(self.prompt_ids),
                       completion_tokens=len(self.generated),
                       ttft_ms=round(self.ttft_ms, 3)
                       if self.ttft_ms is not None else None,
                       tpot_ms=round(self.tpot_ms, 3)
                       if self.tpot_ms is not None else None,
                       reason=reason,
                       trace=ctx.trace_id if ctx is not None else None,
                       slo_good=verdict["good"] if verdict else None)
            if ctx is not None:
                # the per-request JSON timeline, one flight line per
                # request (totals() skips the non-numeric spans field)
                rec.record(kind="reqtrace.timeline", request=self.id,
                           trace=ctx.trace_id, spans=ctx.spans())
        self.events.put(("done", reason, self.usage(), tail_text))

    def fail(self, status: int, message: str) -> None:
        """Reject/abort the session with an HTTP-statused error event."""
        self.finish_reason = "error"
        ctx = self.reqtrace
        if ctx is not None:
            ctx.request_id = self.id
            ctx.event("session.error", request=self.id, status=status)
            obs_reqtrace.request_log().put(ctx)
        self.events.put(("error", status, message))

    def handoff_ready(self, payload: bytes) -> None:
        """The engine exported this session's stream (engine thread):
        hand the snapshot payload to the handler thread, which ships it
        over the transfer channel and answers the gateway."""
        self.finish_reason = "handoff"
        self.events.put(("handoff", payload))

    def migrate_ready(self, payload: bytes | None,
                      target: dict) -> None:
        """A drain is re-homing this session (engine thread): the
        handler thread ships the snapshot (``payload``; None for a
        still-queued session — the sibling just re-runs the request)
        and splices the sibling's stream into the client's connection
        (ISSUE 19 rolling restarts)."""
        self.finish_reason = "migrate"
        self.events.put(("migrate", payload, target))

    # -- stats ----------------------------------------------------------------
    @property
    def tpot_ms(self) -> float | None:
        n = len(self.generated) - 1
        return self._tpot_sum_ms / n if n > 0 else None

    def usage(self) -> dict:
        u = {
            "prompt_tokens": len(self.prompt_ids),
            "completion_tokens": len(self.generated),
            "total_tokens": len(self.prompt_ids) + len(self.generated),
        }
        if self.ttft_ms is not None:
            u["ttft_ms"] = round(self.ttft_ms, 3)
        if self.tpot_ms is not None:
            u["tpot_ms"] = round(self.tpot_ms, 3)
        if self.logprob_rows is not None:
            u["logprobs"] = [
                [{"id": i, "logprob": round(v, 6)} for i, v in row]
                for row in self.logprob_rows
            ]
        return u
