"""Single-stream engine adapter: the BatchGenerator surface over one slot.

The scheduler (``serve/scheduler.py``) speaks only the ``BatchGenerator``
serving API — ``streams`` / ``enqueue`` / ``step`` / ``finish`` /
``pending_admissions`` / ``stats``. That keeps it engine-agnostic, and this
adapter is what buys "serve over every execution path the one-shot master
supports": a single-stream generator (``LlamaGenerator``,
``MeshGenerator``, or the cross-host ``DistributedGenerator`` — anything
built on ``runtime.generator.GeneratorBase``) is presented as a one-slot
batch engine, so ``--mode serve`` works on a host-addressed ``--topology``
deployment too. Requests serialize through the single slot (admission
waits for the running stream to retire); the batched mesh paths go through
``BatchGenerator`` directly and never touch this file.
"""

from __future__ import annotations

import dataclasses
import time

from cake_tpu.obs import prof as obs_prof
from cake_tpu.runtime.generator import Token, encode_prompt
from cake_tpu.utils.token_stream import TokenOutputStream


@dataclasses.dataclass
class _Slot:
    """Mirror of ``batch_generator._Stream``'s serving-visible fields."""

    stream_id: int
    prompt: list[int]
    generated: list[int] = dataclasses.field(default_factory=list)
    done: bool = False
    active: bool = True
    detok: TokenOutputStream | None = None
    end_reason: str | None = None  # "eos" | "length" | "constraint"


class SingleStreamEngine:
    """One-slot ``BatchGenerator`` facade over a ``GeneratorBase``."""

    # the one-slot path has no top-k logprob outputs (the wrapped
    # generators keep sampling fused on device); requests asking for
    # logprobs are refused at the API layer
    logprobs_k = 0

    # cakelint CK-THREAD: same engine-domain contract as the facade's
    # subject. `_encode` is the stateless tokenizer crossing point;
    # `close` runs only after Scheduler.stop has joined the engine
    # thread (teardown happens-after), so it is a declared crossing too.
    _THREAD_DOMAIN = "engine"
    _THREAD_SAFE = ("_encode", "close")

    def __init__(self, gen):
        self.gen = gen
        self.config = gen.config
        self.tokenizer = gen.tokenizer
        self.settings = gen.settings
        self.max_seq = gen.max_seq
        self._eos_ids = set(self.config.eos_ids())
        # the slot starts retired: nothing is admitted until the first
        # arrival, exactly like a primed batch engine's done slots
        self.streams: list[_Slot] = [_Slot(stream_id=-1, prompt=[],
                                           done=True)]
        self._arrivals: list[tuple[list[int], int, object]] = []
        self._index = 0
        self._n_emitted = 0
        self._t_start = time.perf_counter()
        # engine profiling plane (obs/prof) — same phase names as the
        # batched engine so /debug/prof reads identically on either path
        self._prof = obs_prof.profiler()
        self._sentinel = obs_prof.sentinel()
        self._sentinel.install()

    # -- BatchGenerator API subset -------------------------------------------
    @property
    def eos_ids(self) -> frozenset:
        """Public EOS-id surface of the engine facade (scheduler
        finish-reason mapping — no private-attr reaches)."""
        return frozenset(self._eos_ids)

    def _encode(self, p) -> list[int]:
        """The shared prompt-intake rules (``generator.encode_prompt``),
        without mutating generator state."""
        return encode_prompt(p, self.tokenizer, self.config, self.max_seq)

    def enqueue(self, prompt, stream_id: int, guide=None) -> None:
        if guide is not None and not getattr(self.gen, "supports_guide",
                                             False):
            raise ValueError(
                "this serve deployment's generator does not support "
                "constrained decoding (response_format)")
        self._arrivals.append((self._encode(prompt), stream_id, guide))

    def pending_admissions(self) -> int:
        return len(self._arrivals)

    def finish(self, stream_id: int) -> bool:
        """Retire by id at any lifecycle point — live in the slot, or
        still waiting in the arrival queue (same contract as
        ``BatchGenerator.finish``)."""
        s = self.streams[0]
        if s.active and not s.done and s.stream_id == stream_id:
            s.done = True
            return True
        n0 = len(self._arrivals)
        self._arrivals = [a for a in self._arrivals if a[1] != stream_id]
        return len(self._arrivals) != n0

    def step(self) -> list[Token | None]:
        """Advance the slot one token; admit the next queued arrival when
        the slot is free (its prefill runs inside the wrapped generator's
        ``set_prompt``/first ``next_token``, which also resets the
        generator's KV state — retirement IS the KV free here too)."""
        prof = self._prof
        prof.step_begin("single")
        try:
            s = self.streams[0]
            if s.done and self._arrivals:
                with prof.phase("admit"):
                    ids, sid, guide = self._arrivals.pop(0)
                    self.gen.set_prompt(ids)
                    self.gen.set_guide(guide)
                    s = _Slot(stream_id=sid, prompt=ids,
                              detok=self.gen.stream)
                    self.streams[0] = s
                    self._index = 0
            if s.done:
                return [None]
            # next_token dispatches AND syncs (the wrapped generators fetch
            # the token host-side) — one phase prices the whole round trip
            with prof.phase("dispatch"), self._sentinel.decode_phase():
                tok = self.gen.next_token(self._index)
            with prof.phase("emit"):
                self._index += 1
                s.generated.append(tok.id)
                window_full = (len(s.prompt) + len(s.generated)
                               >= self.max_seq)
                s.done = tok.is_end_of_stream or window_full
                if s.done:
                    if getattr(self.gen, "guide_dead", False):
                        s.end_reason = "constraint"
                    elif tok.id in self._eos_ids:
                        s.end_reason = "eos"
                    else:
                        s.end_reason = "length"
                self._n_emitted += 1
                return [Token(id=tok.id, text=tok.text,
                              is_end_of_stream=s.done)]
        finally:
            prof.step_end()

    def drain(self) -> None:
        pass  # single-step path: nothing buffered device-side

    def stats(self) -> dict:
        wall = time.perf_counter() - self._t_start
        s = self.streams[0]
        return {
            "streams_live": int(s.active and not s.done),
            "streams_done": int(s.active and s.done and s.prompt != []),
            "pending_admissions": len(self._arrivals),
            "tokens_emitted": self._n_emitted,
            "wall_s": round(wall, 3),
            "aggregate_tok_s": (
                round(self._n_emitted / wall, 2) if wall > 0 else None
            ),
        }

    def close(self) -> None:
        if hasattr(self.gen, "close"):
            self.gen.close()
