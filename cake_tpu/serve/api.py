"""Network-facing serving API: completions over HTTP, stdlib-only.

A ``ThreadingHTTPServer`` (the ``obs/statusd.py`` shape) in front of the
scheduler:

- ``POST /v1/completions`` — JSON body: ``prompt`` (text, needs the
  engine's tokenizer) or ``prompt_ids`` (the CLI ``--prompt-ids`` escape
  hatch), ``max_tokens``, ``stream``. Sampler knobs (``temperature`` /
  ``top_k`` / ``top_p`` / ``seed``, and ``logit_bias``) are accepted only
  when they match the settings the server was started with — the engine
  compiles ONE sampler into its programs, and silently ignoring a
  mismatch would be worse than refusing it. ``stream: true`` answers
  Server-Sent Events, one event per token (text incrementally
  detokenized by the engine's ``TokenOutputStream``), final event
  carrying the usage stats; ``stream: false`` answers one JSON object.

  Structured generation (cake_tpu/constrain, ISSUE 8):
  ``response_format: {"type": "json_schema", "schema": {...}}`` or
  ``{"type": "regex", "pattern": "..."}`` constrains decoding to the
  grammar (device-side masking, no retrace — finish_reason
  ``"constraint"`` marks a grammar dead end); ``stop: [str]`` ends the
  stream at the first stop-string match with SSE holdback (a potential
  match is withheld until resolved, so stop text never reaches the
  client; finish_reason ``"stop"``, distinct from ``"eos"``);
  ``logprobs: N`` adds top-N logprobs to every token event and the
  final usage block (server capacity set by ``--serve-logprobs``).
- ``POST /v1/fleet/drain`` — gateway-initiated rolling restart (ISSUE
  19): begin a drain that RE-HOMES live sessions to the sibling named
  in ``migrate_to`` instead of making clients wait it out. Admitted
  streams export their KV via the disagg snapshot path and the handler
  splices the sibling's resumed stream onto the client connection
  (skipping the tokens already delivered here), so the client sees one
  uninterrupted, bit-identical stream; queued sessions re-run whole on
  the sibling. Without ``migrate_to`` this is a classic drain.
- ``GET /v1/models`` / ``GET /healthz`` — discovery and liveness.
- ``GET /`` + ``GET /metrics`` — the exact statusd surface
  (``obs.statusd.status_response``), so one port serves traffic AND
  observability and stays byte-identical with a standalone
  ``--status-port`` page.

Backpressure: a full admission queue answers ``429`` with a
``Retry-After`` derived from observed tokens/sec; a draining server
answers ``503``. Handler threads never touch the engine — they hand
sessions to the scheduler and pump its event queues, so a slow client
can only ever stall its own stream.
"""

from __future__ import annotations

import http.server
import json
import logging
import threading
import time
import uuid
from collections import deque

from cake_tpu.obs import reqtrace as obs_reqtrace
from cake_tpu.obs import statusd as _statusd
from cake_tpu.serve.scheduler import Draining, QueueFull
from cake_tpu.serve.session import CLASSES, Session, sse_event

log = logging.getLogger("cake_tpu.serve.api")

# Thread domain (cakelint CK-THREAD): everything in this module runs on
# HTTP handler threads (ThreadingHTTPServer — the nested Handler class
# inherits this module domain). Calls into engine-domain state must go
# through the scheduler's declared crossing points (_THREAD_SAFE);
# handler code never touches the engine directly.
_THREAD_DOMAIN = "handler"

_SAMPLER_KNOBS = ("temperature", "top_k", "top_p", "seed")


def _parse_stop(body: dict, engine) -> list[str]:
    stop = body.get("stop")
    if stop is None:
        return []
    if isinstance(stop, str):
        stop = [stop]
    if (not isinstance(stop, list) or not stop or len(stop) > 8
            or not all(isinstance(s, str) and s for s in stop)):
        raise ValueError(
            "'stop' must be a non-empty string or a list of 1..8 "
            "non-empty strings")
    if engine.tokenizer is None:
        raise ValueError(
            "'stop' needs a server-side tokenizer (stop strings match "
            "the emitted text stream)")
    return stop


def _parse_logit_bias(body: dict, engine) -> None:
    """Validate ``logit_bias`` and require it to match the server's
    compiled sampler (the engine traces ONE bias scatter): out-of-range
    ids and malformed entries are 400s in their own right."""
    if "logit_bias" not in body:
        return
    lb = body["logit_bias"]
    if not isinstance(lb, dict):
        raise ValueError("'logit_bias' must be an object of "
                         "{token_id: bias}")
    norm = []
    vocab = engine.config.vocab_size
    for k, v in lb.items():
        try:
            tok = int(k)
        except (TypeError, ValueError):
            raise ValueError(f"logit_bias key {k!r} is not a token id")
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            raise ValueError(f"logit_bias value for {tok} must be a number")
        if not 0 <= tok < vocab:
            raise ValueError(
                f"logit_bias token id {tok} out of range [0, {vocab})")
        norm.append((tok, float(v)))
    if tuple(sorted(norm)) != tuple(sorted(
            (int(i), float(b)) for i, b in engine.settings.logit_bias)):
        raise ValueError(
            "per-request 'logit_bias' is not supported: the engine "
            "compiles one sampler (server runs logit_bias="
            f"{dict(engine.settings.logit_bias)!r}); omit it or match "
            "the server's value")


def _parse_guide(body: dict, engine):
    rf = body.get("response_format")
    if rf is None:
        return None
    from cake_tpu.constrain import RegexError, guide_for

    try:
        return guide_for(rf, engine.tokenizer, engine.config)
    except RegexError as e:
        raise ValueError(f"bad response_format: {e}")


def _parse_disagg(body: dict, scheduler) -> tuple[dict | None, str | None]:
    """Validate the disagg extension fields a tier-aware gateway injects:
    ``_disagg`` ({"target": "host:port"}) asks this replica to prefill
    and ship the KV pages to the target's transfer channel; ``_resume``
    ({"xfer_id": ...}) asks it to continue an imported stream. Returns
    ``(handoff, resume_xfer)``; raises ValueError on a malformed or
    unsupported combination."""
    dis, res = body.get("_disagg"), body.get("_resume")
    if dis is None and res is None:
        return None, None
    if dis is not None and res is not None:
        raise ValueError("'_disagg' and '_resume' are mutually exclusive")
    if not (hasattr(scheduler.engine, "export_stream")
            and getattr(scheduler.engine, "paged", False)):
        raise ValueError(
            "this replica cannot move KV pages (disagg needs the batched "
            "mesh engine with --kv-layout paged)")
    if dis is not None:
        if not isinstance(dis, dict) or not isinstance(
                dis.get("target"), str):
            raise ValueError("'_disagg' must be {\"target\": \"host:port\"}")
        host, _, port = dis["target"].rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(
                f"'_disagg' target {dis['target']!r} is not host:port")
        return {"host": host, "port": int(port)}, None
    if not isinstance(res, dict) or not isinstance(res.get("xfer_id"), str):
        raise ValueError("'_resume' must be {\"xfer_id\": \"...\"}")
    return None, res["xfer_id"]


def _parse_request(body: dict, scheduler) -> Session:
    """Validate one completions body into a Session (raises ValueError
    with a client-facing message)."""
    if not isinstance(body, dict):
        raise ValueError("body must be a JSON object")
    prompt = body.get("prompt")
    prompt_ids = body.get("prompt_ids")
    if (prompt is None) == (prompt_ids is None):
        raise ValueError("exactly one of 'prompt' or 'prompt_ids' required")
    if prompt is not None:
        if not isinstance(prompt, str):
            raise ValueError("'prompt' must be a string")
        ids = scheduler.encode_prompt(prompt)
    else:
        if (not isinstance(prompt_ids, list)
                or not all(isinstance(t, int) for t in prompt_ids)):
            raise ValueError("'prompt_ids' must be a list of ints")
        ids = scheduler.encode_prompt(prompt_ids)
    max_tokens = body.get("max_tokens", 16)
    if not isinstance(max_tokens, int) or max_tokens < 1:
        raise ValueError("'max_tokens' must be a positive int")
    stream = body.get("stream", False)
    if not isinstance(stream, bool):
        raise ValueError("'stream' must be a boolean")
    engine = scheduler.engine
    settings = engine.settings
    for knob in _SAMPLER_KNOBS:
        if knob in body and body[knob] != getattr(settings, knob):
            raise ValueError(
                f"per-request '{knob}' is not supported: the engine "
                f"compiles one sampler (server runs {knob}="
                f"{getattr(settings, knob)!r}); omit it or match the "
                "server's value"
            )
    _parse_logit_bias(body, engine)
    logprobs = body.get("logprobs", 0)
    if not isinstance(logprobs, int) or logprobs < 0:
        raise ValueError("'logprobs' must be a non-negative int")
    cap = getattr(engine, "logprobs_k", 0)
    if logprobs > cap:
        raise ValueError(
            f"'logprobs': {logprobs} exceeds this server's capacity "
            f"({cap}; start the server with --serve-logprobs N to raise "
            "it)" if cap else
            "'logprobs' is not enabled on this server (start it with "
            "--serve-logprobs N)")
    stop = _parse_stop(body, engine)
    guide = _parse_guide(body, engine)
    timeout = body.get("timeout_s", scheduler.request_timeout_s)
    if timeout is not None and (
        not isinstance(timeout, (int, float)) or timeout <= 0
    ):
        raise ValueError("'timeout_s' must be a positive number")
    # SLO scheduling fields (ISSUE 20): validated here so serve and the
    # gateway agree — the gateway forwards both untouched, and a typo'd
    # class is a 400, not a silent demotion to the default
    cls = body.get("class", "interactive")
    if cls not in CLASSES:
        raise ValueError(
            f"'class' must be one of {list(CLASSES)}, got {cls!r}")
    tenant = body.get("tenant")
    if tenant is not None and not (
            isinstance(tenant, str) and 0 < len(tenant) <= 64):
        raise ValueError("'tenant' must be a non-empty string "
                         "(at most 64 chars)")
    return Session(ids, max_tokens=max_tokens, stream=stream,
                   timeout_s=timeout, stop=stop, logprobs=logprobs,
                   guide=guide, cls=cls, tenant=tenant)


class ApiServer:
    """The serving front end; ``start_api_server`` is the entry point."""

    _GUARDED_BY = {"_relays": "_relay_lock", "_batches": "_batch_lock"}

    def __init__(self, scheduler, status_fn=None, bind: str = "127.0.0.1",
                 port: int = 0, model_id: str = "cake-tpu", on_drain=None):
        self.scheduler = scheduler
        self.model_id = model_id
        # rolling-restart hook: called (handler thread) after a
        # /v1/fleet/drain ack so the process can schedule its own exit
        self.on_drain = on_drain
        self._relay_lock = threading.Lock()
        self._relays = 0
        # /v1/batch registry (ISSUE 20): results land here as each
        # prompt finishes, so a client that disconnected mid-batch
        # re-fetches by id instead of re-running N prompts
        self._batch_lock = threading.Lock()
        self._batches: dict[str, dict] = {}
        # set once a drain carries a migrate_to target: drain() then
        # waits for handler threads still splicing sibling streams
        self._migrating = threading.Event()
        if status_fn is None:
            def status_fn():
                from cake_tpu.obs import metrics as obs_metrics

                return {"role": "serve", "model": model_id,
                        "scheduler": scheduler.stats(),
                        "metrics": obs_metrics.registry().snapshot()}
        self.status_fn = status_fn
        handler = _make_handler(self)
        self.httpd = http.server.ThreadingHTTPServer((bind, port), handler)
        self.port = self.httpd.server_address[1]
        self.bind = bind
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="cake-serve-http")

    def start(self) -> "ApiServer":
        self._thread.start()
        return self

    def drain(self, timeout_s: float = 30.0) -> None:
        """Graceful shutdown: stop admitting, let in-flight streams finish
        (bounded by ``timeout_s``), then stop the listener. The listener
        teardown runs even if the drain raises — a failed drain must not
        leak the bound port."""
        try:
            self.scheduler.stop(drain=True, timeout_s=timeout_s)
            self._await_relays(timeout_s)
        finally:
            self.close()

    def _relay_enter(self) -> None:
        with self._relay_lock:
            self._relays += 1

    def _relay_exit(self) -> None:
        with self._relay_lock:
            self._relays -= 1

    def _await_relays(self, timeout_s: float) -> None:
        """Drain helper: wait out in-flight migration relays (handler
        threads splicing a sibling's stream onto their client) before
        the process tears down — exiting under them would fail the very
        streams the migration saved. The settle window covers the gap
        between the engine thread queueing a migrate event and the
        handler thread entering its relay. No-op unless a migrate
        drain actually started."""
        if not self._migrating.is_set():
            return
        deadline = time.monotonic() + max(0.0, timeout_s)
        quiet_t = time.monotonic()
        while time.monotonic() < deadline:
            with self._relay_lock:
                busy = self._relays > 0
            now = time.monotonic()
            if busy:
                quiet_t = now
            elif now - quiet_t >= 0.25:
                return
            time.sleep(0.05)

    def close(self) -> None:
        try:
            self.httpd.shutdown()
        finally:
            self.httpd.server_close()


def start_api_server(scheduler, status_fn=None, bind: str = "127.0.0.1",
                     port: int = 0, model_id: str = "cake-tpu",
                     on_drain=None) -> ApiServer:
    """Build + start an :class:`ApiServer`; returns it with ``.port``
    bound (``port=0`` picks an ephemeral one)."""
    return ApiServer(scheduler, status_fn=status_fn, bind=bind, port=port,
                     model_id=model_id, on_drain=on_drain).start()


def _iter_sse(resp):
    """Yield each SSE frame's data payload (str) from a sibling's
    streaming HTTP response."""
    for line in resp:
        line = line.strip()
        if line.startswith(b"data: "):
            yield line[6:].decode()


def _make_handler(server: ApiServer):
    scheduler = server.scheduler

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            log.debug("api: " + fmt, *args)

        # -- small reply helpers ------------------------------------------
        def _json(self, status: int, obj: dict,
                  headers: dict | None = None) -> None:
            body = json.dumps(obj, indent=1).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _error(self, status: int, message: str,
                   headers: dict | None = None) -> None:
            self._json(status, {"error": message}, headers)

        # -- GET: health, discovery, status surface -----------------------
        def do_GET(self):  # noqa: N802 (stdlib casing)
            path = self.path.rstrip("/") or "/"
            if path == "/healthz":
                st = scheduler.stats()
                # a draining server must fail the probe at the STATUS
                # level: balancers route on the code, not the body. The
                # body carries the cheap load fields the gateway's p2c
                # signal reads — one GET, not a /metrics scrape.
                body = {
                    "ok": not st["draining"],
                    "draining": st["draining"],
                    "queued": st["queued"],
                    "running": st["running"],
                    "max_concurrent": st["max_concurrent"],
                    "tok_s_ema": st["observed_tok_s"],
                    # disagg tier map: the gateway's prober learns the
                    # replica's role, its transfer address, and the KV
                    # transfers currently in flight from the SAME GET
                    # that feeds the p2c load signal
                    "role": st.get("role", "mixed"),
                    "kv_transfers_inflight": st.get(
                        "kv_transfers_inflight", 0),
                    # spill pressure (ISSUE 20): victims parked in host
                    # RAM are latent load that WILL resume here — the
                    # gateway's p2c signal folds them into load_score
                    "spilled": st.get("spilled", 0),
                    "preemptions": st.get("preemptions", 0),
                }
                if st.get("transfer_port"):
                    body["transfer_port"] = st["transfer_port"]
                eng_st = st.get("engine")
                kv = (eng_st.get("kvpool")
                      if isinstance(eng_st, dict) else None)
                if kv:
                    # paged-KV pressure rides the same cheap load body:
                    # a pool out of free pages defers admissions even
                    # when slots look open
                    body["kv_pages_free"] = kv["pages_free"]
                if st.get("slo"):
                    # SLO burn state (--slo-ttft-ms/--slo-tpot-ms) rides
                    # the same probe body dashboards already poll
                    body["slo"] = st["slo"]
                self._json(200 if not st["draining"] else 503, body)
            elif path.startswith("/v1/batch/"):
                # resumable batch fetch: results recorded so far (the
                # POST side updates the registry as prompts finish)
                key = path.rsplit("/", 1)[1]
                with server._batch_lock:
                    rec = server._batches.get(key)
                    rec = dict(rec, results=list(rec["results"])) \
                        if rec is not None else None
                if rec is None:
                    self._error(404, f"no batch {key!r}")
                else:
                    self._json(200, rec)
            elif path.startswith("/v1/requests/"):
                # per-request debug timeline: spans + SLO verdict for a
                # recent request, by request id or trace id
                key = path.rsplit("/", 1)[1]
                tl = obs_reqtrace.request_log().get(key) if key else None
                if tl is None:
                    self._error(404, f"no recorded request {key!r} "
                                     "(evicted, or never served here)")
                else:
                    self._json(200, tl)
            elif path == "/v1/models":
                eng = scheduler.engine
                self._json(200, {"object": "list", "data": [{
                    "id": server.model_id,
                    "object": "model",
                    "max_seq": eng.max_seq,
                    "max_concurrent": scheduler.max_concurrent,
                    "tokenizer": eng.tokenizer is not None,
                }]})
            elif path in ("/", "/metrics", "/debug/prof"):
                # byte-identical with a standalone statusd page: both
                # build through obs.statusd.status_response (which also
                # serves the engine profiling report at /debug/prof)
                body, ctype = _statusd.status_response(server.status_fn,
                                                       path)
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._error(404, f"no route for GET {self.path}")

        # -- POST: completions --------------------------------------------
        def do_POST(self):  # noqa: N802 (stdlib casing)
            path = self.path.rstrip("/")
            if path == "/v1/fleet/drain":
                self._fleet_drain()
                return
            if path == "/v1/batch":
                self._batch_request()
                return
            if path != "/v1/completions":
                self._error(404, f"no route for POST {self.path}")
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, UnicodeDecodeError) as e:
                self._error(400, f"bad JSON body: {e}")
                return
            try:
                sess = _parse_request(body, scheduler)
                sess.handoff, sess.resume_xfer = _parse_disagg(body,
                                                               scheduler)
            except ValueError as e:
                self._error(400, str(e))
                return
            # kept so a drain can re-submit this request to a sibling
            # if it re-homes the session mid-flight (ISSUE 19)
            sess.raw_body = body
            # request-scoped trace context: honor the client/gateway's
            # traceparent (or mint one), and judge completed requests
            # against the replica's SLO targets, if any
            sess.reqtrace = obs_reqtrace.ReqTrace.from_header(
                self.headers.get(obs_reqtrace.HEADER))
            sess.slo = scheduler.slo
            if scheduler.role == "prefill" and sess.handoff is None:
                # a prefill-tier replica runs bucketed prefill ONLY; a
                # request without a handoff target would decode here and
                # defeat the tier split — refuse loudly so a misrouted
                # gateway (or curl) learns immediately
                self._error(400, "this replica is prefill-tier: "
                                 "completions must arrive via a "
                                 "disagg-aware gateway (_disagg target)")
                return
            if sess.resume_xfer is not None:
                if not self._replay_resume(sess):
                    return  # 409 (unknown transfer) or completed-by-replay
            try:
                scheduler.submit(sess)
            except QueueFull as e:
                # never block the accept loop: full queue answers 429 with
                # the observed-throughput Retry-After hint
                self._abort_resume_import(sess)
                self._error(429, str(e), headers={
                    "Retry-After": str(max(1, round(e.retry_after_s)))})
                return
            except Draining:
                self._abort_resume_import(sess)
                self._error(503, "server is draining")
                return
            # a handler dying mid-pump (any reason, not just the client
            # socket) must hand the slot back: an uncancelled session
            # would keep generating into a queue nobody drains until its
            # token budget runs out
            try:
                if sess.handoff is not None:
                    self._handoff_response(sess)
                elif sess.stream:
                    self._stream_response(sess)
                else:
                    self._unary_response(sess)
            finally:
                if sess.finish_reason is None:
                    scheduler.cancel(sess)

        def _abort_resume_import(self, sess) -> None:
            """A resume refused before admission will never attach: drop
            its begun import NOW so the pinned pages do not sit out the
            import TTL while the gateway re-prefills elsewhere."""
            if sess.resume_xfer is not None:
                scheduler.abort_import(sess.resume_xfer)

        def _replay_resume(self, sess) -> bool:
            """Prime a resume session with the snapshot's already-
            generated tokens (the decode replica re-emits the WHOLE
            stream, so the client's view is identical to an
            uninterrupted one). Returns False when the response was
            already written: unknown transfer (409 — the gateway
            re-prefills) or the replay alone satisfied the request (the
            import is aborted and the stream never attaches)."""
            meta = scheduler.import_meta(sess.resume_xfer)
            if meta is None:
                self._error(409, f"unknown or expired transfer "
                                 f"{sess.resume_xfer!r}; re-prefill")
                return False
            for tok, text in zip(meta["generated"], meta["texts"]):
                sess.on_token(tok, text)
                # clamp inside the loop: a snapshot may carry more
                # tokens than THIS request's budget allows
                if sess.stop_hit or len(sess.generated) >= sess.max_tokens:
                    break
            if sess.stop_hit or len(sess.generated) >= sess.max_tokens:
                scheduler.abort_import(sess.resume_xfer)
                sess.finish("stop" if sess.stop_hit else "length")
                if sess.stream:
                    self._stream_response(sess)
                else:
                    self._unary_response(sess)
                return False
            return True

        def _handoff_response(self, sess) -> None:
            """Wait for the engine's export, ship it over the transfer
            channel (retry/backoff — on THIS thread, never the engine's),
            and answer the gateway with the transfer id to resume."""
            from cake_tpu.disagg import (
                TransferError,
                peek_xfer_id,
                send_snapshot,
            )

            ev = self._next_event(sess)
            if ev[0] == "error":
                _, status, message = ev
                self._error(status, message)
                return
            if ev[0] == "migrate":
                # drain re-home: the sibling re-runs prefill+handoff
                # from the original body; its answer (the decode-side
                # xfer id) relays as-is
                self._migrate_unary(sess, None, ev[2])
                return
            if ev[0] != "handoff":  # e.g. a deadline fired mid-prefill
                self._error(504, f"prefill did not complete ({ev[0]}); "
                                 "re-prefill")
                return
            payload = ev[1]
            ctx = sess.reqtrace
            scheduler.xfer_out_enter()
            try:
                send_snapshot(sess.handoff["host"], sess.handoff["port"],
                              payload,
                              deadline_s=scheduler.transfer_deadline_s,
                              trace=ctx)
            except TransferError as e:
                # retry budget exhausted or receiver rejected: the pages
                # are gone with this replica's slot — tell the gateway
                # to re-prefill (502: infrastructure, not client, fault)
                self._json(502, {"handoff": False, "error": str(e)})
                return
            finally:
                scheduler.xfer_out_exit()
                if ctx is not None:
                    # the prefill half of the request ends here; make
                    # its spans (queue/admit/export/transfer attempts)
                    # queryable under the request id
                    ctx.request_id = sess.id
                    obs_reqtrace.request_log().put(ctx)
            self._json(200, {
                "handoff": True,
                "xfer_id": peek_xfer_id(payload),
                "prompt_tokens": len(sess.prompt_ids),
                "snapshot_bytes": len(payload),
            })

        def _batch_request(self) -> None:
            """``POST /v1/batch`` (ISSUE 20): N prompts in, one JSON
            result set out — the offline workload's front door. Each
            prompt becomes its own session (class defaults to "batch",
            so the scheduler deprioritizes them behind interactive
            traffic and they are preemption victims); submissions
            self-throttle against QueueFull instead of erroring, and
            every finished prompt lands in the server-side registry
            first, so the batch is resumable by id after a disconnect
            (``GET /v1/batch/<id>`` or an idempotent re-POST)."""
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, UnicodeDecodeError) as e:
                self._error(400, f"bad JSON body: {e}")
                return
            if not isinstance(body, dict):
                self._error(400, "body must be a JSON object")
                return
            prompts = body.get("prompts")
            if (not isinstance(prompts, list) or not prompts
                    or len(prompts) > 256):
                self._error(400, "'prompts' must be a list of 1..256 "
                                 "prompts")
                return
            bid = body.get("id")
            if bid is not None and not (isinstance(bid, str)
                                        and 0 < len(bid) <= 128):
                self._error(400, "'id' must be a non-empty string")
                return
            with server._batch_lock:
                if bid is not None and bid in server._batches:
                    # idempotent re-POST: the batch already ran (or is
                    # running) — answer from the registry
                    rec = server._batches[bid]
                    out = dict(rec, results=list(rec["results"]))
                    self._json(200, out)
                    return
                bid = bid or f"batch-{uuid.uuid4().hex[:12]}"
                rec = {"id": bid, "object": "batch", "n": len(prompts),
                       "done": 0, "status": "running",
                       "results": [None] * len(prompts)}
                server._batches[bid] = rec
            shared = {k: v for k, v in body.items()
                      if k not in ("prompts", "id", "prompt",
                                   "prompt_ids", "stream")}
            shared.setdefault("class", "batch")

            def record(i: int, result: dict) -> None:
                with server._batch_lock:
                    rec["results"][i] = result
                    rec["done"] += 1

            pending: deque = deque()
            for i, p in enumerate(prompts):
                per = dict(shared)
                if isinstance(p, str):
                    per["prompt"] = p
                else:
                    per["prompt_ids"] = p
                try:
                    sess = _parse_request(per, scheduler)
                except ValueError as e:
                    record(i, {"error": str(e), "status": 400})
                    continue
                sess.raw_body = per
                sess.slo = scheduler.slo
                pending.append((i, sess))
            active: deque = deque()
            while pending or active:
                while pending:
                    i, sess = pending[0]
                    try:
                        scheduler.submit(sess)
                    except QueueFull:
                        break  # self-throttle: drain one, then retry
                    except Draining:
                        for j, s in list(pending):
                            record(j, {"error": "server is draining",
                                       "status": 503})
                        pending.clear()
                        break
                    pending.popleft()
                    active.append((i, sess))
                if active:
                    i, sess = active.popleft()
                    record(i, self._collect_unary(sess))
                elif pending:
                    time.sleep(0.05)
            with server._batch_lock:
                rec["status"] = "done"
                out = dict(rec, results=list(rec["results"]))
            try:
                self._json(200, out)
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass  # results are in the registry; re-fetch by id

        def _collect_unary(self, sess) -> dict:
            """Pump one batch session to completion and return its
            result object (never raises; errors become result rows)."""
            texts: list[str] = []
            try:
                while True:
                    ev = self._next_event(sess)
                    if ev[0] == "token":
                        if ev[2]:
                            texts.append(ev[2])
                    elif ev[0] == "done":
                        _, reason, usage, tail = ev
                        if tail:
                            texts.append(tail)
                        out = {"id": sess.id, "finish_reason": reason,
                               "usage": usage,
                               "token_ids": list(sess.generated)}
                        if scheduler.engine.tokenizer is not None:
                            out["text"] = "".join(texts)
                        return out
                    elif ev[0] == "migrate":
                        # batches don't relay: the prompt re-runs via
                        # a re-POST against the sibling
                        return {"error": "replica drained mid-batch; "
                                         "re-submit", "status": 503}
                    else:
                        return {"error": ev[2], "status": ev[1]}
            finally:
                if sess.finish_reason is None:
                    scheduler.cancel(sess)

        def _fleet_drain(self) -> None:
            """Gateway-initiated rolling restart (ISSUE 19): begin a
            drain that re-homes live sessions to the sibling named in
            ``migrate_to`` (absent = classic drain). The ack is written
            before the process-exit hook fires so the caller always
            sees it."""
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, UnicodeDecodeError) as e:
                self._error(400, f"bad JSON body: {e}")
                return
            target = body.get("migrate_to") if isinstance(body, dict) \
                else None
            if target is not None and not (
                    isinstance(target, dict)
                    and isinstance(target.get("addr"), str)):
                self._error(400, "'migrate_to' must be "
                                 "{\"addr\": \"host:port\", ...}")
                return
            if target is not None:
                server._migrating.set()
            n = scheduler.migrate_out(target)
            self._json(200, {"ok": True, "draining": True, "migrating": n})
            if server.on_drain is not None:
                server.on_drain()

        def _migrate_post(self, sess, payload, target):
            """Ship the KV snapshot (if any) to the sibling's transfer
            channel and re-submit the original request there as a
            resume. Falls back to a plain full re-run when the snapshot
            cannot be delivered — decoding is deterministic, so the
            sibling reproduces the same stream either way. Returns
            ``(conn, response)``; the caller owns both."""
            import http.client

            from cake_tpu.disagg import (
                TransferError,
                peek_xfer_id,
                send_snapshot,
            )

            body = dict(sess.raw_body or {})
            # a queued resume's import was aborted with the drain; the
            # sibling re-prefills from the prompt the body still carries
            body.pop("_resume", None)
            if payload is not None:
                body.pop("_disagg", None)
                try:
                    xfer = target.get("transfer")
                    if not isinstance(xfer, str):
                        raise TransferError(
                            "sibling advertises no transfer channel")
                    host, _, port = xfer.rpartition(":")
                    scheduler.xfer_out_enter()
                    try:
                        send_snapshot(
                            host, int(port), payload,
                            deadline_s=scheduler.transfer_deadline_s,
                            trace=sess.reqtrace)
                    finally:
                        scheduler.xfer_out_exit()
                    body["_resume"] = {"xfer_id": peek_xfer_id(payload)}
                except TransferError as e:
                    log.warning("drain snapshot ship failed (%s); the "
                                "sibling re-runs request %s in full",
                                e, sess.id)
            host, _, port = target["addr"].rpartition(":")
            raw = json.dumps(body).encode()
            headers = {"Content-Type": "application/json"}
            if sess.reqtrace is not None:
                headers[obs_reqtrace.HEADER] = sess.reqtrace.header()
            conn = http.client.HTTPConnection(host, int(port),
                                              timeout=30.0)
            conn.request("POST", "/v1/completions", raw, headers)
            return conn, conn.getresponse()

        def _migrate_stream(self, sess, payload, target,
                            index: int) -> None:
            """Splice the sibling's stream onto this connection: the
            sibling re-emits the WHOLE stream (resume replay), so the
            first ``index`` token frames — already delivered here — are
            skipped and the rest flow through, making the client's view
            bit-identical to an uninterrupted run. On failure before
            the first relayed byte the connection just closes: the
            gateway has not committed the response (it withholds the
            head until the first body byte) and retries transparently
            against a healthy sibling."""
            server._relay_enter()
            wrote = False
            conn = None
            try:
                conn, resp = self._migrate_post(sess, payload, target)
                if resp.status != 200:
                    raise OSError(f"sibling answered {resp.status}")
                for data in _iter_sse(resp):
                    if data == "[DONE]":
                        self.wfile.write(sse_event("[DONE]"))
                        self.wfile.flush()
                        return
                    frame = json.loads(data)
                    if frame.get("error") is not None:
                        raise OSError(
                            f"sibling stream failed: {frame['error']}")
                    if frame.get("done"):
                        frame["id"] = sess.id
                    elif frame.get("index", 0) < index:
                        continue  # already delivered by this replica
                    self.wfile.write(sse_event(frame))
                    self.wfile.flush()
                    wrote = True
                raise OSError("sibling stream ended without [DONE]")
            except Exception as e:
                log.warning("migrate relay for %s failed: %s", sess.id, e)
                if wrote or index > 0:
                    # mid-stream: the response is committed — the best
                    # remaining option is an explicit error frame
                    try:
                        self.wfile.write(sse_event(
                            {"id": sess.id, "status": 502,
                             "error": f"migration relay failed: {e}"}))
                        self.wfile.flush()
                    except OSError:
                        pass
            finally:
                if conn is not None:
                    conn.close()
                server._relay_exit()

        def _migrate_unary(self, sess, payload, target) -> None:
            """Re-run/resume on the sibling and relay its answer under
            the original request id. Nothing has been written to this
            client yet, so a failure just closes the connection — the
            gateway retries uncommitted responses transparently."""
            server._relay_enter()
            conn = None
            try:
                conn, resp = self._migrate_post(sess, payload, target)
                out = json.loads(resp.read())
                if resp.status != 200:
                    raise OSError(f"sibling answered {resp.status}: {out}")
                if "id" in out:
                    out["id"] = sess.id
                self._json(200, out)
            except (BrokenPipeError, ConnectionResetError):
                pass
            except Exception as e:
                log.warning("migrate relay for %s failed: %s", sess.id, e)
            finally:
                if conn is not None:
                    conn.close()
                server._relay_exit()

        def _next_event(self, sess):
            """Block on the session queue, but never past a dead engine
            thread (its _abort_all is what normally wakes us)."""
            import queue as _q

            while True:
                try:
                    return sess.events.get(timeout=0.5)
                except _q.Empty:
                    t = scheduler._thread
                    if t is None or not t.is_alive():
                        return ("error", 503, "engine thread died")

        def _stream_response(self, sess) -> None:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.end_headers()
            index = 0
            try:
                while True:
                    ev = self._next_event(sess)
                    if ev[0] == "token":
                        _, tok_id, text, top = ev
                        frame = {"index": index, "token": tok_id,
                                 "text": text}
                        if top is not None:
                            frame["logprobs"] = [
                                {"id": i, "logprob": round(v, 6)}
                                for i, v in top
                            ]
                        self.wfile.write(sse_event(frame))
                        index += 1
                    elif ev[0] == "done":
                        _, reason, usage, tail = ev
                        self.wfile.write(sse_event(
                            {"id": sess.id, "done": True,
                             "finish_reason": reason, "usage": usage,
                             "text": tail}))
                        self.wfile.write(sse_event("[DONE]"))
                        self.wfile.flush()
                        return
                    elif ev[0] == "migrate":
                        _, payload, target = ev
                        self._migrate_stream(sess, payload, target, index)
                        return
                    else:  # error
                        _, status, message = ev
                        self.wfile.write(sse_event(
                            {"id": sess.id, "error": message,
                             "status": status}))
                        self.wfile.flush()
                        return
                    self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError):
                # the client went away mid-stream: retire the stream so
                # its slot and KV row go back to the admission queue
                scheduler.cancel(sess)

        def _unary_response(self, sess) -> None:
            texts: list[str] = []
            while True:
                ev = self._next_event(sess)
                if ev[0] == "token":
                    if ev[2]:
                        texts.append(ev[2])
                elif ev[0] == "migrate":
                    # the sibling re-runs the whole request; its full
                    # answer supersedes the tokens collected so far
                    self._migrate_unary(sess, ev[1], ev[2])
                    return
                elif ev[0] == "done":
                    _, reason, usage, tail = ev
                    if tail:
                        texts.append(tail)
                    out = {
                        "id": sess.id,
                        "model": server.model_id,
                        "finish_reason": reason,
                        "usage": usage,
                        "token_ids": list(sess.generated),
                    }
                    if scheduler.engine.tokenizer is not None:
                        out["text"] = "".join(texts)
                    try:
                        self._json(200, out)
                    except (BrokenPipeError, ConnectionResetError, OSError):
                        pass
                    return
                else:
                    _, status, message = ev
                    try:
                        self._error(status, message)
                    except (BrokenPipeError, ConnectionResetError, OSError):
                        pass
                    return

    return Handler
