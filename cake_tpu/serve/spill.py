"""Bounded host-RAM spill store for preempted KV streams (ISSUE 20).

When an interactive arrival finds the engine saturated with batch
streams, the scheduler exports the victim's stream (the disagg snapshot
plane: KV pages + sampler/cursor state, bit-identical round trips) and
parks the payload HERE — host RAM, not device pages — until pressure
drops and the victim resumes through the engine's import path. The
store is the safety valve's safety valve: it is *bounded* (``max_bytes``),
and a store at capacity refuses the claim, which means the preemption
simply does not land — the victim keeps decoding and the arrival waits,
which is strictly better than an unbounded host-RAM balloon.

The acquire/release protocol is explicit so cakelint CK-CLAIM can
verify call sites (``analysis/claims.py`` rule ``serve.spill``):

- ``spill_begin(key, nbytes)`` reserves capacity and returns a claim;
- ``spill_commit(claim, payload)`` lands the payload (the reservation
  becomes an entry);
- ``spill_abort(claim)`` drops the reservation (export raced the
  victim's retirement, engine fault mid-preempt).

Every ``spill_begin`` must reach a ``spill_commit`` or ``spill_abort``
on all paths, exception edges included — a leaked reservation shrinks
the store for every later preemption.
"""

from __future__ import annotations

import dataclasses
import threading

from cake_tpu.obs import metrics as obs_metrics

# current occupancy (gauges, not counters: spilled streams resume and
# leave) — the /healthz spill-pressure fields and the bench's ledger
# both read these
SPILL_BYTES = obs_metrics.gauge("serve.spill_bytes")
SPILL_PAGES = obs_metrics.gauge("serve.spill_pages")


class SpillFull(Exception):
    """The store cannot reserve the requested bytes — the preemption
    must not land (the victim keeps its slot and pages)."""


@dataclasses.dataclass(frozen=True)
class SpillClaim:
    """One reservation token: ``spill_begin``'s result, consumed by
    exactly one ``spill_commit`` or ``spill_abort``."""

    key: str
    nbytes: int
    pages: int


class SpillStore:
    """Host-RAM parking for exported stream snapshots, keyed by session
    id. Thread contract: the scheduler's engine thread owns the
    begin/commit/abort/take lifecycle; ``stats()`` is handler-safe (the
    lock exists for that read, not for contention)."""

    _GUARDED_BY = {"_entries": "_lock", "_reserved": "_lock"}
    _THREAD_DOMAIN = "any"

    def __init__(self, max_bytes: int = 64 << 20):
        if max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1, got {max_bytes}")
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: dict[str, tuple[bytes, int]] = {}  # key -> (payload, pages)
        self._reserved: dict[str, SpillClaim] = {}

    # -- claim lifecycle (cakelint CK-CLAIM rule "serve.spill") --------------
    def spill_begin(self, key: str, nbytes: int, pages: int = 0) -> SpillClaim:
        """Reserve ``nbytes`` for ``key``; raises :class:`SpillFull` at
        capacity (the caller then abandons the preemption) and
        ``ValueError`` on a duplicate key (one spill per stream)."""
        nbytes = int(nbytes)
        with self._lock:
            if key in self._entries or key in self._reserved:
                raise ValueError(f"stream {key!r} is already spilled")
            used = sum(len(p) for p, _ in self._entries.values())
            held = sum(c.nbytes for c in self._reserved.values())
            if used + held + nbytes > self.max_bytes:
                raise SpillFull(
                    f"spill store at capacity ({used + held}B used + "
                    f"{nbytes}B wanted > {self.max_bytes}B)")
            claim = SpillClaim(key=key, nbytes=nbytes, pages=int(pages))
            self._reserved[key] = claim
            return claim

    def spill_commit(self, claim: SpillClaim, payload: bytes) -> None:
        """Land the payload under the claim's key; the reservation is
        consumed."""
        with self._lock:
            if self._reserved.pop(claim.key, None) is None:
                raise ValueError(f"no open claim for {claim.key!r}")
            self._entries[claim.key] = (bytes(payload), claim.pages)
            self._refresh_locked()

    def spill_abort(self, claim: SpillClaim) -> None:
        """Drop the reservation (the preemption did not land)."""
        with self._lock:
            self._reserved.pop(claim.key, None)

    # -- resume side ---------------------------------------------------------
    def take(self, key: str) -> bytes | None:
        """Pop the payload for ``key`` (None = never spilled or already
        taken/discarded); occupancy shrinks immediately."""
        with self._lock:
            ent = self._entries.pop(key, None)
            self._refresh_locked()
            return ent[0] if ent is not None else None

    def discard(self, key: str) -> bool:
        """Drop a parked payload whose stream will never resume here
        (cancel, deadline, migration took it)."""
        with self._lock:
            ent = self._entries.pop(key, None)
            self._refresh_locked()
            return ent is not None

    # -- stats ---------------------------------------------------------------
    def _refresh_locked(self) -> None:
        SPILL_BYTES.set(sum(len(p) for p, _ in self._entries.values()))
        SPILL_PAGES.set(sum(pg for _, pg in self._entries.values()))

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "streams": len(self._entries),
                "bytes": sum(len(p) for p, _ in self._entries.values()),
                "pages": sum(pg for _, pg in self._entries.values()),
                "max_bytes": self.max_bytes,
            }
