"""SLO-aware request scheduler: ONE engine-owner thread over the batch plane.

The continuous-batching engine (``runtime.batch_generator.BatchGenerator``)
is single-threaded by design — every ``step()`` mutates device state. The
scheduler is the concurrency boundary that turns it into a service: HTTP
handler threads only ``submit``/``cancel`` sessions through a lock, and one
engine thread — the only caller of the engine, ever — admits queued
arrivals into free slots (``enqueue``; the engine interleaves each
arrival's prefill with the running batch's decode), runs ``step()``
continuously while work exists, idle-parks on a condition variable
otherwise, fans each emitted row out to per-session event queues, and
retires streams on EOS, ``max_tokens``, client disconnect, or deadline
expiry (``finish`` frees the slot and its KV row for the next arrival).

Backpressure is explicit, never blocking: the admission queue is bounded
(``queue_depth``); a submit past the bound raises :class:`QueueFull`
carrying a ``Retry-After`` estimate derived from the observed aggregate
tokens/sec (outstanding token budget / recent throughput) — the API layer
turns it into a ``429`` without ever stalling the accept loop.

Iteration-level scheduling is the Orca lesson and continuous batching the
vLLM one; both live in the engine already — this layer adds what a service
needs around them: admission, fairness, deadlines, cancellation, and
drain.

SLO-aware scheduling (ISSUE 20), ``sched_policy="slo"`` (the default;
``"fifo"`` is the single-tenant baseline the bench A/B's against):

- **Priority classes** — each session carries a class
  (``session.CLASSES``, highest first): interactive arrivals jump batch
  arrivals in the admission queue (FIFO within a class).
- **Preemption with host-RAM KV spill** — an interactive arrival that
  finds every slot held by batch streams picks a victim (lowest class,
  over-budget tenants preferred, most recently admitted), exports its
  stream via the disagg snapshot path into the bounded
  :class:`~cake_tpu.serve.spill.SpillStore`, and takes the slot + pages.
  The victim resumes bit-identically through the engine's import path
  when pressure drops; device rows the export captured past what the
  client saw replay into the session first, so the client's stream is
  byte-identical to an unpreempted run.
- **Per-tenant fairness** — a decaying token-rate accountant keyed by
  the session's ``tenant`` (defaults to its class): over-budget tenants
  queue behind in-budget arrivals of the same class and are preferred
  preemption victims (``serve.tenant_throttled``).
"""

from __future__ import annotations

import contextlib
import logging
import os
import queue
import threading
import time
from collections import deque

from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.obs import prof as obs_prof
from cake_tpu.obs import reqtrace as obs_reqtrace
from cake_tpu.serve import session as _session
from cake_tpu.serve.session import CLASSES, Session
from cake_tpu.serve.spill import SpillFull, SpillStore

log = logging.getLogger("cake_tpu.serve.scheduler")

# admission policies: "slo" = class-priority + preemption + tenant
# fairness (the production mix); "fifo" = strict arrival order, no
# preemption (the single-tenant baseline the CAKE_BENCH_SLO row A/B's
# class-aware scheduling against)
SCHED_POLICIES = ("slo", "fifo")

# replica roles (cake_tpu/disagg): what this scheduler DOES with a
# request is role-driven — "prefill" runs bucketed prefill only and
# hands the finished KV pages off at the first token; "decode" imports
# pages and runs the steady-state batched step (it still serves plain
# requests: that is the gateway's transparent re-prefill fallback);
# "mixed" is the classic everything-replica.
ROLES = ("mixed", "prefill", "decode")

# KV transfers in flight on this replica (outgoing handoff sends +
# imports awaiting their resume) — the /healthz kv_transfers_inflight
# field the gateway's tier map reads
_INFLIGHT = obs_metrics.gauge("disagg.inflight")

# sessions re-homed to a sibling replica by a drain (ISSUE 19 rolling
# restarts): queued ones re-run whole, admitted ones ride a KV snapshot
MIGRATED = obs_metrics.counter("serve.migrated_sessions")

# SLO-aware scheduling (ISSUE 20): batch victims spilled to host RAM
# for an interactive arrival, how long their resume took (import begin
# through attach queued, replay included), and admissions where an
# over-budget tenant was queued behind in-budget arrivals
PREEMPTIONS = obs_metrics.counter("serve.preemptions")
RESUME_MS = obs_metrics.histogram("serve.resume_ms")
THROTTLED = obs_metrics.counter("serve.tenant_throttled")


class TenantAccounts:
    """Decayed per-tenant token-rate shares (engine thread only — fed by
    ``_deliver``, read by admission ordering and victim selection).

    A tenant is over budget when its share of recently-emitted tokens
    exceeds ``factor``× its fair share (1/active tenants) — a relative
    test, so it needs no absolute rate knob and a lone tenant is never
    over. The half-life makes monopoly a *recent-history* property: a
    tenant that backs off re-earns its place within a few half-lives.
    """

    _THREAD_DOMAIN = "engine"

    def __init__(self, half_life_s: float = 10.0, factor: float = 2.0):
        self.half_life_s = half_life_s
        self.factor = factor
        self._tokens: dict[str, float] = {}
        self._t = time.monotonic()

    def _decay(self) -> None:
        now = time.monotonic()
        dt = now - self._t
        if dt <= 0:
            return
        self._t = now
        k = 0.5 ** (dt / self.half_life_s)
        for tenant in list(self._tokens):
            v = self._tokens[tenant] * k
            if v < 0.5:
                del self._tokens[tenant]  # idle tenants leave the census
            else:
                self._tokens[tenant] = v

    def add(self, tenant: str, n: int = 1) -> None:
        self._decay()
        self._tokens[tenant] = self._tokens.get(tenant, 0.0) + n

    def over_budget(self, tenant: str) -> bool:
        self._decay()
        total = sum(self._tokens.values())
        n = len(self._tokens)
        if n < 2 or total <= 0:
            return False
        return self._tokens.get(tenant, 0.0) / total > self.factor / n


class QueueFull(Exception):
    """Admission queue at capacity; ``retry_after_s`` is the backpressure
    hint (seconds until a slot is plausibly free, from observed tok/s)."""

    def __init__(self, retry_after_s: float):
        super().__init__(f"admission queue full; retry in {retry_after_s:g}s")
        self.retry_after_s = retry_after_s


class Draining(Exception):
    """The scheduler stopped admitting (SIGTERM drain in progress)."""


class Scheduler:
    """Own the engine; serve sessions.

    ``engine`` is a ``BatchGenerator`` (or anything with its serving API —
    see ``serve.engine.SingleStreamEngine`` for the single-stream paths).
    ``start()`` primes it and launches the engine thread; ``stop()`` drains
    or aborts. Thread contract: public methods are handler-safe; everything
    touching the engine runs on the engine thread only.
    """

    # Thread contract, machine-checked by `make lint` (cakelint CK-LOCK):
    # the admission queue, the live-session map, and the lifecycle flags
    # are shared between handler threads and the engine thread, and may
    # only be touched under the condition lock (methods named *_locked
    # assert their caller already holds it). The throughput-EMA fields
    # (_tok_s, _rate_*) are engine-thread-only writes with tolerated
    # atomic reads, so they stay out of the map on purpose.
    _GUARDED_BY = {
        "_queue": "_cond",
        "_by_sid": "_cond",
        "_draining": "_cond",
        "_stopping": "_cond",
        "_import_inbox": "_cond",
        "_imports_meta": "_cond",
        "_xfer_out": "_cond",
        "_engine_stats": "_cond",
        "_migrate_to": "_cond",
        "_spilled": "_cond",
    }

    # Thread domains, machine-checked by cakelint CK-THREAD: the class
    # is engine-domain (only the engine thread runs its un-listed
    # methods), and _THREAD_SAFE names the crossing points — the
    # handler-facing API that hands work across the boundary through the
    # condition lock, the admission queue, and the import inbox instead
    # of touching the engine. `start` primes the engine on the caller's
    # thread happens-before the engine thread exists, so it counts as
    # engine-domain code. The runtime twin (CAKE_THREAD_STRICT=1,
    # runtime/threadcheck) stamps the engine thread at _run entry and
    # asserts membership in the engine's annotated mutators.
    _THREAD_DOMAIN = "engine"
    _THREAD_OF = {"start": "engine"}
    _THREAD_SAFE = (
        "submit", "cancel", "stop", "close", "encode_prompt",
        "submit_import", "abort_import", "import_meta",
        "xfer_out_enter", "xfer_out_exit", "kv_transfers_inflight",
        "retry_after_s", "stats", "_sync_inflight", "migrate_out",
        "can_migrate", "set_policy",
    )

    def __init__(self, engine, queue_depth: int = 64,
                 request_timeout_s: float | None = None,
                 role: str = "mixed", transfer_codec: str = "none",
                 transfer_deadline_s: float = 15.0,
                 import_ttl_s: float = 120.0,
                 slo: obs_reqtrace.SloTracker | None = None,
                 sched_policy: str = "slo", spill_mb: float = 64.0,
                 fairness_factor: float = 2.0):
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}, got {role!r}")
        if sched_policy not in SCHED_POLICIES:
            raise ValueError(f"sched_policy must be one of "
                             f"{SCHED_POLICIES}, got {sched_policy!r}")
        if role != "mixed" and not (hasattr(engine, "export_stream")
                                    and getattr(engine, "paged", False)):
            raise ValueError(
                f"role {role!r} needs a disagg-capable engine "
                "(BatchGenerator with kv_layout='paged')")
        self.engine = engine
        self.queue_depth = queue_depth
        self.request_timeout_s = request_timeout_s
        # disagg plane (cake_tpu/disagg): role + KV-transfer knobs. The
        # transfer listener (if any) reports its port here so /healthz
        # can advertise it to the gateway's tier map.
        self.role = role
        self.transfer_codec = transfer_codec
        self.transfer_deadline_s = transfer_deadline_s
        self.import_ttl_s = import_ttl_s
        # SLO accounting (--slo-ttft-ms/--slo-tpot-ms): sessions judge
        # themselves against this tracker at finish (obs/reqtrace)
        self.slo = slo
        # SLO-aware scheduling (ISSUE 20). sched_policy is written only
        # by set_policy (under _cond) and read by the engine thread each
        # pass — a str attribute swap, tolerated like the _tok_s reads.
        # The spill store exists only when the engine can export pages
        # (SingleStreamEngine and slot-layout engines degrade to class
        # ordering without preemption).
        self.sched_policy = sched_policy
        can_spill = bool(hasattr(engine, "export_stream")
                         and getattr(engine, "paged", False))
        self._spill: SpillStore | None = (
            SpillStore(max_bytes=int(spill_mb * (1 << 20)))
            if can_spill and spill_mb > 0 else None)
        # spilled victims awaiting resume: {"sess": Session, "t": float}
        self._spilled: list[dict] = []
        # token-rate fairness accountant — engine-thread-only (fed by
        # _deliver, read by admission/victim ordering), so it stays out
        # of _GUARDED_BY like the throughput EMA
        self._tenants = TenantAccounts(factor=fairness_factor)
        self._n_preempt = 0  # engine-thread writes, atomic healthz reads
        # testing/chaos.SpillChaos hook, consulted on the engine thread
        # at the preempt/resume protocol points (tests arm it directly)
        self.spill_chaos = None
        self.transfer_port: int | None = None
        self.max_concurrent = 0  # set by start() (dp may pad the batch up)
        self._queue: deque[Session] = deque()
        self._by_sid: dict[int, Session] = {}
        self._next_sid = 0
        self._cond = threading.Condition()
        self._thread: threading.Thread | None = None
        self._stopping = False
        self._draining = False
        # KV-transfer state: the import inbox feeds snapshot payloads
        # from transfer-listener threads to the engine thread (the only
        # thread allowed to touch the engine/pool); the meta map mirrors
        # begun imports for the resume handler; _xfer_out counts
        # outgoing handoff sends in flight
        self._import_inbox: deque = deque()
        self._imports_meta: dict[str, dict] = {}
        self._xfer_out = 0
        # drain migration target ({"addr", "transfer"}): set by
        # migrate_out, consumed by the engine thread's _migrate_all
        self._migrate_to: dict | None = None
        self._last_sweep = time.monotonic()
        # engine-stats snapshot for handler threads: the engine thread
        # refreshes it every loop pass, so stats()/healthz never walk
        # live engine state from a foreign thread (cakelint CK-THREAD)
        self._engine_stats: dict = {}
        # observed-throughput window for the Retry-After estimate
        self._rate_tokens = 0
        self._rate_t0 = time.perf_counter()
        self._tok_s = 0.0

    # -- lifecycle ------------------------------------------------------------
    def start(self, max_concurrent: int = 4,
              warm_prompt_len: int | None = None,
              warm_constrain: bool = False) -> None:
        """Prime the engine with ``max_concurrent`` retired slots and start
        the engine thread. A batch engine needs a live batch before
        ``enqueue`` can splice arrivals into it, so priming runs one
        minimal ``set_prompts`` and retires every slot immediately — every
        real request then rides the continuous-admission path. With
        ``warm_prompt_len``, the admission-prefill program is compiled here
        too, outside the serving window (``warm_admission``);
        ``warm_constrain`` additionally compiles the masked decode
        program, so the FIRST constrained request (``response_format``)
        does not stall every live stream on an XLA compile mid-serving."""
        if self._thread is not None:
            raise RuntimeError("scheduler already started")
        if max_concurrent < 1:
            raise ValueError(
                f"max_concurrent must be >= 1, got {max_concurrent}")
        if not self.engine.streams:
            cfg = self.engine.config
            tok = cfg.bos_token_id if cfg.bos_token_id is not None else 0
            self.engine.set_prompts([[tok]] * max_concurrent)
            for s in self.engine.streams:
                s.done = True
        # dp padding may have grown the batch; padded rows are admissible
        # slots too, so serve them rather than leaving them dummy rows
        self.max_concurrent = len(self.engine.streams)
        self._next_sid = self.max_concurrent  # clear of the priming ids
        if warm_prompt_len and hasattr(self.engine, "warm_admission"):
            self.engine.warm_admission(warm_prompt_len)
        if warm_constrain and hasattr(self.engine, "warm_constrain"):
            self.engine.warm_constrain()
        # seed the handler-facing snapshot happens-before the engine
        # thread exists; from here on only that thread refreshes it
        self._refresh_engine_stats()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cake-serve-engine")
        self._thread.start()

    def stop(self, drain: bool = True, timeout_s: float = 30.0) -> None:
        """Stop serving. ``drain=True`` (the SIGTERM path): stop admitting
        — queued-but-unadmitted sessions are refused with a 503 — finish
        every in-flight stream, then park the thread. ``drain=False``:
        abort in-flight streams with an error event."""
        with self._cond:
            self._draining = True
            if not drain:
                self._stopping = True
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            deadline = time.monotonic() + timeout_s
            while t.is_alive() and time.monotonic() < deadline:
                t.join(timeout=0.1)
            if t.is_alive():
                # in-flight streams outlived the budget: hard-stop
                with self._cond:
                    self._stopping = True
                    self._cond.notify_all()
                t.join(timeout=5.0)

    def close(self) -> None:
        self.stop(drain=False, timeout_s=5.0)
        if hasattr(self.engine, "close"):
            self.engine.close()

    # -- handler-side API -----------------------------------------------------
    def encode_prompt(self, prompt) -> list[int]:
        """Engine intake rules (tokenize, BOS, window/vocab bounds) without
        touching engine state — safe from handler threads (the tokenizer
        is stateless per encode)."""
        return self.engine._encode(prompt)

    def submit(self, sess: Session) -> None:
        """Queue a session FIFO (raises :class:`QueueFull` past the bound,
        :class:`Draining` during shutdown). Never blocks on the engine."""
        with self._cond:
            if self._draining:
                raise Draining()
            # admission is asynchronous, so a submit destined for a free
            # slot sits in the queue for one engine-thread pass; the bound
            # is therefore on WAITING requests — total outstanding is
            # capped at max_concurrent + queue_depth
            free = max(0, self.max_concurrent - len(self._by_sid))
            if len(self._queue) >= self.queue_depth + free:
                _session.REJECTED.inc()
                raise QueueFull(self.retry_after_s())
            if self.request_timeout_s and sess.deadline is None:
                sess.deadline = sess.t_submit + self.request_timeout_s
            self._queue.append(sess)
            _session.QUEUE_DEPTH.set(len(self._queue))
            self._cond.notify_all()

    def cancel(self, sess: Session) -> None:
        """Flag a session whose client went away; the engine thread frees
        its slot (or drops it from the queue) at the next loop pass."""
        sess.cancelled.set()
        with self._cond:
            self._cond.notify_all()

    def can_migrate(self) -> bool:
        """Admitted streams can ride a KV snapshot to a sibling (the
        disagg export plane). Queued sessions re-home regardless."""
        return bool(hasattr(self.engine, "export_stream")
                    and getattr(self.engine, "paged", False))

    def set_policy(self, policy: str) -> None:
        """Swap the admission policy between runs (the CAKE_BENCH_SLO
        row A/B's "fifo" against "slo" on one warmed stack). Handler-
        safe; takes effect at the engine thread's next pass."""
        if policy not in SCHED_POLICIES:
            raise ValueError(f"sched_policy must be one of "
                             f"{SCHED_POLICIES}, got {policy!r}")
        with self._cond:
            self.sched_policy = policy

    def migrate_out(self, target: dict | None) -> int:
        """Begin a drain that RE-HOMES live sessions instead of making
        clients wait it out (ISSUE 19 rolling restarts): stop admitting,
        and ask the engine thread to hand every live session to its
        handler with a migration target — queued sessions re-run whole
        on the sibling, admitted ones export their stream via the
        existing disagg snapshot path. ``target`` is ``{"addr":
        "host:port", "transfer": "host:port"}`` (None = classic drain:
        in-flight streams finish here). Returns the number of sessions
        that will migrate."""
        with self._cond:
            self._draining = True
            n = 0
            if target is not None and isinstance(target.get("addr"), str):
                self._migrate_to = dict(target)
                n = len(self._queue) + len(self._by_sid)
            self._cond.notify_all()
        return n

    # -- KV-transfer plane (cake_tpu/disagg) ----------------------------------
    def submit_import(self, payload: bytes, timeout_s: float = 10.0) -> dict:
        """Hand an inbound snapshot to the engine thread and wait for its
        verdict (called by the transfer listener). Parse + fingerprint
        validation happen on the engine thread (`import_begin`); pool
        pressure does NOT delay the verdict — the pages land later via
        the engine's FIFO-fair arrival queue. Raises ``ValueError`` with
        the refusal reason (the sender's XFER_REJECT) on a bad snapshot,
        ``TimeoutError`` when the engine thread is wedged or gone."""
        reply: queue.Queue = queue.Queue()
        with self._cond:
            if self._draining:
                raise ValueError("replica is draining; re-prefill elsewhere")
            self._import_inbox.append(("begin", payload, reply))
            self._cond.notify_all()
        try:
            verdict, value = reply.get(timeout=timeout_s)
        except queue.Empty:
            raise TimeoutError("engine thread did not pick up the import")
        if verdict == "err":
            raise ValueError(value)
        return value

    def abort_import(self, xfer_id: str) -> None:
        """Queue an import abort (resume satisfied by the replay alone,
        or the caller gave up) — processed on the engine thread."""
        with self._cond:
            self._import_inbox.append(("abort", xfer_id, None))
            self._cond.notify_all()

    def import_meta(self, xfer_id: str) -> dict | None:
        """Resume metadata for a begun import (None = unknown/expired)."""
        with self._cond:
            meta = self._imports_meta.get(xfer_id)
            return dict(meta) if meta is not None else None

    def xfer_out_enter(self) -> None:
        with self._cond:
            self._xfer_out += 1
        self._sync_inflight()

    def xfer_out_exit(self) -> None:
        with self._cond:
            self._xfer_out -= 1
        self._sync_inflight()

    def kv_transfers_inflight(self) -> int:
        with self._cond:
            return self._xfer_out + len(self._imports_meta)

    def _sync_inflight(self) -> None:
        _INFLIGHT.set(self.kv_transfers_inflight())

    def _drain_import_inbox(self) -> None:
        """Engine thread: apply queued KV-transfer ops."""
        while True:
            with self._cond:
                if not self._import_inbox:
                    return
                kind, payload, reply = self._import_inbox.popleft()
            if kind == "begin":
                t_begin = time.time()
                try:
                    meta = self.engine.import_begin(payload)
                except Exception as e:
                    if reply is not None:
                        reply.put(("err", str(e)))
                    continue
                with self._cond:
                    self._imports_meta[meta["xfer_id"]] = dict(
                        meta, t=time.monotonic())
                self._sync_inflight()
                ctx = obs_reqtrace.ReqTrace.from_wire(meta.get("trace"))
                if ctx is not None:
                    # the snapshot carried its request's trace context:
                    # land the import as a span parented under the
                    # prefill tier's export, and make it queryable here
                    ctx.add_span("disagg.import", t_begin,
                                 (time.time() - t_begin) * 1e3,
                                 xfer=meta["xfer_id"])
                    obs_reqtrace.request_log().put(ctx)
                if reply is not None:
                    reply.put(("ok", meta))
            else:  # abort
                self.engine.import_abort(payload)
                with self._cond:
                    self._imports_meta.pop(payload, None)
                self._sync_inflight()

    def _sweep_imports(self) -> bool:
        """Engine thread, ~1/s: expire begun-but-unresumed imports so an
        orphaned transfer (gateway died between ACK and resume) cannot
        pin pool pages forever. Returns True when a sweep pass ran (the
        parked loop refreshes the stats snapshot on that cadence — a
        sweep can unpin pages with no work pass in sight)."""
        now = time.monotonic()
        if now - self._last_sweep < 1.0:
            return False
        self._last_sweep = now
        if hasattr(self.engine, "expire_imports"):
            self.engine.expire_imports(self.import_ttl_s)
        with self._cond:
            stale = [x for x, m in self._imports_meta.items()
                     if now - m["t"] > self.import_ttl_s]
            for x in stale:
                self._imports_meta.pop(x, None)
        if stale:
            self._sync_inflight()
        return True

    def _refresh_engine_stats(self, best_effort: bool = False) -> None:
        """Engine thread: publish the stats snapshot handler threads
        read (stats()/healthz) — they must never walk live engine state
        themselves (cakelint CK-THREAD). ``best_effort`` swallows a
        stats() failure (the fault/shutdown paths refresh so a dead
        engine doesn't keep advertising its last healthy snapshot, but
        a faulted engine may not be able to report at all)."""
        try:
            snap = self.engine.stats()
        except Exception:
            if not best_effort:
                raise
            return
        with self._cond:
            self._engine_stats = snap

    def _fail_lost_attaches(self) -> None:
        """Engine thread: sessions whose resume attach found its import
        gone (TTL raced the resume) fail with a retryable status instead
        of hanging until their deadline."""
        if not hasattr(self.engine, "take_attach_failures"):
            return
        for sid in self.engine.take_attach_failures():
            with self._cond:
                sess = self._by_sid.pop(sid, None)
            if sess is not None and sess.finish_reason is None:
                sess.fail(409, "kv import expired before the resume "
                               "attached; re-prefill elsewhere")

    def retry_after_s(self) -> float:
        """Backpressure hint: outstanding token budget over the observed
        aggregate tokens/sec, clamped to something a client can act on."""
        with self._cond:
            remaining = sum(
                max(1, s.max_tokens - len(s.generated))
                for s in self._by_sid.values()
            ) + sum(s.max_tokens for s in self._queue)
        rate = self._tok_s
        if rate <= 0:
            return 2.0
        return min(max(remaining / rate, 1.0), 120.0)

    def stats(self) -> dict:
        with self._cond:
            queued = len(self._queue)
            running = len(self._by_sid)
            spilled = len(self._spilled)
            draining = self._draining
            # the engine block is the ENGINE THREAD's own snapshot
            # (refreshed every loop pass) — handler threads must not
            # walk live engine state (cakelint CK-THREAD); one pass of
            # lag is invisible next to probe intervals
            engine_stats = dict(self._engine_stats)
        return {
            "queued": queued,
            "running": running,
            "max_concurrent": self.max_concurrent,
            "queue_depth": self.queue_depth,
            "draining": draining,
            "observed_tok_s": round(self._tok_s, 2),
            "role": self.role,
            "sched_policy": self.sched_policy,
            # spill pressure (ISSUE 20): victims parked in host RAM and
            # the preemption count — /healthz forwards both so the
            # gateway's p2c load signal sees latent load that will
            # resume here
            "spilled": spilled,
            "preemptions": self._n_preempt,
            **({"spill": self._spill.stats()}
               if self._spill is not None else {}),
            "kv_transfers_inflight": self.kv_transfers_inflight(),
            **({"transfer_port": self.transfer_port}
               if self.transfer_port else {}),
            **({"slo": self.slo.snapshot()}
               if self.slo is not None else {}),
            "engine": engine_stats,
        }

    # -- engine thread --------------------------------------------------------
    def _has_work_locked(self) -> bool:
        return bool(self._queue or self._by_sid or self._import_inbox
                    or self._spilled
                    or self._migrate_to is not None
                    or self.engine.pending_admissions())

    def _run(self) -> None:
        # claim the engine's thread domain for this thread (runtime twin
        # of cakelint CK-THREAD, runtime/threadcheck): under
        # CAKE_THREAD_STRICT=1 every annotated engine/pool mutator
        # asserts it runs here. Cleared on exit — post-join teardown and
        # drain replays may legitimately drive the engine again.
        stamp = getattr(self.engine, "_domain_stamp", None)
        if stamp is not None:
            stamp.stamp()
        try:
            self._run_loop()
        finally:
            if stamp is not None:
                stamp.clear()

    def _run_loop(self) -> None:
        # retrace-sentinel warmup budget: after this many engine passes the
        # compile set is assumed stable, and further decode-phase compiles
        # are retrace findings (obs/prof). Explicitly tunable — chained
        # block-size buckets legitimately compile late on some deployments.
        warm_steps = int(os.environ.get("CAKE_PROF_WARM_STEPS", "32"))
        steps = 0
        while True:
            with self._cond:
                self._expire_queued_locked()
                while not self._stopping and not self._has_work_locked():
                    if self._draining:
                        break  # drained dry: park
                    t_park = time.perf_counter()
                    self._cond.wait(timeout=0.1)
                    obs_prof.profiler().observe_ms(
                        "idle_park",
                        (time.perf_counter() - t_park) * 1e3)
                    self._expire_queued_locked()
                    # imports awaiting resume are not "work" (nothing to
                    # step), but their TTL must still tick while parked —
                    # and a sweep that runs can unpin pages, so the
                    # handler-facing stats snapshot refreshes with it
                    # (the condition's RLock makes the re-acquire safe)
                    if self._sweep_imports():
                        self._refresh_engine_stats()
                if self._stopping or (self._draining
                                      and not self._has_work_locked()):
                    break
            try:
                self._drain_import_inbox()
                self._sweep_imports()
                if self._migrate_all():
                    # the slot set just went empty: skip the engine step
                    # and let the top-of-loop drain check park/exit
                    self._refresh_engine_stats(best_effort=True)
                    continue
                self._admit()
                row = self.engine.step()
                steps += 1
                if steps == warm_steps:
                    obs_prof.sentinel().mark_steady()
                self._deliver(row)
                self._retire()
                self._sweep_spilled()
                self._fail_lost_attaches()
                self._refresh_engine_stats()
            except Exception as e:  # engine fault: fail every session
                log.exception("engine thread fault: %s", e)
                with self._cond:
                    # flip to draining BEFORE aborting: a dead engine must
                    # refuse new work (submit -> 503, /healthz -> 503) —
                    # otherwise submissions queue behind a thread that
                    # will never serve them and the balancer keeps
                    # routing traffic here
                    self._draining = True
                self._abort_all(f"engine failure: {e}")
                # don't keep advertising the last HEALTHY snapshot for
                # a dead engine (stats may itself fail mid-fault)
                self._refresh_engine_stats(best_effort=True)
                return
        self._abort_all("server shutting down")
        self._refresh_engine_stats(best_effort=True)

    def _expire_queued_locked(self) -> None:
        """Refuse queued sessions past their arrival deadline (and drop
        cancelled ones) without spending engine work on them. During a
        drain, everything still queued is refused."""
        now = time.perf_counter()
        keep: deque[Session] = deque()
        for s in self._queue:
            if s.cancelled.is_set():
                _session.CANCELLED.inc()
            elif self._draining:
                if self._migrate_to is not None:
                    # drain with a sibling: re-home instead of refusing —
                    # nothing was emitted yet, so the session re-runs
                    # whole over there and the client sees one stream
                    s.migrate_ready(None, self._migrate_to)
                    MIGRATED.inc()
                else:
                    s.fail(503, "server is draining; retry against a peer")
            elif s.deadline is not None and now > s.deadline:
                _session.TIMEOUTS.inc()
                s.fail(504, "deadline expired while queued")
            else:
                keep.append(s)
                continue
            # a refused resume will never attach: release its begun
            # import's pinned pages now instead of waiting out the TTL
            if s.resume_xfer is not None:
                self._import_inbox.append(("abort", s.resume_xfer, None))
        if len(keep) != len(self._queue):
            self._queue = keep
            _session.QUEUE_DEPTH.set(len(self._queue))

    def _admit(self) -> None:
        """Move queued sessions into the engine while slots are spoken
        for < max_concurrent (the engine interleaves each arrival's
        prefill with decode). Under ``sched_policy="slo"`` the pick is
        class-ordered — spilled resumes and queued arrivals merge, and
        a saturated engine preempts a batch victim for a waiting
        higher-class arrival (``_maybe_preempt``); ``"fifo"`` keeps
        strict arrival order with no preemption."""
        self._maybe_resume_storm()
        while True:
            while True:
                with self._cond:
                    pick = (self._pick_next_locked()
                            if len(self._by_sid) < self.max_concurrent
                            else None)
                if pick is None:
                    break
                kind, item = pick
                if kind == "resume":
                    self._resume_one(item)
                else:
                    self._admit_one(item)
            if not self._maybe_preempt():
                return

    def _pick_next_locked(self):
        """Pop and return the next admission candidate: ``("resume",
        entry)`` for a spilled victim, ``("admit", session)`` for a
        queued arrival, None when nothing is eligible. Ordering under
        "slo": higher class first; within a class, in-budget tenants
        before over-budget ones, resumes before fresh arrivals (they
        are strictly older), FIFO last. "fifo" is strict arrival order
        (spilled entries only exist under "slo", but drain-overlap ones
        still resume here)."""
        if self.sched_policy == "fifo":
            if self._spilled:
                return ("resume", self._spilled.pop(0))
            if not self._queue:
                return None
            sess = self._queue.popleft()
            _session.QUEUE_DEPTH.set(len(self._queue))
            return ("admit", sess)
        best_key, best = None, None
        for j, ent in enumerate(self._spilled):
            s = ent["sess"]
            key = (CLASSES.index(s.cls),
                   self._tenants.over_budget(s.tenant), 0, j)
            if best_key is None or key < best_key:
                best_key, best = key, ("resume", j)
        for i, s in enumerate(self._queue):
            key = (CLASSES.index(s.cls),
                   self._tenants.over_budget(s.tenant), 1, i)
            if best_key is None or key < best_key:
                best_key, best = key, ("admit", i)
        if best is None:
            return None
        kind, idx = best
        if kind == "resume":
            return ("resume", self._spilled.pop(idx))
        sess = self._queue[idx]
        if any(CLASSES.index(q.cls) == CLASSES.index(sess.cls)
               for q in list(self._queue)[:idx]):
            # an earlier same-class arrival was bypassed — only an
            # over-budget tenant sorts behind within its class
            THROTTLED.inc()
        del self._queue[idx]
        _session.QUEUE_DEPTH.set(len(self._queue))
        return ("admit", sess)

    def _admit_one(self, sess: Session) -> None:
        """Hand one queued session to the engine (enqueue, or attach a
        begun import for a gateway-routed resume)."""
        with self._cond:
            sid = self._next_sid
            self._next_sid += 1
        ctx = sess.reqtrace
        if ctx is not None:
            t_now = time.time()
            ctx.add_span("serve.queue", sess.t_submit_unix,
                         (t_now - sess.t_submit_unix) * 1e3,
                         request=sess.id)
        admit_span = (ctx.span("serve.admit", request=sess.id)
                      if ctx is not None else contextlib.nullcontext())
        try:
            with admit_span:
                if sess.resume_xfer is not None:
                    # a resumed import: attach the already-landed
                    # pages to a slot (page-table edit) — the
                    # snapshot, not the request body, is the source
                    # of stream state
                    self.engine.import_attach(sess.resume_xfer, sid)
                    with self._cond:
                        self._imports_meta.pop(sess.resume_xfer, None)
                    self._sync_inflight()
                # guide= only when constrained: unconstrained
                # admission keeps the bare protocol every engine
                # stub speaks
                elif sess.guide is not None:
                    self.engine.enqueue(sess.prompt_ids, sid,
                                        guide=sess.guide)
                else:
                    self.engine.enqueue(sess.prompt_ids, sid)
        except KeyError as e:  # unknown/expired transfer
            sess.fail(409, str(e))
            return
        except ValueError as e:  # encode raced the window, etc.
            sess.fail(400, str(e))
            return
        sess.t_admit_unix = time.time()
        sess.stream_id = sid
        with self._cond:
            self._by_sid[sid] = sess

    # -- preemption + spill (ISSUE 20) ----------------------------------------
    def _chaos_fire(self, kind: str) -> bool:
        chaos = self.spill_chaos
        return bool(chaos is not None and chaos.fire(kind))

    def _maybe_resume_storm(self) -> None:
        """Chaos hook: a "resume_storm" fault resumes EVERY spilled
        victim at once, regardless of capacity — the attaches queue
        FIFO-fair at the engine and their page demand drives the pool's
        deferral path (`kvpool.admit_defers`) under pressure."""
        if self._spill is None:
            return
        with self._cond:
            if not self._spilled:
                return
        if not self._chaos_fire("resume_storm"):
            return
        with self._cond:
            storm, self._spilled = self._spilled, []
        log.warning("chaos: resume storm over %d spilled streams",
                    len(storm))
        for ent in storm:
            self._resume_one(ent)

    def _resume_one(self, ent: dict) -> None:
        """Bring a spilled victim back: pop its payload from the store,
        import it through the engine's snapshot path, replay any tokens
        the export captured past what the client saw (buffered device
        rows drain into the snapshot, and `finish` discarded their
        emission), and attach to a fresh slot. The replay makes the
        client's stream byte-identical to an unpreempted run; the
        engine emits only NEW tokens after the attach."""
        sess: Session = ent["sess"]
        if sess.cancelled.is_set():
            _session.CANCELLED.inc()
            self._spill.discard(sess.id)
            sess.finish("cancelled")
            return
        t0 = time.perf_counter()
        t0_unix = time.time()
        payload = self._spill.take(sess.id)
        if payload is None:
            sess.fail(503, "spilled stream lost; retry")
            return
        try:
            meta = self.engine.import_begin(payload)
        except Exception as e:
            log.exception("resume import of %s failed", sess.id)
            sess.fail(500, f"spill resume failed: {e}")
            return
        xid = meta["xfer_id"]
        # replay the suffix the client never saw; the session's stop
        # holdback / max_tokens clamp applies exactly as if the tokens
        # had streamed live
        n_seen = len(sess.generated)
        for tid, txt in zip(meta["generated"][n_seen:],
                            meta["texts"][n_seen:]):
            sess.on_token(tid, txt)
            if sess.stop_hit or len(sess.generated) >= sess.max_tokens:
                break
        if sess.stop_hit or len(sess.generated) >= sess.max_tokens:
            # the replay alone finished the request: no slot needed
            self.engine.import_abort(xid)
            sess.finish("stop" if sess.stop_hit else "length")
            return
        with self._cond:
            sid = self._next_sid
            self._next_sid += 1
        try:
            self.engine.import_attach(xid, sid)
        except KeyError as e:
            sess.fail(409, str(e))
            return
        sess.stream_id = sid
        with self._cond:
            self._by_sid[sid] = sess
        dt_ms = (time.perf_counter() - t0) * 1e3
        RESUME_MS.observe(dt_ms)
        if sess.reqtrace is not None:
            sess.reqtrace.add_span("serve.resume", t0_unix, dt_ms,
                                   request=sess.id)

    def _pages_of(self, sess: Session) -> int:
        """KV pages a live stream holds (ceil of its token count over
        the pool's page size) — bookkeeping for the spill gauges."""
        with self._cond:
            ps = (self._engine_stats.get("kvpool") or {}).get("page_size", 0)
        n = len(sess.prompt_ids) + len(sess.generated)
        return (n - 1) // ps + 1 if ps and n else 0

    def _maybe_preempt(self) -> bool:
        """A waiting arrival outranks a running stream: spill the worst
        victim (lowest class, over-budget tenant preferred, most
        recently admitted) to host RAM and free its slot + pages.
        Returns True when a preemption landed (the admit loop then
        re-picks). The export is side-effect-free until `finish`, so
        every refusal path — store full, victim raced retirement,
        chaos fault — leaves the victim decoding untouched."""
        if self._spill is None or self.sched_policy != "slo":
            return False
        with self._cond:
            if self._draining or len(self._by_sid) < self.max_concurrent:
                return False
            waiting = [ent["sess"] for ent in self._spilled]
            waiting += list(self._queue)
            if not waiting:
                return False
            want = min(CLASSES.index(s.cls) for s in waiting)
            cands = [
                (sid, sess) for sid, sess in self._by_sid.items()
                if CLASSES.index(sess.cls) > want
                and sess.handoff is None and sess.logprobs == 0
                and sess.finish_reason is None
                and not sess.cancelled.is_set()
            ]
        if not cands:
            return False
        cands.sort(key=lambda it: (
            -CLASSES.index(it[1].cls),
            not self._tenants.over_budget(it[1].tenant),
            -(it[1].t_admit_unix or 0.0),
        ))
        for sid, sess in cands:
            slot = self._slot_of(sid)
            if slot is None or self.engine.streams[slot].done:
                continue  # finished since the locked snapshot
            if self._chaos_fire("victim_finish"):
                # injected selection race: the victim "finished" between
                # pick and export — bail out, nothing was touched
                log.warning("chaos: victim %d finished during spill", sid)
                return False
            try:
                if self._chaos_fire("spill_full"):
                    raise SpillFull("chaos: spill store at capacity")
                payload = self.engine.export_stream(
                    sid, codec=self.transfer_codec)
                claim = self._spill.spill_begin(
                    sess.id, len(payload), pages=self._pages_of(sess))
            except SpillFull as e:
                log.info("preemption skipped: %s", e)
                return False  # payload dropped; victim keeps decoding
            except ValueError:
                continue  # stream raced retirement / already spilled
            except Exception:
                log.exception("export of victim %d failed", sid)
                return False
            try:
                self.engine.finish(sid)  # frees the slot + pages
                with self._cond:
                    self._by_sid.pop(sid, None)
                    self._spilled.append(
                        {"sess": sess, "t": time.monotonic()})
                self._spill.spill_commit(claim, payload)
            except Exception:
                self._spill.spill_abort(claim)
                raise
            self._n_preempt += 1
            PREEMPTIONS.inc()
            if sess.reqtrace is not None:
                sess.reqtrace.add_span("serve.preempt", time.time(), 0.0,
                                       request=sess.id)
            log.info("preempted stream %d (%s/%s) for a higher-class "
                     "arrival", sid, sess.cls, sess.tenant)
            return True
        return False

    def _sweep_spilled(self) -> None:
        """Spilled victims still own a deadline and a client: close out
        the ones that cancelled or expired while parked, and drop their
        payloads (they will never resume here)."""
        if self._spill is None:
            return
        with self._cond:
            ents = list(self._spilled)
        if not ents:
            return
        now = time.perf_counter()
        for ent in ents:
            sess = ent["sess"]
            reason = None
            if sess.cancelled.is_set():
                _session.CANCELLED.inc()
                reason = "cancelled"
            elif sess.deadline is not None and now > sess.deadline:
                _session.TIMEOUTS.inc()
                reason = "timeout"
            if reason is None:
                continue
            self._spill.discard(sess.id)
            with self._cond:
                if ent in self._spilled:
                    self._spilled.remove(ent)
            sess.finish(reason)

    def _deliver(self, row) -> None:
        """Fan one emitted row out to its sessions' event queues. A
        handoff session (prefill role: the gateway asked for the KV to
        ship elsewhere) gets NO token events — its first token is the
        export trigger, and every token it has rides the snapshot to be
        replayed by the decode replica's resume."""
        n = 0
        handoffs: list[tuple[int, Session, object]] = []
        with self._cond:
            # _by_sid is written only on this (engine) thread; the locked
            # snapshot keeps the _GUARDED_BY annotation honest and stays
            # correct if a second writer ever appears
            by_sid = dict(self._by_sid)
        for slot, tok in enumerate(row):
            if tok is None:
                continue
            stream = self.engine.streams[slot]
            sess = by_sid.get(stream.stream_id)
            if sess is None:
                continue  # priming/dummy slot, or already aborted
            if sess.handoff is not None:
                handoffs.append((stream.stream_id, sess, tok))
                continue
            sess.on_token(tok.id, tok.text,
                          logprobs=getattr(tok, "logprobs", None))
            self._tenants.add(sess.tenant)
            n += 1
            if tok.is_end_of_stream:
                # the engine records WHY it ended the stream ("eos" |
                # "length" | "constraint"); the eos_ids fallback covers
                # engines that only flag the end
                sess.finish_reason = (
                    getattr(stream, "end_reason", None)
                    or ("eos" if tok.id in self.engine.eos_ids
                        else "length")
                )
        for sid, sess, tok in handoffs:
            self._handoff_one(sid, sess, tok)
        if n:
            self._rate_tokens += n
            dt = time.perf_counter() - self._rate_t0
            if dt >= 0.5:
                # sliding half-life blend: recent throughput dominates
                inst = self._rate_tokens / dt
                self._tok_s = inst if self._tok_s == 0 else (
                    0.5 * self._tok_s + 0.5 * inst)
                self._rate_tokens = 0
                self._rate_t0 = time.perf_counter()

    def _handoff_one(self, sid: int, sess: Session, tok) -> None:
        """Export + retire a prefilled stream at its first token; the
        snapshot payload rides the session's event queue to the handler
        thread, which ships it over the transfer channel (the slow part
        — retry/backoff against the decode replica — must never run on
        the engine thread)."""
        if tok.is_end_of_stream:
            # nothing to hand off: the stream completed AT its first
            # token (EOS / window / grammar dead end). 409 tells the
            # gateway to re-prefill elsewhere — rare, and the plain
            # path reproduces the 1-token stream deterministically.
            self.engine.finish(sid)
            with self._cond:
                self._by_sid.pop(sid, None)
            sess.fail(409, "stream completed during prefill; re-prefill")
            return
        ctx = sess.reqtrace
        try:
            if ctx is not None:
                # inside the span so the snapshot's wire-trace parent is
                # the export span itself — the decode tier's
                # disagg.import then hangs under it in the merged tree
                with ctx.span("disagg.export", request=sess.id):
                    payload = self.engine.export_stream(
                        sid, codec=self.transfer_codec, trace=ctx.wire())
            else:
                payload = self.engine.export_stream(
                    sid, codec=self.transfer_codec)
        except Exception as e:
            log.exception("export of stream %d failed", sid)
            self.engine.finish(sid)
            with self._cond:
                self._by_sid.pop(sid, None)
            sess.fail(500, f"kv export failed: {e}")
            return
        self.engine.finish(sid)
        with self._cond:
            self._by_sid.pop(sid, None)
        sess.handoff_ready(payload)

    def _migrate_all(self) -> bool:
        """Engine thread: drain-migrate every admitted session to the
        sibling named by migrate_out (ISSUE 19 rolling restarts). Each
        live stream's KV exports via the disagg snapshot path when the
        engine supports it; the payload (or None — the sibling re-runs
        the whole request) rides the session's event queue to the
        handler thread, which ships it and splices the sibling's stream
        onto the client connection (serve/api._migrate_relay). Returns
        True when a migration pass ran — the run loop then skips the
        engine step, since the slot set just went empty."""
        with self._cond:
            target = self._migrate_to
            if target is None:
                return False
            self._migrate_to = None
        # finished/cancelled sessions close out normally first (tail
        # flush, counters) so only live streams ride the migration
        self._retire()
        with self._cond:
            items = list(self._by_sid.items())
        exportable = self.can_migrate()
        for sid, sess in items:
            payload = None
            # handoff sessions re-run their prefill+handoff on the
            # sibling from the original body; no snapshot to carry
            if exportable and sess.handoff is None:
                try:
                    payload = self.engine.export_stream(
                        sid, codec=self.transfer_codec)
                except Exception:
                    log.exception("drain export of stream %d failed; "
                                  "sibling re-runs the request", sid)
                    payload = None
            self.engine.finish(sid)
            with self._cond:
                self._by_sid.pop(sid, None)
            sess.migrate_ready(payload, target)
            MIGRATED.inc()
        # spilled victims migrate too: their snapshot is already in host
        # RAM, so it rides the same path without touching the engine
        with self._cond:
            spilled, self._spilled = self._spilled, []
        for ent in spilled:
            sess = ent["sess"]
            payload = self._spill.take(sess.id) if self._spill else None
            sess.migrate_ready(payload, target)
            MIGRATED.inc()
        return True

    def _slot_of(self, sid: int) -> int | None:
        for i, s in enumerate(self.engine.streams):
            if s.stream_id == sid:
                return i
        return None

    def _retire(self) -> None:
        """Close out sessions that ended this pass: engine EOS/window,
        token budget, client disconnect, deadline. ``finish(stream_id)``
        is the slot/KV free; the detok tail is flushed into the terminal
        event so streamed text matches the full decode."""
        now = time.perf_counter()
        with self._cond:
            items = list(self._by_sid.items())
        for sid, sess in items:
            reason = None
            if sess.finish_reason in ("eos", "stop", "length", "constraint"):
                reason = sess.finish_reason
            elif sess.stop_hit:
                reason = "stop"  # server-side stop string matched
            elif len(sess.generated) >= sess.max_tokens:
                reason = "length"
            elif sess.cancelled.is_set():
                reason = "cancelled"
            elif sess.deadline is not None and now > sess.deadline:
                reason = "timeout"
            if reason is None:
                continue
            self.engine.finish(sid)
            slot = self._slot_of(sid)
            tail = None
            if slot is not None:
                detok = self.engine.streams[slot].detok
                if detok is not None and reason != "cancelled":
                    tail = detok.decode_rest()
            if reason == "cancelled":
                _session.CANCELLED.inc()
            elif reason == "timeout":
                _session.TIMEOUTS.inc()
            sess.finish(reason, tail_text=tail)
            with self._cond:
                self._by_sid.pop(sid, None)

    def _abort_all(self, message: str) -> None:
        with self._cond:
            queued = list(self._queue)
            self._queue.clear()
            running = list(self._by_sid.values())
            self._by_sid.clear()
            spilled = [ent["sess"] for ent in self._spilled]
            self._spilled.clear()
            _session.QUEUE_DEPTH.set(0)
        for s in spilled:
            if self._spill is not None:
                self._spill.discard(s.id)
        for s in queued + running + spilled:
            if s.finish_reason is None:
                s.fail(503, message)
