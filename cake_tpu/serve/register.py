"""Replica-side fleet self-registration (ISSUE 19).

A serve replica started with ``--register-with URL`` announces itself to
the gateway (``POST /v1/fleet/register`` carrying its serving address,
role, and transfer port) and keeps the resulting lease alive from a
small heartbeat thread. The gateway answers each registration with the
lease TTL *and* the heartbeat cadence it wants (``heartbeat_s``, TTL/3)
— the replica obeys the server, so retuning ``--lease-ttl`` on the
gateway retunes the whole fleet without touching replica flags.

Membership semantics live in ``gateway/health.py`` (registration is a
lease; a missed renewal demotes through the probe hysteresis, never
instantly deletes). This module is deliberately dumb: register, renew,
and — on shutdown — deregister FIRST, so the gateway stops routing
before the replica's 503s start (the SIGTERM satellite: without the
explicit deregister, a probe-interval-wide race window can route a
request into a dying replica).

Failures are soft everywhere: a gateway that is down, restarting, or
not yet started never prevents the replica from serving. Registration
simply retries on the next heartbeat — which is also exactly how the
fleet re-forms after a gateway restart with an empty ``--backends``.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.request

log = logging.getLogger("cake_tpu.serve.register")

# Thread domain (cakelint CK-THREAD): the heartbeat loop runs on its own
# daemon thread; every attribute it shares with the caller is either
# write-once-before-start or an Event/lock.
_THREAD_DOMAIN = "register"

# fallback cadence until the gateway tells us its heartbeat_s
_DEFAULT_HEARTBEAT_S = 3.0


class Registrar:
    """Keeps one replica's registration lease alive against a gateway.

    ``gateway`` is the base URL (``http://host:port``); ``addr`` is the
    serving address the gateway should route to (``host:port``).
    """

    _GUARDED_BY = {"_heartbeat_s": "_lock"}

    def __init__(self, gateway: str, addr: str, role: str | None = None,
                 transfer_port: int = 0,
                 heartbeat_s: float = _DEFAULT_HEARTBEAT_S):
        self.gateway = gateway.rstrip("/")
        self.addr = addr
        self.role = role
        self.transfer_port = int(transfer_port)
        self._lock = threading.Lock()
        self._heartbeat_s = max(0.2, float(heartbeat_s))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="cake-fleet-register")

    # -- wire ----------------------------------------------------------------
    def _post(self, path: str, body: dict, timeout_s: float = 2.0) -> dict:
        req = urllib.request.Request(
            self.gateway + path,
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read() or b"{}")

    def register_once(self) -> bool:
        """One registration/renewal POST. Returns True when the gateway
        acknowledged; False (logged at debug — this is the normal state
        while a gateway restarts) on any failure."""
        body: dict = {"addr": self.addr}
        if self.role:
            body["role"] = self.role
        if self.transfer_port:
            body["transfer_port"] = self.transfer_port
        try:
            ack = self._post("/v1/fleet/register", body)
        except (urllib.error.URLError, OSError, ValueError) as e:
            log.debug("fleet register against %s failed: %s",
                      self.gateway, e)
            return False
        hb = ack.get("heartbeat_s")
        if isinstance(hb, (int, float)) and hb > 0:
            with self._lock:
                self._heartbeat_s = max(0.2, float(hb))
        return bool(ack.get("ok"))

    def deregister(self) -> bool:
        """Stop the heartbeat, then tell the gateway to stop routing
        here — in that order, so a heartbeat can't re-acquire the lease
        after the goodbye. Called BEFORE the server starts failing
        probes (the SIGTERM drain path)."""
        self._stop.set()
        try:
            self._post("/v1/fleet/deregister", {"addr": self.addr})
            return True
        except (urllib.error.URLError, OSError, ValueError) as e:
            # gateway gone: nothing routes here anyway
            log.debug("fleet deregister against %s failed: %s",
                      self.gateway, e)
            return False

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Registrar":
        """Register now (best effort) and start the renewal thread."""
        self.register_once()
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop renewing without deregistering (the lease just expires);
        deregister() is the graceful variant."""
        self._stop.set()

    def _run(self) -> None:
        while True:
            with self._lock:
                hb = self._heartbeat_s
            if self._stop.wait(timeout=hb):
                return
            self.register_once()
