"""Request-serving plane: HTTP API + SLO-aware scheduling over the engine.

The reference is strictly single-request and in-process; the engine here
(``runtime.batch_generator.BatchGenerator``) already out-builds it —
continuous batching, shared-prefix reuse, adaptive decode blocks,
lookahead dispatch, batched speculation — but an engine only becomes a
*service* with a serving front end (the Orca / vLLM lesson: request
queueing, admission, streaming, cancellation are their own subsystem).
That front end is this package, stdlib-only:

- :mod:`cake_tpu.serve.session` — per-request state: prompt intake
  (text or ``prompt_ids``), SSE framing, TTFT/TPOT measurement feeding
  the ``serve.*`` registry series and flight records.
- :mod:`cake_tpu.serve.scheduler` — the single engine-owner thread:
  bounded FIFO admission with deadlines, token fan-out to per-request
  queues, retirement on EOS / ``max_tokens`` / disconnect / deadline,
  429-style backpressure with an observed-throughput Retry-After.
- :mod:`cake_tpu.serve.engine` — one-slot BatchGenerator facade over the
  single-stream generators, so serving also runs over the cross-host
  ``--topology`` path.
- :mod:`cake_tpu.serve.api` — threaded HTTP server: ``POST
  /v1/completions`` (JSON or SSE), ``GET /v1/models``, ``GET /healthz``,
  plus the mounted ``/`` + ``/metrics`` statusd surface.

CLI surface: ``--mode serve --serve-port/--serve-bind --max-concurrent
--queue-depth --request-timeout``; ``python -m cake_tpu.tools.loadgen``
drives it. See README "Serving over HTTP".
"""

from cake_tpu.serve.api import ApiServer, start_api_server  # noqa: F401
from cake_tpu.serve.engine import SingleStreamEngine  # noqa: F401
from cake_tpu.serve.scheduler import (  # noqa: F401
    Draining,
    QueueFull,
    Scheduler,
)
from cake_tpu.serve.session import Session  # noqa: F401
