"""The gateway front door: one port, many serve replicas behind it.

A threaded HTTP server (the ``serve/api.py`` shape, stdlib-only) that
proxies the serving API across a fleet of ``--mode serve`` replicas:

- ``POST /v1/completions`` — routed by the configured policy
  (``gateway/policy.py``) to one UP backend. Unary responses relay whole;
  ``stream: true`` responses pass through as raw SSE bytes chunk by chunk
  (bit-identical to a direct connection — the gateway never reframes). A
  connect failure or 5xx **before the first SSE byte** is retried
  transparently on another backend (the client never learns); once a byte
  has been forwarded the stream is committed and a mid-flight death
  truncates it honestly. A 429 marks the backend saturated and tries the
  next one — the client sees 429 (with the backend's ``Retry-After``)
  only when EVERY routable backend refused.
- ``GET /v1/models`` — relayed from any UP backend (replicas serve the
  same model by contract).
- ``GET /healthz`` — the gateway's own probe surface: 200 while at least
  one backend is routable and the gateway is not draining, 503 otherwise,
  body carrying the per-backend state map (so a gateway can itself sit
  behind another gateway or an external balancer).
- ``GET /`` + ``GET /metrics`` — the shared ``obs/statusd`` status
  surface: fleet state JSON and the process registry (all ``gateway.*``
  series) in Prometheus text.
- ``POST /v1/fleet/register`` / ``/v1/fleet/deregister`` — the dynamic
  membership plane (ISSUE 19): serve replicas self-announce and lease
  their membership (``health.HealthMonitor.register``), and the SIGTERM
  drain path deregisters explicitly before any 503 is served.
- ``POST /v1/fleet/drain/<backend>`` — operator-initiated rolling
  restart: pin the backend DRAINING, pick a sibling, relay the drain
  order; the replica migrates its in-flight streams to the sibling over
  the KV-transfer plane and exits clean.

Graceful drain mirrors serve: ``drain()`` (the SIGTERM path) stops
admitting (503), waits for in-flight proxied requests — streams included
— to finish, then closes the listener.
"""

from __future__ import annotations

import http.client
import http.server
import json
import logging
import threading
import time

from cake_tpu.gateway import policy as policy_mod
from cake_tpu.gateway.health import Backend, HealthMonitor
from cake_tpu.obs import flight as obs_flight
from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.obs import reqtrace as obs_reqtrace
from cake_tpu.obs import statusd as _statusd
from cake_tpu.obs import trace as obs_trace

log = logging.getLogger("cake_tpu.gateway.api")

# Thread domain (cakelint CK-THREAD): module code — the nested Handler
# class included — runs on the gateway's HTTP handler threads. The
# gateway never holds engine-domain objects; backends are reached over
# HTTP and all shared state is "any"-domain (internally locked).
_THREAD_DOMAIN = "handler"

REQUESTS = obs_metrics.counter("gateway.requests")
RETRIES = obs_metrics.counter("gateway.retries")
REJECTED = obs_metrics.counter("gateway.rejected")
SATURATED = obs_metrics.counter("gateway.saturated")
ADDED_MS = obs_metrics.histogram("gateway.added_ms")
# fleet-saturation admission control (ISSUE 19): requests shed at the
# door when every routable backend refused, and requests that rode the
# bounded admission queue instead of eating an instant 429
SHED = obs_metrics.counter("gateway.shed")
QUEUED_ADMISSIONS = obs_metrics.counter("gateway.queued_admissions")
# disagg two-stage routing (cake_tpu/disagg): tiered routes that went
# prefill -> transfer -> decode resume end-to-end, and fallbacks that
# re-prefilled the request on the classic path after a tiered-path
# failure (transfer lost, import expired, decode replica gone)
HANDOFFS = obs_metrics.counter("disagg.handoffs")
REPREFILLS = obs_metrics.counter("disagg.reprefills")

_HOP_HEADERS = ("Content-Type", "Cache-Control", "Retry-After")


class _Attempt:
    """One backend attempt: connection + response, closed as a unit."""

    def __init__(self, backend: Backend, connect_timeout: float,
                 read_timeout: float):
        self.backend = backend
        self.conn = http.client.HTTPConnection(
            backend.host, backend.port, timeout=connect_timeout)
        self.read_timeout = read_timeout
        self.resp: http.client.HTTPResponse | None = None
        self.t_sent: float | None = None

    def send(self, method: str, path: str, body: bytes | None = None,
             headers: dict | None = None):
        """Connect (short timeout), widen to the stream timeout, send,
        and read the response head. Raises ``OSError`` on any transport
        failure — the retry loop's cue. ``t_sent`` is stamped the moment
        the request is fully handed to the backend, BEFORE the response
        wait: everything up to it is gateway-added latency, everything
        after it is the backend working."""
        self.conn.connect()
        self.conn.sock.settimeout(self.read_timeout)
        hdrs = dict(headers or {})
        if body is not None:
            hdrs.update({"Content-Type": "application/json",
                         "Content-Length": str(len(body))})
        self.conn.request(method, path, body=body, headers=hdrs)
        self.t_sent = time.perf_counter()
        self.resp = self.conn.getresponse()
        return self.resp

    def close(self) -> None:
        try:
            self.conn.close()
        except OSError:
            pass


class _FleetHTTPServer(http.server.ThreadingHTTPServer):
    # The front door takes whole-fleet thundering herds by design: a
    # registration storm (every replica re-announcing after a gateway
    # restart) plus client retries all connect at once. The stdlib's
    # 5-connection listen backlog resets the overflow before a handler
    # thread ever sees it.
    request_queue_size = 128


class GatewayServer:
    """The routing front door; ``start_gateway`` is the entry point."""

    # cakelint CK-THREAD: the gateway holds no engine-domain state —
    # in-flight accounting is condition-locked (CK-LOCK below) and
    # every backend touch goes through the "any"-domain health plane
    _THREAD_DOMAIN = "any"

    # in-flight accounting shared between handler threads and drain()
    _GUARDED_BY = {"_inflight": "_cond", "_draining": "_cond"}

    def __init__(self, monitor: HealthMonitor, policy,
                 bind: str = "127.0.0.1", port: int = 0,
                 prefix_block: int = 64, connect_timeout: float = 2.0,
                 read_timeout: float = 300.0, status_fn=None,
                 slo: obs_reqtrace.SloTracker | None = None,
                 admit_wait_s: float = 0.5, admit_queue: int = 32):
        self.monitor = monitor
        self.policy = policy
        # admission control under fleet saturation: how long an
        # interactive request may wait for a slot to free (0 = always
        # shed), and how many may wait at once (past that, shed even
        # interactive traffic — a bounded queue, not a buffer bloat)
        self.admit_wait_s = max(0.0, admit_wait_s)
        self._admit_sem = threading.Semaphore(max(1, admit_queue))
        # SLO accounting at the front door (--slo-ttft-ms/--slo-tpot-ms):
        # the gateway judges end-to-end latency AS THE CLIENT SEES IT —
        # routing, retries, and tiered hops included (obs/reqtrace)
        self.slo = slo
        # one source of truth for the affinity alignment: a Prefix policy
        # carries its own block, and the key MUST be computed at that
        # block for the policy's hashing to group what it means to group;
        # the server-level knob only covers policies without one
        self.prefix_block = max(1, getattr(policy, "block", None)
                                or prefix_block)
        self.connect_timeout = connect_timeout
        self.read_timeout = read_timeout
        self._cond = threading.Condition()
        self._inflight = 0
        self._draining = False
        if status_fn is None:
            def status_fn():
                return {"role": "gateway",
                        "policy": getattr(policy, "name", "?"),
                        "backends": monitor.describe(),
                        "metrics": obs_metrics.registry().snapshot()}
        self.status_fn = status_fn
        handler = _make_handler(self)
        self.httpd = _FleetHTTPServer((bind, port), handler)
        self.port = self.httpd.server_address[1]
        self.bind = bind
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True,
                                        name="cake-gateway-http")

    def start(self) -> "GatewayServer":
        self._thread.start()
        return self

    # -- drain bookkeeping ----------------------------------------------------
    def _enter(self) -> bool:
        with self._cond:
            if self._draining:
                return False
            self._inflight += 1
            return True

    def _exit(self) -> None:
        with self._cond:
            self._inflight -= 1
            self._cond.notify_all()

    def is_draining(self) -> bool:
        with self._cond:
            return self._draining

    # -- admission queue (CK-CLAIM gateway.admit: enter pairs with exit) ------
    def _admit_enter(self):
        """Claim one bounded admission-queue slot; the token (or None
        when the queue is full) MUST go back through :meth:`_admit_exit`
        in a finally."""
        return self._admit_sem if self._admit_sem.acquire(
            blocking=False) else None

    def _admit_exit(self, token) -> None:
        if token is not None:
            token.release()

    def drain(self, timeout_s: float = 30.0) -> None:
        """SIGTERM path: stop admitting (503), let in-flight proxied
        requests — streams included — run out (bounded), then close the
        listener. Teardown runs even if the wait is interrupted."""
        try:
            with self._cond:
                self._draining = True
                deadline = time.monotonic() + timeout_s
                while self._inflight > 0:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        log.warning("drain timed out with %d request(s) "
                                    "in flight", self._inflight)
                        break
                    self._cond.wait(left)
        finally:
            self.close()

    def close(self) -> None:
        try:
            self.httpd.shutdown()
        finally:
            self.httpd.server_close()


def start_gateway(monitor: HealthMonitor, policy, bind: str = "127.0.0.1",
                  port: int = 0, **kw) -> GatewayServer:
    """Build + start a :class:`GatewayServer` (``port=0`` ephemeral)."""
    return GatewayServer(monitor, policy, bind=bind, port=port,
                         **kw).start()


def _make_handler(server: GatewayServer):
    monitor = server.monitor

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            log.debug("gateway: " + fmt, *args)

        # -- reply helpers ------------------------------------------------
        def _send_raw(self, status: int, body: bytes,
                      headers: dict | None = None) -> None:
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _json(self, status: int, obj: dict,
                  headers: dict | None = None) -> None:
            self._send_raw(status, json.dumps(obj, indent=1).encode(),
                           headers)

        def _error(self, status: int, message: str,
                   headers: dict | None = None) -> None:
            self._json(status, {"error": message}, headers)

        # -- request-scoped tracing helpers -------------------------------
        _ctx: obs_reqtrace.ReqTrace | None = None

        def _trace_headers(self) -> dict:
            """Outbound traceparent for a backend hop (the live span —
            gateway.route or gateway.retry — becomes the parent)."""
            return ({obs_reqtrace.HEADER: self._ctx.header()}
                    if self._ctx is not None else {})

        def _finish_request(self) -> None:
            """Close out one proxied request: SLO verdict on the
            end-to-end latency the client saw, the gateway.request
            flight record, the request-log entry behind
            ``GET /v1/requests/<id>``, and — when this process is
            tracing — the remote tiers' timelines stitched into the
            local tracer so one ``--trace`` file shows the whole fleet."""
            ctx, rs = self._ctx, self._rstat
            if ctx is None:
                return
            self._ctx = None
            ttft_ms = ((rs["t_first"] - rs["t0"]) * 1e3
                       if rs["t_first"] is not None else None)
            tpot_ms = None
            if rs["tokens"] > 1 and rs["t_last"] is not None \
                    and rs["t_last"] > rs["t_first"]:
                tpot_ms = ((rs["t_last"] - rs["t_first"]) * 1e3
                           / (rs["tokens"] - 1))
            verdict = None
            if server.slo is not None and rs["ok"]:
                verdict = server.slo.observe(ttft_ms, tpot_ms)
                ctx.slo = verdict
            if obs_trace.tracer().enabled:
                self._stitch_backends(ctx)
            obs_reqtrace.request_log().put(ctx)
            rec = obs_flight.recorder()
            if rec.enabled:
                rec.record(kind="gateway.request", trace=ctx.trace_id,
                           ok=rs["ok"], tokens=rs["tokens"],
                           ttft_ms=round(ttft_ms, 3)
                           if ttft_ms is not None else None,
                           tpot_ms=round(tpot_ms, 3)
                           if tpot_ms is not None else None,
                           backends=",".join(
                               b.name for b in rs["backends"]),
                           slo_good=verdict["good"] if verdict else None)

        def _stitch_backends(self, ctx) -> None:
            """Pull each touched backend's span timeline for this trace
            (its /v1/requests debug endpoint) and land the spans on the
            local tracer under per-backend tracks — best-effort: a
            backend without the endpoint, or with the entry evicted,
            just contributes nothing."""
            seen = set()
            for b in self._rstat["backends"]:
                if b.addr in seen:
                    continue
                seen.add(b.addr)
                conn = http.client.HTTPConnection(
                    b.host, b.port, timeout=server.connect_timeout)
                try:
                    conn.request("GET",
                                 f"/v1/requests/{ctx.trace_id}")
                    resp = conn.getresponse()
                    if resp.status != 200:
                        continue
                    tl = json.loads(resp.read())
                except (OSError, ValueError):
                    continue
                finally:
                    conn.close()
                obs_reqtrace.stitch_timeline(tl, f"{b.name}@{b.addr}")

        def _fleet_timeline(self, key: str) -> dict | None:
            """One request's fleet-wide span timeline: the gateway's own
            entry (gateway.route/retry + the client-view SLO verdict)
            merged with every routable backend's ``/v1/requests`` answer,
            deduped by span id — so the debug endpoint shows the same
            connected tree on the gateway as a stitched trace file does.
            Best-effort per backend; None only when NOBODY knows the id."""
            merged: dict = {"trace_id": None}
            seen: set = set()
            spans: list = []

            def absorb(tl: dict | None) -> None:
                if not tl:
                    return
                merged["trace_id"] = merged["trace_id"] or tl.get(
                    "trace_id")
                for k in ("request_id", "slo"):
                    if tl.get(k) is not None and k not in merged:
                        merged[k] = tl[k]
                for s in tl.get("spans") or []:
                    if s.get("span") not in seen:
                        seen.add(s.get("span"))
                        spans.append(s)

            absorb(obs_reqtrace.request_log().get(key))
            for b in {b.addr: b for b in monitor.routable()}.values():
                conn = http.client.HTTPConnection(
                    b.host, b.port, timeout=server.connect_timeout)
                try:
                    conn.request("GET", f"/v1/requests/{key}")
                    resp = conn.getresponse()
                    if resp.status == 200:
                        absorb(json.loads(resp.read()))
                except (OSError, ValueError):
                    pass
                finally:
                    conn.close()
            if not spans:
                return None
            merged["spans"] = sorted(spans, key=lambda s: s["t"])
            return merged

        def _fleet_prof(self) -> dict:
            """The fleet's engine-profiling view: every routable
            backend's ``/debug/prof`` body keyed by backend name, plus a
            ``fleet`` rollup (compile/retrace sums and per-phase merged
            count/p99 — the numbers a capacity question actually needs).
            Best-effort per backend, same contract as the timeline
            endpoint: an older replica without the route contributes
            nothing."""
            from cake_tpu.obs import prof as obs_prof

            backends: dict = {}
            fleet: dict = {"compiles": 0, "retraces": 0, "phases": {}}

            def absorb(name: str, rep: dict | None) -> None:
                if not isinstance(rep, dict):
                    return
                backends[name] = rep
                fleet["compiles"] += int(rep.get("compiles") or 0)
                fleet["retraces"] += int(rep.get("retraces") or 0)
                for ph, snap in (rep.get("phases") or {}).items():
                    agg = fleet["phases"].setdefault(
                        ph, {"count": 0, "p99_max_ms": 0.0})
                    agg["count"] += int(snap.get("count") or 0)
                    agg["p99_max_ms"] = max(agg["p99_max_ms"],
                                            float(snap.get("p99") or 0.0))

            absorb("gateway", obs_prof.report())
            for b in {b.addr: b for b in monitor.routable()}.values():
                conn = http.client.HTTPConnection(
                    b.host, b.port, timeout=server.connect_timeout)
                try:
                    conn.request("GET", "/debug/prof")
                    resp = conn.getresponse()
                    if resp.status == 200:
                        absorb(f"{b.name}@{b.addr}",
                               json.loads(resp.read()))
                except (OSError, ValueError):
                    pass
                finally:
                    conn.close()
            return {"backends": backends, "fleet": fleet}

        def _relay(self, resp, data: bytes) -> None:
            """One whole (non-streaming) backend response to the client,
            status and relevant headers preserved."""
            self.send_response(resp.status)
            for h in _HOP_HEADERS:
                v = resp.getheader(h)
                if v is not None:
                    self.send_header(h, v)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        # -- GET: health, discovery, status surface -----------------------
        def do_GET(self):  # noqa: N802 (stdlib casing)
            path = self.path.rstrip("/") or "/"
            if path == "/healthz":
                ups = monitor.routable()
                draining = server.is_draining()
                ok = bool(ups) and not draining
                tiers: dict[str, int] = {}
                for b in ups:
                    tiers[b.role] = tiers.get(b.role, 0) + 1
                now = time.monotonic()
                body = {
                    "ok": ok,
                    "draining": draining,
                    "backends_up": len(ups),
                    # the tier map: two-stage routing engages while both
                    # "prefill" and "decode" are nonzero here
                    "tiers": tiers,
                    # per-backend row: state + membership staleness
                    # (registered_via, probe age, lease expiry) so --top
                    # and operators read fleet health at a glance
                    "backends": {b.name: b.health_entry(now)
                                 for b in monitor.backends},
                }
                if server.slo is not None:
                    body["slo"] = server.slo.snapshot()
                self._json(200 if ok else 503, body)
            elif path == "/v1/models":
                self._proxy_get("/v1/models")
            elif path.startswith("/v1/requests/"):
                key = path[len("/v1/requests/"):]
                tl = self._fleet_timeline(key)
                if tl is None:
                    self._error(404, f"unknown request {key}")
                else:
                    self._json(200, tl)
            elif path == "/debug/prof":
                # fleet-merged engine profiling plane (the per-replica
                # body lives on each backend's own /debug/prof)
                self._json(200, self._fleet_prof())
            elif path in ("/", "/metrics"):
                body, ctype = _statusd.status_response(server.status_fn,
                                                       path)
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._error(404, f"no route for GET {self.path}")

        def _proxy_get(self, path: str) -> None:
            """Relay a small GET from any UP backend (retrying across the
            fleet; replicas answer identically by contract)."""
            tried: list = []
            while True:
                cands = [b for b in monitor.routable() if b not in tried]
                if not cands:
                    self._error(502, "no backend available")
                    return
                b = server.policy.choose(cands, now=time.monotonic())
                tried.append(b)
                att = _Attempt(b, server.connect_timeout,
                               server.connect_timeout)
                try:
                    resp = att.send("GET", path)
                    data = resp.read()
                except OSError:
                    monitor.report_failure(b)
                    continue
                finally:
                    att.close()
                self._relay(resp, data)
                return

        # -- POST: routed completions + the fleet membership plane --------
        def do_POST(self):  # noqa: N802 (stdlib casing)
            path = self.path.rstrip("/")
            if path == "/v1/fleet/register":
                self._fleet_register()
                return
            if path == "/v1/fleet/deregister":
                self._fleet_deregister()
                return
            if path.startswith("/v1/fleet/drain/"):
                self._fleet_drain(path[len("/v1/fleet/drain/"):])
                return
            if path != "/v1/completions":
                self._error(404, f"no route for POST {self.path}")
                return
            if not server._enter():
                # refused at the door: rejected only — gateway.requests
                # counts ACCEPTED requests (the catalog's contract)
                REJECTED.inc()
                self._error(503, "gateway is draining")
                return
            REQUESTS.inc()
            # request-scoped trace context (obs/reqtrace): honor the
            # client's traceparent or mint one; every backend hop below
            # re-propagates it, so the whole fleet shares one trace id
            ctx = obs_reqtrace.ReqTrace.from_header(
                self.headers.get(obs_reqtrace.HEADER))
            self._ctx = ctx
            self._rstat = {"t0": time.perf_counter(), "t_first": None,
                           "t_last": None, "tokens": 0, "ok": False,
                           "backends": []}
            try:
                with ctx.span("gateway.route",
                              policy=getattr(server.policy, "name", "?")):
                    self._proxy_completions()
            finally:
                server._exit()
                self._finish_request()

        # -- fleet membership endpoints (ISSUE 19) ------------------------
        def _read_json(self) -> dict | None:
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, OSError):
                return None
            return body if isinstance(body, dict) else None

        def _fleet_register(self) -> None:
            """A serve replica announcing itself: create-or-renew its
            membership lease (idempotent — a registration storm updates
            one entry in place). The answer tells the replica its lease
            TTL and the heartbeat cadence that keeps it alive."""
            body = self._read_json()
            if body is None:
                self._error(400, "register wants a JSON object body")
                return
            addr = body.get("addr")
            if not isinstance(addr, str) or not addr:
                # a replica that only knows its port: pair it with the
                # peer address this registration arrived from
                port = body.get("port")
                addr = (f"{self.client_address[0]}:{port}"
                        if port else None)
            if not addr:
                self._error(400,
                            "register wants addr (host:port) or port")
                return
            try:
                b = monitor.register(
                    addr, role=body.get("role"),
                    transfer_port=int(body.get("transfer_port", 0) or 0))
            except ValueError as e:
                self._error(400, str(e))
                return
            self._json(200, {
                "ok": True, "name": b.name, "state": b.state,
                "lease_ttl_s": monitor.lease_ttl_s,
                # renew comfortably inside the TTL: one lost beat plus
                # jitter must not expire the lease
                "heartbeat_s": round(max(0.2,
                                         monitor.lease_ttl_s / 3), 3),
            })

        def _fleet_deregister(self) -> None:
            """Explicit leave (the replica's SIGTERM sends this BEFORE
            its /healthz starts answering 503): pin the member DRAINING
            so not one request routes into the exit. Idempotent — a
            stale or repeated deregister is a harmless no-op."""
            body = self._read_json()
            key = (body or {}).get("addr") or (body or {}).get("name")
            if not isinstance(key, str) or not key:
                self._error(400, "deregister wants addr or name")
                return
            b = monitor.deregister(key)
            self._json(200, {"ok": True, "known": b is not None,
                             **({"name": b.name} if b else {})})

        def _fleet_drain(self, key: str) -> None:
            """Operator-initiated rolling restart of one backend: pin it
            DRAINING here first (new sessions re-home immediately), pick
            a migration sibling from the same tier, then relay the drain
            order — the replica migrates its in-flight decode streams to
            the sibling over the KV-transfer plane and exits clean."""
            b = monitor.lookup(key)
            if b is None:
                self._error(404, f"unknown backend {key!r}")
                return
            monitor.deregister(b.addr)
            sibs = [x for x in monitor.routable()
                    if x.addr != b.addr and x.role != "prefill"
                    and x.transfer_addr()]
            sib = next((x for x in sibs if x.role == b.role),
                       sibs[0] if sibs else None)
            payload: dict = {}
            if sib is not None:
                payload["migrate_to"] = {"addr": sib.addr,
                                         "transfer": sib.transfer_addr()}
            att = _Attempt(b, server.connect_timeout,
                           server.read_timeout)
            try:
                try:
                    resp = att.send("POST", "/v1/fleet/drain",
                                    json.dumps(payload).encode())
                    reply = json.loads(resp.read() or b"{}")
                    status = resp.status
                except (OSError, ValueError) as e:
                    self._json(502, {"ok": False, "backend": b.name,
                                     "error": f"drain relay failed: {e}"})
                    return
            finally:
                att.close()
            self._json(status if status < 500 else 502,
                       {"ok": status == 200, "backend": b.name,
                        "addr": b.addr,
                        "migrate_to": payload.get("migrate_to"),
                        "replica": reply})

        def _admit_wait(self, raw: bytes, t0: float) -> bool:
            """The fleet is saturated: hold this request in the bounded
            admission queue until a backend frees up (True — re-route
            it) or the budget runs out (False — shed). The budget is
            ``admit_wait_s`` capped by the request's own deadline
            headroom; batch-class requests (``"class": "batch"`` in the
            body) never queue — they are the load to shed first."""
            budget = server.admit_wait_s
            if budget <= 0:
                return False
            try:
                body = json.loads(raw or b"{}")
            except ValueError:
                body = None
            if not isinstance(body, dict):
                body = {}
            if str(body.get("class", "interactive")) == "batch":
                return False
            timeout_s = body.get("timeout_s")
            if isinstance(timeout_s, (int, float)) and timeout_s > 0:
                budget = min(budget, max(
                    0.0, timeout_s - (time.perf_counter() - t0)))
            if budget <= 0:
                return False
            tok = None
            try:
                tok = server._admit_enter()
                if tok is None:
                    return False  # queue itself is full: shed
                QUEUED_ADMISSIONS.inc()
                with self._ctx.span("gateway.admit_queue"):
                    deadline = time.monotonic() + budget
                    while time.monotonic() < deadline:
                        if server.is_draining():
                            return False
                        now = time.monotonic()
                        if any(not x.saturated(now)
                               for x in monitor.routable()
                               if x.role != "prefill"):
                            return True
                        time.sleep(0.05)
                    return False
            finally:
                server._admit_exit(tok)

        def _proxy_completions(self) -> None:
            try:
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length)
            except (ValueError, OSError) as e:
                self._error(400, f"bad request body: {e}")
                return
            # the body is parsed ONLY to derive the affinity key, and
            # only the prefix policy reads one — p2c/round_robin must
            # not pay a json.loads of a potentially huge prompt per
            # request on the front door's hot path
            key = None
            if getattr(server.policy, "wants_key", False):
                try:
                    body = json.loads(raw or b"{}")
                except ValueError:
                    body = None  # forward anyway; the backend 400s
                if isinstance(body, dict):
                    key = policy_mod.prefix_key(body,
                                                server.prefix_block)
            # class-aware routing (ISSUE 20): batch traffic drains to
            # the least-loaded replica while interactive keeps the
            # configured policy (prefix affinity's hot-KV wins matter
            # for latency, not throughput). The byte-scan keeps the
            # hot path free of a json.loads unless a class is present;
            # the body itself forwards untouched either way.
            is_batch = False
            if b'"class"' in raw:
                try:
                    cbody = json.loads(raw or b"{}")
                    is_batch = (isinstance(cbody, dict)
                                and cbody.get("class") == "batch")
                except ValueError:
                    pass
            t0 = time.perf_counter()
            # two-stage tiered route (cake_tpu/disagg): when the fleet
            # has both a prefill and a decode tier, prefill runs on one
            # replica and the KV pages ship to another that decodes.
            # Any tiered-path failure falls through to the classic loop
            # below — the transparent re-prefill (the client never
            # learns the tiered attempt happened).
            if self._tiered_completions(raw, t0):
                return
            tried: list = []
            last_429: tuple | None = None
            queued = False
            while True:
                now = time.monotonic()
                # prefill-tier replicas refuse plain completions by
                # contract (serve 400s them loudly); the classic path
                # routes over everything else
                cands = [b for b in monitor.routable()
                         if b not in tried and b.role != "prefill"]
                if not cands:
                    if last_429 is None:
                        REJECTED.inc()
                        self._error(503, "no backend available")
                        return
                    # every routable backend is saturated: admission
                    # control decides — one bounded, deadline-aware
                    # wait in the admission queue (interactive class),
                    # then shed with a Retry-After derived from
                    # fleet-wide tok/s instead of relaying whichever
                    # 429 happened to come last
                    if not queued and self._admit_wait(raw, t0):
                        queued = True
                        tried, last_429 = [], None
                        continue
                    SATURATED.inc()
                    SHED.inc()
                    retry_after = _fleet_retry_after(monitor, raw)
                    self._json(429, {"error": "fleet saturated",
                                     "shed": True,
                                     "retry_after_s": retry_after},
                               {"Retry-After": str(retry_after)})
                    return
                if is_batch:
                    b = policy_mod.pick_batch(cands)
                else:
                    b = server.policy.choose(cands, key=key, now=now,
                                             first_attempt=not tried)
                tried.append(b)
                b.requests.inc()
                if len(tried) > 1:
                    RETRIES.inc()
                    tried[-2].retries.inc()
                    # a transparent re-route gets its own span, nested
                    # under gateway.route — chaos runs read as a retry
                    # chain in the request timeline
                    with self._ctx.span("gateway.retry", backend=b.name,
                                        attempt=len(tried)):
                        outcome = self._try_backend(b, raw, t0)
                else:
                    outcome = self._try_backend(b, raw, t0)
                if outcome == "done":
                    return
                if isinstance(outcome, tuple):  # a 429: remember, go on
                    last_429 = outcome

        def _tiered_completions(self, raw: bytes, t0: float) -> bool:
            """The disagg two-stage route. Returns True when a response
            reached the client; False means "route classically" — a
            tier is empty, the body opted out, or the tiered attempt
            failed somewhere recoverable (the transparent re-prefill:
            the classic path redoes the prefill on a mixed/decode
            replica and the client never learns)."""
            now = time.monotonic()
            routable = monitor.routable()
            prefill_tier = [b for b in routable if b.role == "prefill"]
            decode_tier = [b for b in routable
                           if b.role == "decode" and b.transfer_addr()]
            if not prefill_tier or not decode_tier:
                return False
            try:
                body = json.loads(raw or b"{}")
            except ValueError:
                return False  # malformed: let the backend 400 it
            if not isinstance(body, dict) or "_disagg" in body \
                    or "_resume" in body:
                return False  # the caller drives its own disagg route
            key = policy_mod.prefix_key(body, server.prefix_block)
            dec = policy_mod.pick_decode(decode_tier, key=key, now=now)
            if dec.role != "decode":  # prober raced a role flip: loud
                log.error("decode-tier pick %s (%s) no longer advertises "
                          "role=decode (now %r); refusing the tiered "
                          "route", dec.name, dec.addr, dec.role)
                return False
            pre = policy_mod.pick_prefill(prefill_tier)
            xfer_id = self._handoff(pre, body, dec)
            if xfer_id is None:
                REPREFILLS.inc()
                return False
            rraw = json.dumps(
                dict(body, _resume={"xfer_id": xfer_id})).encode()
            dec.requests.inc()
            # 409 = the decode import is gone (TTL raced, replica
            # restarted): bounce instead of relaying — the classic path
            # re-prefills and the stream is reproduced bit-identically
            outcome = self._try_backend(dec, rraw, t0, bounce=(409,))
            if outcome == "done":
                HANDOFFS.inc()
                return True
            REPREFILLS.inc()
            return False

        def _handoff(self, pre: Backend, body: dict,
                     dec: Backend) -> str | None:
            """Stage 1: ask ``pre`` to prefill and ship the KV pages to
            ``dec``'s transfer channel. Returns the transfer id to
            resume, or None when the tiered path must fall back."""
            praw = json.dumps(
                dict(body, _disagg={"target": dec.transfer_addr()})
            ).encode()
            pre.requests.inc()
            att = _Attempt(pre, server.connect_timeout,
                           server.read_timeout)
            try:
                try:
                    resp = att.send("POST", "/v1/completions", praw,
                                    headers=self._trace_headers())
                    self._rstat["backends"].append(pre)
                    data = resp.read()
                except OSError as e:
                    log.debug("prefill backend %s failed: %s",
                              pre.name, e)
                    pre.errors.inc()
                    monitor.report_failure(pre)
                    return None
            finally:
                att.close()
            if resp.status == 429:
                monitor.report_saturated(
                    pre, _as_seconds(resp.getheader("Retry-After")))
                return None
            if resp.status == 503:
                monitor.report_draining(pre)
                return None
            if resp.status != 200:
                # a 502 is the TRANSFER failing (the prefill replica is
                # alive and answered); 4xx/5xx all mean the same thing
                # here: this route is off, re-prefill classically
                log.debug("handoff via %s answered %d", pre.name,
                          resp.status)
                return None
            try:
                reply = json.loads(data)
            except ValueError:
                return None
            monitor.report_success(pre)
            if not (isinstance(reply, dict) and reply.get("handoff")
                    and isinstance(reply.get("xfer_id"), str)):
                return None
            return reply["xfer_id"]

        def _try_backend(self, b: Backend, raw: bytes, t0: float,
                         bounce: tuple = ()):
            """One routed attempt. Returns ``"done"`` when a response
            (success or deterministic client error) reached the client,
            a ``(body, retry_after)`` tuple on 429, or ``None`` when the
            attempt failed and the retry loop should pick another
            backend. ``bounce``: statuses to swallow and return ``None``
            for instead of relaying (the tiered route's 409 — the caller
            re-prefills; nothing reaches the client)."""
            att = _Attempt(b, server.connect_timeout, server.read_timeout)
            try:
                try:
                    resp = att.send("POST", "/v1/completions", raw,
                                    headers=self._trace_headers())
                    self._rstat["backends"].append(b)
                    t_sent = att.t_sent
                except OSError as e:
                    log.debug("backend %s connect/send failed: %s",
                              b.name, e)
                    b.errors.inc()
                    monitor.report_failure(b)
                    return None
                if resp.status in bounce:
                    log.debug("backend %s bounced with %d", b.name,
                              resp.status)
                    return None
                if resp.status == 429:
                    monitor.report_saturated(
                        b, _as_seconds(resp.getheader("Retry-After")))
                    try:
                        data = resp.read()
                    except OSError:
                        data = b"{}"
                    return (data, resp.getheader("Retry-After"))
                if resp.status == 503:
                    # the replica is draining (or refusing): route around
                    # it and tell the monitor why
                    monitor.report_draining(b)
                    return None
                if resp.status >= 500:
                    b.errors.inc()
                    monitor.report_failure(b)
                    return None
                ctype = resp.getheader("Content-Type", "")
                if ctype.startswith("text/event-stream"):
                    return self._relay_stream(b, resp, t0, t_sent)
                # unary (200 or a deterministic 4xx): relay whole
                try:
                    data = resp.read()
                except OSError:
                    b.errors.inc()
                    monitor.report_failure(b)
                    return None
                if resp.status < 400:
                    ADDED_MS.observe((t_sent - t0) * 1e3)
                    monitor.report_success(b)
                    rs = self._rstat
                    rs["t_first"] = rs["t_last"] = time.perf_counter()
                    rs["ok"] = True
                try:
                    self._relay(resp, data)
                except OSError:
                    pass  # client went away; nothing to unwind
                return "done"
            finally:
                att.close()

        def _relay_stream(self, b: Backend, resp, t0: float,
                          t_sent: float):
            """SSE pass-through. The client's response head is withheld
            until the backend's first body byte arrives, so a backend
            dying post-headers is still transparently retried; after the
            first forwarded byte the stream is committed."""
            try:
                first = resp.read1(65536)
            except OSError:
                b.errors.inc()
                monitor.report_failure(b)
                return None
            if not first:  # EOF before any event: died mid-prefill
                b.errors.inc()
                monitor.report_failure(b)
                return None
            ADDED_MS.observe((t_sent - t0) * 1e3)
            monitor.report_success(b)
            rs = self._rstat
            rs["t_first"] = rs["t_last"] = time.perf_counter()
            # counting serialized token events in the raw SSE bytes keeps
            # the relay zero-parse; good enough for a TPOT estimate
            rs["tokens"] += first.count(b'"token"')
            try:
                self.send_response(200)
                for h in ("Content-Type", "Cache-Control"):
                    v = resp.getheader(h)
                    if v is not None:
                        self.send_header(h, v)
                self.end_headers()
                self.wfile.write(first)
                self.wfile.flush()
                while True:
                    try:
                        chunk = resp.read1(65536)
                    except OSError as e:
                        # BACKEND died mid-stream: the stream is already
                        # committed, so truncate honestly — but this one
                        # is the replica's fault, count it against it
                        log.debug("backend %s died mid-stream: %s",
                                  b.name, e)
                        b.errors.inc()
                        break
                    if not chunk:
                        rs["ok"] = True
                        break  # normal close-delimited end of stream
                    n_tok = chunk.count(b'"token"')
                    if n_tok:
                        rs["tokens"] += n_tok
                        rs["t_last"] = time.perf_counter()
                    self.wfile.write(chunk)
                    self.wfile.flush()
            except OSError as e:
                # CLIENT went away: closing our backend socket (the
                # attempt's finally) makes the replica's next write fail,
                # which cancels its session and frees the slot — normal
                # churn, not a backend error
                log.debug("client left stream via %s: %s", b.name, e)
            return "done"

    return Handler


def _fleet_retry_after(monitor: HealthMonitor, raw: bytes) -> int:
    """Retry-After from fleet-wide throughput: outstanding work (queued
    + running across routable backends) times this request's own token
    ask, over the fleet's summed tok/s EMA — clamped to [1, 30] s."""
    pending, tok_s = 0, 0.0
    for b in monitor.routable():
        ld = b.load_snapshot()
        pending += int(ld.get("queued", 0)) + int(ld.get("running", 0))
        tok_s += float(ld.get("tok_s_ema", 0.0) or 0.0)
    max_tokens = 16
    try:
        body = json.loads(raw or b"{}")
        if isinstance(body, dict):
            max_tokens = int(body.get("max_tokens", 16) or 16)
    except (ValueError, TypeError):
        pass
    est = (max(1, pending) * max(1, max_tokens)) / max(tok_s, 1.0)
    return max(1, min(30, round(est)))


def _as_seconds(retry_after: str | None) -> float:
    try:
        return float(retry_after) if retry_after else 1.0
    except ValueError:
        return 1.0


def parse_backends(spec: str) -> list[Backend]:
    """``host:port,host:port,...`` -> named Backend list (``b0``, ``b1``,
    ... in spec order — the names key the per-backend metric series)."""
    addrs = [a.strip() for a in spec.split(",") if a.strip()]
    if not addrs:
        raise ValueError("--backends wants host:port[,host:port...]")
    if len(set(addrs)) != len(addrs):
        raise ValueError(f"duplicate backend address in {spec!r}")
    return [Backend(f"b{i}", a) for i, a in enumerate(addrs)]
