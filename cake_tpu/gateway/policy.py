"""Pluggable request routing over the UP backend set.

Three policies, selected by ``--route-policy``:

- ``p2c`` (default) — power-of-two-choices: sample two backends, send the
  request to the less loaded one (load = outstanding work per slot from
  the ``/healthz`` load fields). Mitzenmacher's result is that this beats
  random assignment exponentially in the max-queue sense while needing
  only two load lookups — no global scan, no coordination;
- ``round_robin`` — strict rotation; the baseline the bench row and the
  prefix-affinity acceptance test compare against;
- ``prefix`` — prefix affinity (the SGLang observation): requests whose
  prompts open with the same ``prefix_block``-aligned tokens hash to the
  same preferred replica via rendezvous hashing, so that replica's engine
  prefix store (``BatchGenerator._prefix_store``) keeps their shared
  prefix KV hot — the per-engine cache becomes a fleet-wide one. A
  saturated preferred replica falls back to p2c over the rest (affinity
  is a throughput optimization, never a queueing obligation).

A policy sees only the candidate list the proxy hands it (UP backends not
yet tried for this request) and returns one of them; the retry loop in
``gateway/api.py`` owns exclusion and exhaustion.
"""

from __future__ import annotations

import hashlib
import random
import threading

from cake_tpu.obs import metrics as obs_metrics

POLICIES = ("p2c", "round_robin", "prefix")

# routing-decision series: how often prefix affinity actually landed on
# the preferred replica vs fell back to p2c (saturation / no key)
PREFIX_HITS = obs_metrics.counter("gateway.route_prefix_hits")
PREFIX_FALLBACK = obs_metrics.counter("gateway.route_prefix_fallback")


def prefix_key(body: dict, block: int) -> bytes | None:
    """The affinity key for one completions body: the FIRST
    ``block``-aligned run of the prompt (token ids, or characters for a
    text prompt the gateway cannot tokenize). ``None`` — a prompt shorter
    than one block, or an unparseable body — means "no preference" and
    routes via p2c.

    One block, not the whole prompt, is the point: requests sharing a
    system prompt but differing in their user tail (and total length)
    must map to the SAME key — and therefore the same replica — for the
    second one to hit the first one's cached prefix KV. The engine's
    store keys are ``prefix_block``-aligned too, so a first-block match
    is exactly the granularity at which the cache can pay off.
    """
    ids = body.get("prompt_ids")
    if (isinstance(ids, list) and len(ids) >= block
            and all(isinstance(t, int) for t in ids)):
        return b"ids:" + ",".join(map(str, ids[:block])).encode()
    prompt = body.get("prompt")
    if isinstance(prompt, str) and len(prompt) >= block:
        return b"txt:" + prompt[:block].encode("utf-8", "replace")
    return None


def _rendezvous(key: bytes, name: str) -> int:
    """Highest-random-weight score of ``key`` on backend ``name``: stable
    across processes (no PYTHONHASHSEED), and removing one backend only
    remaps the keys that preferred it."""
    h = hashlib.sha1(key + b"\x00" + name.encode()).digest()
    return int.from_bytes(h[:8], "big")


class RoundRobin:
    """Strict rotation over the candidate list."""

    name = "round_robin"
    wants_key = False  # the proxy skips body parsing entirely

    _GUARDED_BY = {"_i": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._i = 0

    def choose(self, candidates, key=None, now: float = 0.0,
               first_attempt: bool = True):
        with self._lock:
            i = self._i
            self._i += 1
        return candidates[i % len(candidates)]


class P2C:
    """Power-of-two-choices on the live load signal."""

    name = "p2c"
    wants_key = False

    def __init__(self, rng: random.Random | None = None):
        self._rng = rng or random.Random()

    def choose(self, candidates, key=None, now: float = 0.0,
               first_attempt: bool = True):
        if len(candidates) == 1:
            return candidates[0]
        a, b = self._rng.sample(candidates, 2)
        sat_a, sat_b = a.saturated(now), b.saturated(now)
        if sat_a != sat_b:
            return b if sat_a else a
        la, lb = a.load_score(), b.load_score()
        if la != lb:
            return a if la < lb else b
        return a if self._rng.random() < 0.5 else b


class Prefix:
    """Prefix affinity with p2c fallback."""

    name = "prefix"
    wants_key = True  # the proxy parses the body to derive the key

    def __init__(self, block: int = 64, rng: random.Random | None = None):
        if block < 1:
            raise ValueError(f"prefix block must be >= 1, got {block}")
        self.block = block
        self._p2c = P2C(rng)

    def choose(self, candidates, key=None, now: float = 0.0,
               first_attempt: bool = True):
        if key is None:
            return self._p2c.choose(candidates, now=now)
        preferred = max(candidates,
                        key=lambda b: _rendezvous(key, b.name))
        if preferred.saturated(now) and len(candidates) > 1:
            # affinity never queues behind a full replica: the KV rebuild
            # elsewhere costs less than waiting for the hot one
            if first_attempt:
                PREFIX_FALLBACK.inc()
            rest = [b for b in candidates if b is not preferred]
            return self._p2c.choose(rest, now=now)
        # the routing-decision counters score the FIRST choice only: on a
        # retry the true preferred replica has already been excluded, so
        # landing on the runner-up must not read as an affinity hit
        if first_attempt:
            PREFIX_HITS.inc()
        return preferred


def pick_prefill(candidates, rng: random.Random | None = None):
    """Prefill-tier choice (the disagg two-stage route's first hop):
    least queued work wins — a prefill replica's cost is its prompt
    queue (plus KV transfers still draining), not decoding neighbors,
    so queue depth is the whole signal and p2c's sampled-pair dance
    buys nothing over just reading it. Ties break randomly so equal
    replicas share the load."""
    # snapshot scores once: the probe thread mutates load fields
    # concurrently, and re-reading between min() and the tie filter
    # could leave no backend matching the stale minimum
    scored = [(b.queue_score(), b) for b in candidates]
    best = min(score for score, _ in scored)
    tied = [b for score, b in scored if score == best]
    return (rng or random).choice(tied)


def pick_batch(candidates, rng: random.Random | None = None):
    """Batch-class choice (ISSUE 20 SLO routing): drain offline traffic
    to the least-loaded replica instead of the affinity pick —
    interactive requests keep prefix affinity and its hot-KV wins, while
    batch floods spread wherever slack is (their TTFT does not matter
    and their slots are the preemption victims). Least outstanding work
    per slot, spilled victims included; ties break randomly."""
    scored = [(b.load_score(), b) for b in candidates]
    best = min(score for score, _ in scored)
    tied = [b for score, b in scored if score == best]
    return (rng or random).choice(tied)


_DECODE_PREFIX = Prefix()


def pick_decode(candidates, key=None, now: float = 0.0,
                rng: random.Random | None = None):
    """Decode-tier choice (the two-stage route's second hop): p2c on the
    live load signal, with prefix affinity when the request carries a
    key — a decode replica's engine prefix store serves imported
    streams too, so same-prefix resumes landing together keep their
    shared pages hot. Delegates to the Prefix policy (tier-scoped), so
    a saturated preferred replica falls back to p2c over the rest and
    the affinity hit/fallback counters cover the tiered route too."""
    policy = Prefix(rng=rng) if rng is not None else _DECODE_PREFIX
    return policy.choose(candidates, key=key, now=now)


def make_policy(name: str, prefix_block: int = 64,
                rng: random.Random | None = None):
    """Policy registry (the ``--route-policy`` values)."""
    if name == "p2c":
        return P2C(rng)
    if name == "round_robin":
        return RoundRobin()
    if name == "prefix":
        return Prefix(prefix_block, rng)
    raise ValueError(
        f"unknown routing policy {name!r} (have {', '.join(POLICIES)})")
