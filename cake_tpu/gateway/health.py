"""Per-backend health: UP / DRAINING / DOWN state machines + prober.

Every serve replica behind the gateway gets one :class:`Backend`, whose
state is fed from two directions:

- **active probes** — a background :class:`HealthMonitor` thread GETs each
  backend's ``/healthz`` every ``probe_interval`` seconds. The serve plane
  answers that probe with its cheap load fields (``queued`` / ``running`` /
  ``tok_s_ema`` / ``max_concurrent``), so one GET is both the liveness
  check and the p2c load signal — no ``/metrics`` scrape on the hot path;
- **passive signals** — every proxied request's outcome
  (``report_success`` / ``report_failure`` / ``report_saturated``), so a
  backend that dies between probes is marked down by the traffic itself,
  not a poll later.

Transitions carry hysteresis in both directions: ``down_after``
consecutive failures (probe or passive) before UP -> DOWN, ``up_after``
consecutive probe successes before DOWN -> UP — one dropped packet must
not flap a replica out of rotation, and one lucky probe must not flap a
crashing one back in. DRAINING is different: it is the backend's own
explicit statement (a 503 ``/healthz`` with ``draining: true``), so it is
believed immediately both ways.

A DOWN backend routes through a circuit breaker: re-probes back off with
full jitter on the shape of :class:`cake_tpu.runtime.retry.RetryPolicy`
(the same policy plane the distributed master's reconnects use) instead
of hammering a dead port every interval, and while the breaker holds the
backend is not probed at all. Routing (``gateway/policy.py``) only ever
sees ``routable()`` — the UP subset.
"""

from __future__ import annotations

import json
import logging
import random
import threading
import time
import urllib.error
import urllib.request

from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.runtime.retry import RetryPolicy

log = logging.getLogger("cake_tpu.gateway.health")

UP = "up"
DRAINING = "draining"
DOWN = "down"

# gauge encoding for the per-backend state series (gateway.<name>.state)
_STATE_VALUE = {UP: 2, DRAINING: 1, DOWN: 0}

BACKENDS_UP = obs_metrics.gauge("gateway.backends_up")
BREAKER_OPEN = obs_metrics.gauge("gateway.breaker_open")


class Backend:
    """One serve replica: address, health state, and live load signal."""

    # cakelint CK-THREAD: internally locked, callable from any domain
    # (handler threads route and report; the prober thread probes)
    _THREAD_DOMAIN = "any"

    # Shared between HTTP handler threads (routing + passive signals) and
    # the monitor's probe thread; every touch goes through the lock
    # (machine-checked by cakelint CK-LOCK).
    _GUARDED_BY = {
        "_state": "_lock",
        "_fails": "_lock",
        "_oks": "_lock",
        "_load": "_lock",
        "_saturated_until": "_lock",
        "_breaker_attempt": "_lock",
        "_next_probe_t": "_lock",
        "_role": "_lock",
        "_transfer_port": "_lock",
    }

    def __init__(self, name: str, addr: str):
        host, _, port = addr.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"backend address {addr!r} is not host:port")
        self.name = name
        self.addr = addr
        self.host = host
        self.port = int(port)
        self._lock = threading.Lock()
        # optimistic start: a freshly configured backend is routable until
        # the first probe (run synchronously at monitor start) says no
        self._state = UP
        self._fails = 0
        self._oks = 0
        self._load = {"queued": 0, "running": 0, "max_concurrent": 1,
                      "tok_s_ema": 0.0}
        self._saturated_until = 0.0
        self._breaker_attempt = 0
        self._next_probe_t = 0.0
        # disagg tier map (cake_tpu/disagg): the replica's own /healthz
        # body states its role and transfer address — the prober RECORDS
        # what it discovered rather than trusting static config, so a
        # decode-tier route can never silently land on a prefill replica
        self._role = "mixed"
        self._transfer_port = 0
        # per-backend traffic/health series (dynamic gateway.* family)
        self.requests = obs_metrics.counter(f"gateway.{name}.requests")
        self.retries = obs_metrics.counter(f"gateway.{name}.retries")
        self.errors = obs_metrics.counter(f"gateway.{name}.errors")
        self._state_gauge = obs_metrics.gauge(f"gateway.{name}.state")
        self._state_gauge.set(_STATE_VALUE[UP])

    # -- read side (routing) --------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def routable(self) -> bool:
        with self._lock:
            return self._state == UP

    @property
    def role(self) -> str:
        """The role the last probe DISCOVERED ("mixed" until a probe
        says otherwise — a plain serve replica advertises mixed)."""
        with self._lock:
            return self._role

    def transfer_addr(self) -> str | None:
        """``host:port`` of the replica's KV transfer channel, or None
        when it advertises none (it cannot be a decode-tier target)."""
        with self._lock:
            port = self._transfer_port
        return f"{self.host}:{port}" if port else None

    def queue_score(self) -> float:
        """Queued work — the prefill-tier routing signal (prefill cost
        scales with waiting prompts, not decoding neighbors)."""
        with self._lock:
            return self._load["queued"] + self._load.get(
                "kv_transfers_inflight", 0)

    def load_score(self) -> float:
        """Outstanding work per slot — the p2c comparison key."""
        with self._lock:
            ld = self._load
            return (ld["queued"] + ld["running"]) / max(
                1, ld["max_concurrent"])

    def saturated(self, now: float | None = None) -> bool:
        """No free slot at the last probe, or a recent 429 said so."""
        now = time.monotonic() if now is None else now
        with self._lock:
            ld = self._load
            if now < self._saturated_until:
                return True
            return ld["queued"] + ld["running"] >= ld["max_concurrent"]

    def breaker_open(self, now: float | None = None) -> bool:
        """DOWN with the next re-probe still backed off into the future."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return self._state == DOWN and now < self._next_probe_t

    def probe_due(self, now: float) -> bool:
        with self._lock:
            return self._state != DOWN or now >= self._next_probe_t

    def describe(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "addr": self.addr,
                "state": self._state,
                "role": self._role,
                **({"transfer_addr": f"{self.host}:{self._transfer_port}"}
                   if self._transfer_port else {}),
                "load": dict(self._load),
                "consecutive_failures": self._fails,
                "requests": self.requests.value,
                "errors": self.errors.value,
            }

    # -- write side (monitor + passive request outcomes) ----------------------
    def probe_ok(self, load: dict, up_after: int) -> None:
        """A 200 ``/healthz``: refresh the load signal; DOWN needs
        ``up_after`` consecutive clean probes to re-enter rotation,
        DRAINING re-enters immediately (the backend explicitly said it is
        serving again)."""
        with self._lock:
            for k in self._load:
                if k in load:
                    self._load[k] = load[k]
            if "kv_transfers_inflight" in load:
                self._load["kv_transfers_inflight"] = \
                    load["kv_transfers_inflight"]
            role = load.get("role", "mixed")
            if role != self._role:
                log.info("backend %s (%s): role %s -> %s", self.name,
                         self.addr, self._role, role)
                self._role = role
            self._transfer_port = int(load.get("transfer_port", 0) or 0)
            self._fails = 0
            self._oks += 1
            if self._state == DRAINING or (
                self._state == DOWN and self._oks >= up_after
            ):
                self._set_state_locked(UP)
            if self._state == UP:
                self._breaker_attempt = 0
                self._next_probe_t = 0.0

    def probe_draining(self) -> None:
        """The backend's own drain statement (503 + ``draining: true``):
        believed immediately, no hysteresis, no breaker — it is alive and
        will say when it is back."""
        with self._lock:
            self._fails = 0
            self._oks = 0
            if self._state != DRAINING:
                self._set_state_locked(DRAINING)

    def report_failure(self, policy: RetryPolicy,
                       rng: random.Random, down_after: int,
                       now: float | None = None) -> None:
        """A probe or proxied request failed (connect refused, timeout,
        5xx): count toward DOWN; once DOWN, back the next re-probe off
        with full jitter (the circuit breaker)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._fails += 1
            self._oks = 0
            if self._state != DOWN and self._fails >= down_after:
                self._set_state_locked(DOWN)
            if self._state == DOWN:
                # equal-jitter floor on the full-jitter sample: a breaker
                # whose jitter lands near zero would re-probe instantly,
                # which is no breaker at all
                self._next_probe_t = now + max(
                    policy.backoff_s(min(self._breaker_attempt, 8), rng),
                    policy.base_s / 2)
                self._breaker_attempt += 1

    def report_success(self) -> None:
        """A proxied request completed: clears the failure streak (state
        transitions stay probe-driven — traffic only ever lands on UP
        backends, so there is nothing to promote)."""
        with self._lock:
            self._fails = 0

    def report_saturated(self, retry_after_s: float,
                         now: float | None = None) -> None:
        """The backend answered 429: treat it as saturated for the
        Retry-After window without waiting for the next probe."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._saturated_until = max(
                self._saturated_until, now + max(0.0, retry_after_s))

    def _set_state_locked(self, state: str) -> None:
        log.info("backend %s (%s): %s -> %s", self.name, self.addr,
                 self._state, state)
        self._state = state
        self._state_gauge.set(_STATE_VALUE[state])


class HealthMonitor:
    """Background ``/healthz`` prober over a fixed backend set."""

    # cakelint CK-THREAD: every mutation goes through Backend's lock;
    # the monitor's own state is an Event + immutable config, so its
    # surface is callable from handler threads and the prober alike
    _THREAD_DOMAIN = "any"

    def __init__(self, backends: list[Backend], probe_interval: float = 2.0,
                 down_after: int = 2, up_after: int = 2,
                 probe_timeout: float | None = None,
                 retry_policy: RetryPolicy | None = None,
                 rng: random.Random | None = None):
        if not backends:
            raise ValueError("a gateway needs at least one backend")
        if probe_interval <= 0:
            raise ValueError("probe_interval must exceed 0")
        self.backends = list(backends)
        self.probe_interval = probe_interval
        self.down_after = max(1, down_after)
        self.up_after = max(1, up_after)
        self.probe_timeout = (probe_timeout if probe_timeout is not None
                              else max(0.5, min(2.0, probe_interval)))
        # breaker shape: first re-probe within ~a probe interval, capped
        # well under a minute — a restarted replica should not sit out
        # long, it just must not be hammered while dead
        self.retry_policy = retry_policy or RetryPolicy(
            deadline_s=None, max_attempts=1 << 30,
            base_s=probe_interval, cap_s=max(4 * probe_interval, 15.0))
        self._rng = rng or random.Random()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- routing views --------------------------------------------------------
    def routable(self) -> list[Backend]:
        return [b for b in self.backends if b.routable()]

    def describe(self) -> list[dict]:
        return [b.describe() for b in self.backends]

    # -- passive signals (called by the proxy path) ---------------------------
    def report_failure(self, backend: Backend) -> None:
        backend.report_failure(self.retry_policy, self._rng,
                               self.down_after)
        self._publish_gauges()

    def report_success(self, backend: Backend) -> None:
        backend.report_success()

    def report_saturated(self, backend: Backend,
                         retry_after_s: float) -> None:
        backend.report_saturated(retry_after_s)

    def report_draining(self, backend: Backend) -> None:
        backend.probe_draining()
        self._publish_gauges()

    # -- lifecycle ------------------------------------------------------------
    def start(self, initial_probe: bool = True) -> "HealthMonitor":
        """Launch the probe thread; with ``initial_probe`` one synchronous
        pass runs first, so a gateway never starts routing on pure
        optimism toward a port nobody listens on."""
        if self._thread is not None:
            raise RuntimeError("monitor already started")
        if initial_probe:
            # the bootstrap pass is DECISIVE (down_after=1): hysteresis
            # exists to absorb blips on a backend with history, but at
            # start there is no history — a port refusing the very first
            # probe is dead NOW, and marking it UP anyway would falsify
            # the whole point of probing before routing
            self.probe_pass(bootstrap=True)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cake-gateway-health")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.wait(self.probe_interval):
            try:
                self.probe_pass()
            except Exception:  # a probe pass must never kill the thread
                log.exception("health probe pass failed")

    def probe_pass(self, bootstrap: bool = False) -> None:
        """Probe every backend whose breaker allows it, then refresh the
        fleet-level gauges. ``bootstrap`` collapses the DOWN hysteresis
        to one failure (the decisive first pass)."""
        now = time.monotonic()
        down_after = 1 if bootstrap else self.down_after
        for b in self.backends:
            if b.probe_due(now):
                self._probe_one(b, down_after)
        self._publish_gauges()

    def _probe_one(self, b: Backend, down_after: int) -> None:
        url = f"http://{b.addr}/healthz"
        try:
            with urllib.request.urlopen(url,
                                        timeout=self.probe_timeout) as r:
                body = json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read())
            except ValueError:
                body = {}
            finally:
                e.close()
            if e.code == 503 and body.get("draining"):
                b.probe_draining()
            else:
                b.report_failure(self.retry_policy, self._rng, down_after)
            return
        except (OSError, ValueError):
            b.report_failure(self.retry_policy, self._rng, down_after)
            return
        b.probe_ok(body, self.up_after)

    def _publish_gauges(self) -> None:
        now = time.monotonic()
        BACKENDS_UP.set(sum(1 for b in self.backends if b.routable()))
        BREAKER_OPEN.set(sum(1 for b in self.backends
                             if b.breaker_open(now)))
