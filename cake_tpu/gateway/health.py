"""Per-backend health: UP / DRAINING / DOWN state machines + prober.

Every serve replica behind the gateway gets one :class:`Backend`, whose
state is fed from two directions:

- **active probes** — a background :class:`HealthMonitor` thread GETs each
  backend's ``/healthz`` every ``probe_interval`` seconds. The serve plane
  answers that probe with its cheap load fields (``queued`` / ``running`` /
  ``tok_s_ema`` / ``max_concurrent``), so one GET is both the liveness
  check and the p2c load signal — no ``/metrics`` scrape on the hot path;
- **passive signals** — every proxied request's outcome
  (``report_success`` / ``report_failure`` / ``report_saturated``), so a
  backend that dies between probes is marked down by the traffic itself,
  not a poll later.

Transitions carry hysteresis in both directions: ``down_after``
consecutive failures (probe or passive) before UP -> DOWN, ``up_after``
consecutive probe successes before DOWN -> UP — one dropped packet must
not flap a replica out of rotation, and one lucky probe must not flap a
crashing one back in. DRAINING is different: it is the backend's own
explicit statement (a 503 ``/healthz`` with ``draining: true``), so it is
believed immediately both ways.

A DOWN backend routes through a circuit breaker: re-probes back off with
full jitter on the shape of :class:`cake_tpu.runtime.retry.RetryPolicy`
(the same policy plane the distributed master's reconnects use) instead
of hammering a dead port every interval, and while the breaker holds the
backend is not probed at all. Routing (``gateway/policy.py``) only ever
sees ``routable()`` — the UP subset.

Membership is dynamic (ISSUE 19): ``--backends`` seeds *static* members,
and serve replicas self-register over ``POST /v1/fleet/register``
(:meth:`HealthMonitor.register`). A dynamic registration is a **lease
with a TTL**, renewed from two directions — the replica's periodic
re-register heartbeat and every successful gateway-side probe. A missed
renewal never deletes: an expired lease feeds the same hysteresis
failure counter a refused probe does (demote, ``down_after`` applies),
and only a lease that has stayed expired for a whole GC window is
removed from membership. An explicit deregister (the SIGTERM drain
path) pins the backend DRAINING — a racing 200 probe cannot flip it
back to UP until a fresh registration clears the pin — so the probe
race window can never route a request into a dying replica.
"""

from __future__ import annotations

import itertools
import json
import logging
import random
import threading
import time
import urllib.error
import urllib.request

from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.runtime.retry import RetryPolicy

log = logging.getLogger("cake_tpu.gateway.health")

UP = "up"
DRAINING = "draining"
DOWN = "down"

# gauge encoding for the per-backend state series (gateway.<name>.state)
_STATE_VALUE = {UP: 2, DRAINING: 1, DOWN: 0}

BACKENDS_UP = obs_metrics.gauge("gateway.backends_up")
BREAKER_OPEN = obs_metrics.gauge("gateway.breaker_open")
REGISTRATIONS = obs_metrics.counter("gateway.registrations")
DEREGISTRATIONS = obs_metrics.counter("gateway.deregistrations")
LEASE_EXPIRED = obs_metrics.counter("gateway.lease_expired")

STATIC = "static"
DYNAMIC = "dynamic"


class Backend:
    """One serve replica: address, health state, and live load signal."""

    # cakelint CK-THREAD: internally locked, callable from any domain
    # (handler threads route and report; the prober thread probes)
    _THREAD_DOMAIN = "any"

    # Shared between HTTP handler threads (routing + passive signals) and
    # the monitor's probe thread; every touch goes through the lock
    # (machine-checked by cakelint CK-LOCK).
    _GUARDED_BY = {
        "_state": "_lock",
        "_fails": "_lock",
        "_oks": "_lock",
        "_load": "_lock",
        "_saturated_until": "_lock",
        "_breaker_attempt": "_lock",
        "_next_probe_t": "_lock",
        "_role": "_lock",
        "_transfer_port": "_lock",
        "_lease_ttl_s": "_lock",
        "_lease_expires_t": "_lock",
        "_lease_noted": "_lock",
        "_deregistered": "_lock",
        "_last_probe_t": "_lock",
    }

    def __init__(self, name: str, addr: str,
                 registered_via: str = STATIC):
        host, _, port = addr.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"backend address {addr!r} is not host:port")
        self.name = name
        self.addr = addr
        self.host = host
        self.port = int(port)
        # how this member joined: STATIC (--backends seed, immortal) or
        # DYNAMIC (self-registered, lease-governed). Immutable.
        self.registered_via = registered_via
        self._lock = threading.Lock()
        # lease plane (dynamic members only): 0 = no lease held
        self._lease_ttl_s = 0.0
        self._lease_expires_t = 0.0
        self._lease_noted = False  # expiry already counted this episode
        # an explicit deregister pins DRAINING until re-registration:
        # without the pin, a 200 probe racing the replica's own drain
        # flag would flip it back UP and route traffic into the exit
        self._deregistered = False
        self._last_probe_t = 0.0
        # optimistic start: a freshly configured backend is routable until
        # the first probe (run synchronously at monitor start) says no
        self._state = UP
        self._fails = 0
        self._oks = 0
        self._load = {"queued": 0, "running": 0, "max_concurrent": 1,
                      "tok_s_ema": 0.0, "spilled": 0}
        self._saturated_until = 0.0
        self._breaker_attempt = 0
        self._next_probe_t = 0.0
        # disagg tier map (cake_tpu/disagg): the replica's own /healthz
        # body states its role and transfer address — the prober RECORDS
        # what it discovered rather than trusting static config, so a
        # decode-tier route can never silently land on a prefill replica
        self._role = "mixed"
        self._transfer_port = 0
        # per-backend traffic/health series (dynamic gateway.* family)
        self.requests = obs_metrics.counter(f"gateway.{name}.requests")
        self.retries = obs_metrics.counter(f"gateway.{name}.retries")
        self.errors = obs_metrics.counter(f"gateway.{name}.errors")
        self._state_gauge = obs_metrics.gauge(f"gateway.{name}.state")
        self._state_gauge.set(_STATE_VALUE[UP])

    # -- read side (routing) --------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def routable(self) -> bool:
        with self._lock:
            return self._state == UP

    @property
    def role(self) -> str:
        """The role the last probe DISCOVERED ("mixed" until a probe
        says otherwise — a plain serve replica advertises mixed)."""
        with self._lock:
            return self._role

    def transfer_addr(self) -> str | None:
        """``host:port`` of the replica's KV transfer channel, or None
        when it advertises none (it cannot be a decode-tier target)."""
        with self._lock:
            port = self._transfer_port
        return f"{self.host}:{port}" if port else None

    def queue_score(self) -> float:
        """Queued work — the prefill-tier routing signal (prefill cost
        scales with waiting prompts, not decoding neighbors)."""
        with self._lock:
            return self._load["queued"] + self._load.get(
                "kv_transfers_inflight", 0)

    def load_score(self) -> float:
        """Outstanding work per slot — the p2c comparison key. Spilled
        streams (ISSUE 20 preemption) count as latent load: they hold
        no slot today but WILL resume on this replica."""
        with self._lock:
            ld = self._load
            return (ld["queued"] + ld["running"]
                    + ld.get("spilled", 0)) / max(
                1, ld["max_concurrent"])

    def saturated(self, now: float | None = None) -> bool:
        """No free slot at the last probe, or a recent 429 said so."""
        now = time.monotonic() if now is None else now
        with self._lock:
            ld = self._load
            if now < self._saturated_until:
                return True
            return ld["queued"] + ld["running"] >= ld["max_concurrent"]

    def breaker_open(self, now: float | None = None) -> bool:
        """DOWN with the next re-probe still backed off into the future."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return self._state == DOWN and now < self._next_probe_t

    def probe_due(self, now: float) -> bool:
        with self._lock:
            return self._state != DOWN or now >= self._next_probe_t

    def load_snapshot(self) -> dict:
        with self._lock:
            return dict(self._load)

    # -- lease plane ----------------------------------------------------------
    def lease_renew(self, ttl_s: float, now: float | None = None) -> None:
        """(Re)take the membership lease and clear the deregister pin —
        a fresh registration is the replica's statement that it is back."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._lease_ttl_s = max(0.0, ttl_s)
            self._lease_expires_t = now + self._lease_ttl_s
            self._lease_noted = False
            self._deregistered = False

    def lease_expired(self, now: float | None = None) -> bool:
        """The lease lapsed (dynamic members only; static never expire)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return bool(self._lease_expires_t) and now >= \
                self._lease_expires_t

    def lease_note_expiry(self, now: float) -> bool:
        """True exactly once per expiry episode (drives the
        ``gateway.lease_expired`` counter; renewal re-arms it)."""
        with self._lock:
            if (self._lease_expires_t and now >= self._lease_expires_t
                    and not self._lease_noted):
                self._lease_noted = True
                return True
            return False

    def lease_gc_due(self, now: float, gc_s: float) -> bool:
        """Expired for a whole GC window AND not routable: safe to drop
        from membership. Static seeds are immortal."""
        if self.registered_via != DYNAMIC:
            return False
        with self._lock:
            if not self._lease_expires_t or self._state == UP:
                return False
            return now >= self._lease_expires_t + gc_s

    def deregistered(self) -> bool:
        with self._lock:
            return self._deregistered

    def mark_deregistered(self) -> None:
        """Explicit deregister (drain notification): DRAINING now, and
        pinned there — only :meth:`lease_renew` lifts the pin."""
        with self._lock:
            self._fails = 0
            self._oks = 0
            self._deregistered = True
            if self._state != DRAINING:
                self._set_state_locked(DRAINING)

    def advertise(self, role: str | None, transfer_port: int) -> None:
        """Registration-time capability hints (the probe loop keeps
        confirming them against the replica's own /healthz answers)."""
        with self._lock:
            if role:
                self._role = role
            if transfer_port:
                self._transfer_port = int(transfer_port)

    def note_probe(self, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._last_probe_t = now

    def health_entry(self, now: float | None = None) -> dict:
        """The per-backend row in the gateway's own ``/healthz`` map:
        state plus membership staleness at a glance (ISSUE 19)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return {
                "state": self._state,
                "registered_via": self.registered_via,
                "last_probe_age_s": (
                    round(now - self._last_probe_t, 3)
                    if self._last_probe_t else None),
                "lease_expires_in_s": (
                    round(self._lease_expires_t - now, 3)
                    if self._lease_expires_t else None),
            }

    def describe(self) -> dict:
        with self._lock:
            now = time.monotonic()
            return {
                "name": self.name,
                "addr": self.addr,
                "state": self._state,
                "role": self._role,
                "registered_via": self.registered_via,
                **({"transfer_addr": f"{self.host}:{self._transfer_port}"}
                   if self._transfer_port else {}),
                **({"lease_expires_in_s":
                    round(self._lease_expires_t - now, 3)}
                   if self._lease_expires_t else {}),
                "last_probe_age_s": (round(now - self._last_probe_t, 3)
                                     if self._last_probe_t else None),
                "load": dict(self._load),
                "consecutive_failures": self._fails,
                "requests": self.requests.value,
                "errors": self.errors.value,
            }

    # -- write side (monitor + passive request outcomes) ----------------------
    def probe_ok(self, load: dict, up_after: int) -> None:
        """A 200 ``/healthz``: refresh the load signal; DOWN needs
        ``up_after`` consecutive clean probes to re-enter rotation,
        DRAINING re-enters immediately (the backend explicitly said it is
        serving again) — unless the deregister pin holds, in which case
        the probe refreshes load but can never promote (the replica said
        it is leaving; only a fresh registration outranks that). A clean
        probe also renews a held lease: the gateway-side half of the
        heartbeat, riding the existing probe loop."""
        with self._lock:
            for k in self._load:
                if k in load:
                    self._load[k] = load[k]
            if "kv_transfers_inflight" in load:
                self._load["kv_transfers_inflight"] = \
                    load["kv_transfers_inflight"]
            role = load.get("role", "mixed")
            if role != self._role:
                log.info("backend %s (%s): role %s -> %s", self.name,
                         self.addr, self._role, role)
                self._role = role
            self._transfer_port = int(load.get("transfer_port", 0) or 0)
            self._fails = 0
            if self._deregistered:
                return
            if self._lease_ttl_s:
                self._lease_expires_t = (time.monotonic()
                                         + self._lease_ttl_s)
                self._lease_noted = False
            self._oks += 1
            if self._state == DRAINING or (
                self._state == DOWN and self._oks >= up_after
            ):
                self._set_state_locked(UP)
            if self._state == UP:
                self._breaker_attempt = 0
                self._next_probe_t = 0.0

    def probe_draining(self) -> None:
        """The backend's own drain statement (503 + ``draining: true``):
        believed immediately, no hysteresis, no breaker — it is alive and
        will say when it is back."""
        with self._lock:
            self._fails = 0
            self._oks = 0
            if self._state != DRAINING:
                self._set_state_locked(DRAINING)

    def report_failure(self, policy: RetryPolicy,
                       rng: random.Random, down_after: int,
                       now: float | None = None) -> None:
        """A probe or proxied request failed (connect refused, timeout,
        5xx): count toward DOWN; once DOWN, back the next re-probe off
        with full jitter (the circuit breaker)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._fails += 1
            self._oks = 0
            if self._state != DOWN and self._fails >= down_after:
                self._set_state_locked(DOWN)
            if self._state == DOWN:
                # equal-jitter floor on the full-jitter sample: a breaker
                # whose jitter lands near zero would re-probe instantly,
                # which is no breaker at all
                self._next_probe_t = now + max(
                    policy.backoff_s(min(self._breaker_attempt, 8), rng),
                    policy.base_s / 2)
                self._breaker_attempt += 1

    def report_success(self) -> None:
        """A proxied request completed: clears the failure streak (state
        transitions stay probe-driven — traffic only ever lands on UP
        backends, so there is nothing to promote)."""
        with self._lock:
            self._fails = 0

    def report_saturated(self, retry_after_s: float,
                         now: float | None = None) -> None:
        """The backend answered 429: treat it as saturated for the
        Retry-After window without waiting for the next probe."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._saturated_until = max(
                self._saturated_until, now + max(0.0, retry_after_s))

    def _set_state_locked(self, state: str) -> None:
        log.info("backend %s (%s): %s -> %s", self.name, self.addr,
                 self._state, state)
        self._state = state
        self._state_gauge.set(_STATE_VALUE[state])


# process-wide dynamic-member name sequence: names key the per-backend
# metric families (gateway.<name>.*), so they must never be reused for a
# DIFFERENT address within one process (get-or-create would silently
# merge two replicas' series)
_DYN_SEQ = itertools.count()


class HealthMonitor:
    """Background ``/healthz`` prober over a dynamic backend set:
    ``--backends`` seeds static members, :meth:`register` adds/renews
    leased dynamic ones (ISSUE 19)."""

    # cakelint CK-THREAD: every mutation goes through Backend's lock or
    # the membership lock below; the rest is an Event + immutable
    # config, so the surface is callable from handler threads and the
    # prober alike
    _THREAD_DOMAIN = "any"

    # membership: handler threads register/deregister while the prober
    # iterates — every touch of the list goes through the lock
    # (machine-checked by cakelint CK-LOCK)
    _GUARDED_BY = {"_backends": "_mlock"}

    def __init__(self, backends: list[Backend], probe_interval: float = 2.0,
                 down_after: int = 2, up_after: int = 2,
                 probe_timeout: float | None = None,
                 retry_policy: RetryPolicy | None = None,
                 rng: random.Random | None = None,
                 lease_ttl_s: float = 10.0,
                 lease_gc_s: float | None = None,
                 allow_empty: bool = False):
        if not backends and not allow_empty:
            raise ValueError("a gateway needs at least one backend "
                             "(or allow_empty=True to form the fleet "
                             "from self-registrations)")
        if probe_interval <= 0:
            raise ValueError("probe_interval must exceed 0")
        self._mlock = threading.Lock()
        self._backends = list(backends)
        self.lease_ttl_s = max(0.5, lease_ttl_s)
        # how long an expired lease may linger (demoted, still listed)
        # before the member is dropped: generous, so a replica that
        # crashed mid-upgrade can still rejoin under its old entry
        self.lease_gc_s = (lease_gc_s if lease_gc_s is not None
                           else max(30.0, 3 * self.lease_ttl_s))
        self.probe_interval = probe_interval
        self.down_after = max(1, down_after)
        self.up_after = max(1, up_after)
        self.probe_timeout = (probe_timeout if probe_timeout is not None
                              else max(0.5, min(2.0, probe_interval)))
        # breaker shape: first re-probe within ~a probe interval, capped
        # well under a minute — a restarted replica should not sit out
        # long, it just must not be hammered while dead
        self.retry_policy = retry_policy or RetryPolicy(
            deadline_s=None, max_attempts=1 << 30,
            base_s=probe_interval, cap_s=max(4 * probe_interval, 15.0))
        self._rng = rng or random.Random()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- routing views --------------------------------------------------------
    @property
    def backends(self) -> list[Backend]:
        """Membership snapshot (stable order: seeds first, then
        registration order). Always a copy — iterate freely."""
        with self._mlock:
            return list(self._backends)

    def routable(self) -> list[Backend]:
        return [b for b in self.backends if b.routable()]

    def describe(self) -> list[dict]:
        return [b.describe() for b in self.backends]

    def lookup(self, key: str) -> Backend | None:
        """Find a member by name or host:port address."""
        for b in self.backends:
            if b.name == key or b.addr == key:
                return b
        return None

    # -- dynamic membership (the fleet registration plane) --------------------
    def register(self, addr: str, role: str | None = None,
                 transfer_port: int = 0) -> Backend:
        """Create-or-renew the lease for ``addr`` (idempotent: a
        duplicate registration — crash-rejoin, retried POST, or a
        100-way storm — updates the one existing entry in place, never a
        phantom second member). A brand-new or non-routable member gets
        one decisive welcome probe so membership re-forms within a
        heartbeat, not an ``up_after`` hysteresis climb."""
        created = False
        with self._mlock:
            b = next((x for x in self._backends if x.addr == addr), None)
            if b is None:
                b = self._lease_acquire(addr)
                self._backends.append(b)
                created = True
        b.advertise(role, transfer_port)
        b.lease_renew(self.lease_ttl_s)
        REGISTRATIONS.inc()
        if created:
            log.info("backend %s (%s): registered (dynamic)", b.name,
                     addr)
        if created or not b.routable():
            # decisive (down_after=1), same rationale as the bootstrap
            # pass: a registering replica has no failure history, one
            # honest probe settles it either way
            self._probe_one(b, down_after=1)
        self._publish_gauges()
        return b

    def _lease_acquire(self, addr: str) -> Backend:
        """Mint the leased member object (CK-CLAIM ``gateway.lease``:
        the caller must hand it to the membership list or release it)."""
        return Backend(f"d{next(_DYN_SEQ)}", addr,
                       registered_via=DYNAMIC)

    def _lease_release(self, b: Backend) -> None:
        """Drop a member whose lease lapsed past the GC window."""
        with self._mlock:
            if b in self._backends:
                self._backends.remove(b)
        log.warning("backend %s (%s): expired lease past GC window; "
                    "dropped from membership", b.name, b.addr)

    def deregister(self, key: str) -> Backend | None:
        """Explicit leave (drain notification): pin the member DRAINING
        immediately — before any 503 is ever served — and leave the
        lease to expire on its own. Returns None for an unknown member
        (a stale deregister must be harmless)."""
        b = self.lookup(key)
        if b is None:
            return None
        b.mark_deregistered()
        DEREGISTRATIONS.inc()
        log.info("backend %s (%s): deregistered", b.name, b.addr)
        self._publish_gauges()
        return b

    # -- passive signals (called by the proxy path) ---------------------------
    def report_failure(self, backend: Backend) -> None:
        backend.report_failure(self.retry_policy, self._rng,
                               self.down_after)
        self._publish_gauges()

    def report_success(self, backend: Backend) -> None:
        backend.report_success()

    def report_saturated(self, backend: Backend,
                         retry_after_s: float) -> None:
        backend.report_saturated(retry_after_s)

    def report_draining(self, backend: Backend) -> None:
        backend.probe_draining()
        self._publish_gauges()

    # -- lifecycle ------------------------------------------------------------
    def start(self, initial_probe: bool = True) -> "HealthMonitor":
        """Launch the probe thread; with ``initial_probe`` one synchronous
        pass runs first, so a gateway never starts routing on pure
        optimism toward a port nobody listens on."""
        if self._thread is not None:
            raise RuntimeError("monitor already started")
        if initial_probe:
            # the bootstrap pass is DECISIVE (down_after=1): hysteresis
            # exists to absorb blips on a backend with history, but at
            # start there is no history — a port refusing the very first
            # probe is dead NOW, and marking it UP anyway would falsify
            # the whole point of probing before routing
            self.probe_pass(bootstrap=True)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cake-gateway-health")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)

    def _run(self) -> None:
        while not self._stop.wait(self.probe_interval):
            try:
                self.probe_pass()
            except Exception:  # a probe pass must never kill the thread
                log.exception("health probe pass failed")

    def probe_pass(self, bootstrap: bool = False) -> None:
        """Probe every backend whose breaker allows it, enforce lease
        expiry (demote via the hysteresis counter, GC only after a whole
        grace window), then refresh the fleet-level gauges.
        ``bootstrap`` collapses the DOWN hysteresis to one failure (the
        decisive first pass)."""
        now = time.monotonic()
        down_after = 1 if bootstrap else self.down_after
        reap = []
        for b in self.backends:
            if b.lease_note_expiry(now):
                LEASE_EXPIRED.inc()
                log.warning("backend %s (%s): lease expired", b.name,
                            b.addr)
            if b.lease_gc_due(now, self.lease_gc_s):
                reap.append(b)
                continue
            if b.lease_expired(now) and not b.deregistered():
                # missed renewal = one hysteresis failure per pass:
                # demotes after down_after passes, never deletes — the
                # flap-absorbing state machine is the same one probes use
                b.report_failure(self.retry_policy, self._rng,
                                 down_after, now)
            if b.probe_due(now):
                self._probe_one(b, down_after)
        for b in reap:
            self._lease_release(b)
        self._publish_gauges()

    def _probe_one(self, b: Backend, down_after: int) -> None:
        url = f"http://{b.addr}/healthz"
        b.note_probe()
        try:
            with urllib.request.urlopen(url,
                                        timeout=self.probe_timeout) as r:
                body = json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read())
            except ValueError:
                body = {}
            finally:
                e.close()
            if e.code == 503 and body.get("draining"):
                b.probe_draining()
            else:
                b.report_failure(self.retry_policy, self._rng, down_after)
            return
        except (OSError, ValueError):
            b.report_failure(self.retry_policy, self._rng, down_after)
            return
        b.probe_ok(body, self.up_after)

    def _publish_gauges(self) -> None:
        now = time.monotonic()
        BACKENDS_UP.set(sum(1 for b in self.backends if b.routable()))
        BREAKER_OPEN.set(sum(1 for b in self.backends
                             if b.breaker_open(now)))
