"""Multi-replica routing gateway in front of the serve plane.

``--mode serve`` scales one process; this package scales horizontally: a
stdlib-only HTTP front door (``--mode gateway --backends h:p,h:p,...``)
that health-checks a fleet of serve replicas (``gateway/health.py``:
UP / DRAINING / DOWN with probe hysteresis and a circuit breaker on the
``runtime/retry`` backoff shape), routes each request by a pluggable
policy (``gateway/policy.py``: power-of-two-choices on the live load
signal, round-robin, or prefix affinity that turns the per-engine prefix
KV store into a fleet-wide cache), and proxies unary + SSE responses
byte-for-byte with transparent retry before the first forwarded byte
(``gateway/api.py``).
"""

from cake_tpu.gateway.api import (GatewayServer, parse_backends,
                                  start_gateway)
from cake_tpu.gateway.health import (DOWN, DRAINING, UP, Backend,
                                     HealthMonitor)
from cake_tpu.gateway.policy import POLICIES, make_policy, prefix_key

__all__ = [
    "Backend",
    "DOWN",
    "DRAINING",
    "GatewayServer",
    "HealthMonitor",
    "POLICIES",
    "UP",
    "make_policy",
    "parse_backends",
    "prefix_key",
    "start_gateway",
]
