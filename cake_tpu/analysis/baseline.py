"""Baseline file: grandfathered findings with one-line justifications.

The gate's contract is "no NEW findings": a violation that is deliberate
(the examples drive the engine raw because demonstrating the engine API
is their whole point) lives in a committed baseline with a justification,
and everything else fails the build. Entries match on
``(checker, path, key)`` — never line numbers — so a baseline survives
unrelated edits; an entry whose finding disappeared is reported STALE so
dead grandfather clauses can't accumulate.

Format (``analysis-baseline.json``)::

    {"version": 1,
     "entries": [{"checker": "CK-ENGINE",
                  "path": "examples/serve_demo.py",
                  "key": "BatchGenerator.step",
                  "justification": "demo drives the engine directly"}]}
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path

from cake_tpu.analysis.core import Finding


@dataclasses.dataclass(frozen=True)
class BaselineEntry:
    checker: str
    path: str
    key: str
    justification: str = ""

    @property
    def match_key(self) -> tuple[str, str, str]:
        return (self.checker, self.path, self.key)

    def to_dict(self) -> dict:
        return {"checker": self.checker, "path": self.path, "key": self.key,
                "justification": self.justification}


def load(path: str | Path) -> list[BaselineEntry]:
    data = json.loads(Path(path).read_text())
    if not isinstance(data, dict) or "entries" not in data:
        raise ValueError(f"{path}: not a cakelint baseline (no 'entries')")
    entries = []
    for e in data["entries"]:
        just = (e.get("justification") or "").strip()
        if not just or just.lower().startswith("todo"):
            raise ValueError(
                f"{path}: entry {e.get('checker')}:{e.get('path')}:"
                f"{e.get('key')} has no real justification — every "
                "grandfathered finding must say why it is deliberate "
                "(--write-baseline stubs don't count)"
            )
        entries.append(BaselineEntry(
            checker=e["checker"], path=e["path"], key=e["key"],
            justification=e["justification"],
        ))
    return entries


def save(path: str | Path, entries) -> None:
    Path(path).write_text(json.dumps(
        {"version": 1,
         "entries": [e.to_dict() for e in sorted(
             entries, key=lambda e: e.match_key)]},
        indent=1) + "\n")


def from_findings(findings, justification: str = "TODO: justify"):
    """Seed baseline entries from findings (``--write-baseline``); one
    entry per distinct (checker, path, key)."""
    seen = {}
    for f in findings:
        seen.setdefault(f.baseline_key, BaselineEntry(
            checker=f.checker, path=f.path, key=f.key or f.message,
            justification=justification))
    return list(seen.values())


def apply(findings: list[Finding], entries: list[BaselineEntry],
          checker_ids=None, paths=None):
    """Split findings against the baseline.

    Returns ``(new, suppressed, stale)``: findings not covered by any
    entry, findings an entry grandfathers, and entries that matched
    nothing (their violation was fixed — delete them). Staleness is
    only meaningful for entries the run could have re-found: pass the
    run's ``checker_ids`` and scanned ``paths`` so a subset run
    (``--checkers CK-METRIC``, an explicit path) never reports
    out-of-scope entries as fixed."""
    covered = {e.match_key: e for e in entries}
    used: set[tuple[str, str, str]] = set()
    new, suppressed = [], []
    for f in findings:
        if f.baseline_key in covered:
            used.add(f.baseline_key)
            suppressed.append(f)
        else:
            new.append(f)
    stale = [
        e for e in entries
        if e.match_key not in used
        and (checker_ids is None or e.checker in checker_ids)
        and (paths is None or e.path in paths)
    ]
    return new, suppressed, stale
