"""cakelint: project-specific static analysis that gates CI.

``python -m cake_tpu.analysis`` runs every registered checker over the
package, the examples, and bench.py, and exits nonzero on any finding
not grandfathered by ``analysis-baseline.json``. See ``core.py`` for
the framework, the sibling modules for the checkers, and README
"Static analysis" for the workflow (baseline, suppressions, adding a
checker).
"""

from __future__ import annotations

from cake_tpu.analysis.core import (  # noqa: F401
    DEFAULT_ROOTS,
    REPO_ROOT,
    Checker,
    Finding,
    Module,
    run_checkers,
)
from cake_tpu.analysis.claims import ClaimChecker
from cake_tpu.analysis.engine_ownership import EngineOwnershipChecker
from cake_tpu.analysis.guarded_by import GuardedByChecker
from cake_tpu.analysis.metrics_catalog import MetricsCatalogChecker
from cake_tpu.analysis.thread_domains import ThreadDomainChecker
from cake_tpu.analysis.trace_purity import TracePurityChecker
from cake_tpu.analysis.wire_safety import WireSafetyChecker

ALL_CHECKERS = (
    MetricsCatalogChecker,
    EngineOwnershipChecker,
    GuardedByChecker,
    TracePurityChecker,
    WireSafetyChecker,
    ClaimChecker,
    ThreadDomainChecker,
)


def default_checkers() -> list[Checker]:
    return [cls() for cls in ALL_CHECKERS]


def run(roots=None, checkers=None, repo_root=None) -> list[Finding]:
    """Run (a subset of) the suite; returns raw findings (no baseline)."""
    return run_checkers(checkers or default_checkers(), roots=roots,
                        repo_root=repo_root)
