"""cakelint CLI: ``python -m cake_tpu.analysis``.

Exit status: 0 when every finding is baselined (or none exist),
1 on new findings, 2 on usage errors. ``--json`` makes the output
machine-readable (findings + stale baseline entries + summary);
``--write-baseline`` seeds a baseline from the current findings, each
entry stamped "TODO: justify" — the committed file must replace those
with real one-line justifications (load() enforces it).
"""

from __future__ import annotations

import argparse
import json
import sys

from cake_tpu import analysis
from cake_tpu.analysis import baseline as baseline_mod
from cake_tpu.analysis import core


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m cake_tpu.analysis",
        description="cakelint: AST invariant checkers for cake-tpu",
    )
    p.add_argument("paths", nargs="*",
                   help="files/dirs to scan (default: cake_tpu, examples, "
                        "bench.py)")
    p.add_argument("--baseline", metavar="FILE",
                   help="grandfather findings listed in FILE; exit 0 "
                        "unless NEW findings exist")
    p.add_argument("--write-baseline", metavar="FILE",
                   help="write current findings to FILE as baseline "
                        "entries (justifications stubbed TODO)")
    p.add_argument("--json", action="store_true",
                   help="JSON output (findings, stale entries, summary)")
    p.add_argument("--checkers",
                   help="comma-separated checker ids to run "
                        "(e.g. CK-METRIC,CK-WIRE)")
    p.add_argument("--list", action="store_true", dest="list_checkers",
                   help="list available checkers and exit")
    return p


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    checkers = analysis.default_checkers()
    if args.list_checkers:
        for c in checkers:
            print(f"{c.id:<11} {c.name:<18} {c.description}")
        return 0
    if args.checkers:
        wanted = {w.strip() for w in args.checkers.split(",")}
        unknown = wanted - {c.id for c in checkers} - {c.name for c in
                                                       checkers}
        if unknown:
            print(f"unknown checker(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        checkers = [c for c in checkers
                    if c.id in wanted or c.name in wanted]

    roots = args.paths or None
    mods, parse_findings = core.load_modules(roots)
    full = core.is_full_scan(roots)
    # unused suppressions are judged like stale baseline entries: only
    # when the run could have re-found what the comment suppresses —
    # full surface, every checker enabled
    unused = [] if (full and not args.checkers) else None
    findings = core.check_modules(mods, checkers, full, parse_findings,
                                  unused_out=unused)
    unused = unused or []

    if args.write_baseline:
        seeded = baseline_mod.from_findings(findings)
        baseline_mod.save(args.write_baseline, seeded)
        print(f"wrote {args.write_baseline}: {len(seeded)} entries "
              f"covering {len(findings)} findings (justify each before "
              "committing)")
        return 0

    entries = []
    if args.baseline:
        try:
            entries = baseline_mod.load(args.baseline)
        except (OSError, ValueError, KeyError) as e:
            print(f"baseline error: {e}", file=sys.stderr)
            return 2
    # staleness is judged only against what this run could re-find: a
    # subset run (--checkers, explicit paths) must not report live
    # out-of-scope entries as "fixed"
    scanned = {m.rel for m in mods} | {f.path for f in parse_findings}
    new, suppressed, stale = baseline_mod.apply(
        findings, entries, checker_ids={c.id for c in checkers},
        paths=scanned)
    if not full:
        # a partial scan skips cross-file passes, so an unmatched entry
        # may be "not re-checked" rather than "fixed" — stay quiet
        stale = []

    if args.json:
        print(json.dumps({
            "new": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in suppressed],
            "stale_baseline_entries": [e.to_dict() for e in stale],
            "unused_suppressions": unused,
            "summary": {"new": len(new), "baselined": len(suppressed),
                        "stale": len(stale), "unused": len(unused)},
        }, indent=1))
    else:
        for f in new:
            print(f.render())
        for e in stale:
            print(f"STALE baseline entry (violation fixed — delete it): "
                  f"{e.checker}:{e.path}:{e.key}")
        for u in unused:
            ids = "" if u["ids"] is None else f"[{', '.join(u['ids'])}]"
            print(f"UNUSED suppression (nothing to suppress — delete "
                  f"it): {u['path']}:{u['line']}: "
                  f"cakelint: ignore{ids}")
        tail = (f"cakelint: {len(new)} new finding(s), "
                f"{len(suppressed)} baselined, {len(stale)} stale "
                f"baseline entr(ies), {len(unused)} unused "
                "suppression(s)")
        print(tail if (new or suppressed or stale or unused)
              else "cakelint: clean (0 findings)")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
