"""CK-LOCK: ``_GUARDED_BY`` lock-discipline annotations, enforced.

Clang's ``GUARDED_BY`` for this tree: a class (or module) declares which
attributes its lock protects, and this checker verifies every touch of a
guarded attribute happens lexically inside ``with self.<lock>:`` (or
``with <lock>:`` for module globals). The annotation is a plain class
attribute, so it documents the threading contract at the top of the
class AND makes it machine-checked::

    class Scheduler:
        _GUARDED_BY = {"_queue": "_cond", "_by_sid": "_cond"}

Escape hatches, each an explicit reviewable convention:

- ``__init__``/``__new__`` are exempt (construction happens-before any
  sharing);
- a method named ``*_locked`` asserts "caller holds the lock" — the same
  contract the scheduler already encodes in ``_expire_queued_locked``;
- ``cakelint: ignore[CK-LOCK]`` on the line for single-site exceptions
  (e.g. a deliberate lock-free atomic read).

The checker is lexical, not a race detector: it cannot see a lock held
by a caller (hence ``*_locked``) and does not model aliasing. What it
does catch is the class of bug that bit ``Scheduler._deliver``/``_retire``
— a shared dict read off-thread without the condition lock — the moment
it is written, not when a soak test flakes.
"""

from __future__ import annotations

import ast

from cake_tpu.analysis import core


class GuardedByChecker(core.Checker):
    id = "CK-LOCK"
    name = "guarded-by"
    description = ("attributes in a _GUARDED_BY map may only be touched "
                   "inside `with <lock>:` blocks")

    def check_module(self, mod: core.Module):
        # class-level maps: self.<attr> guarded by self.<lock>
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                guarded = self._guarded_map(node.body)
                if guarded:
                    yield from self._check_class(mod, node, guarded)
        # module-level map: bare globals guarded by a module lock
        guarded = self._guarded_map(mod.tree.body)
        if guarded:
            yield from self._check_globals(mod, guarded)

    @staticmethod
    def _guarded_map(body) -> dict[str, str]:
        for stmt in body:
            if (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "_GUARDED_BY"):
                return core.const_dict(stmt.value) or {}
        return {}

    # -- class attrs ------------------------------------------------------
    def _check_class(self, mod, cls: ast.ClassDef, guarded: dict[str, str]):
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name in ("__init__", "__new__"):
                continue
            if item.name.endswith("_locked"):
                continue  # contract: caller holds the lock
            for node in ast.walk(item):
                if not (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"
                        and node.attr in guarded):
                    continue
                lock = guarded[node.attr]
                if self._under_lock(node, ("self", lock)):
                    continue
                yield self.finding(
                    mod, node,
                    f"'self.{node.attr}' is _GUARDED_BY 'self.{lock}' but "
                    f"touched outside `with self.{lock}:` "
                    f"(in {cls.name}.{item.name})",
                    hint=f"wrap the access in `with self.{lock}:`, or name "
                         "the method *_locked if every caller already "
                         "holds it",
                    key=f"{cls.name}.{item.name}:{node.attr}",
                )

    # -- module globals ----------------------------------------------------
    def _check_globals(self, mod, guarded: dict[str, str]):
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Name) and node.id in guarded):
                continue
            fn = core.enclosing_function(node)
            if fn is None:
                continue  # module top level: import-time init, single thread
            if self._is_local(fn, node.id):
                continue  # a local that shadows the guarded global
            lock = guarded[node.id]
            if self._under_lock(node, (lock,)):
                continue
            yield self.finding(
                mod, node,
                f"global '{node.id}' is _GUARDED_BY '{lock}' but touched "
                f"outside `with {lock}:` (in {getattr(fn, 'name', '<lambda>')})",
                hint=f"wrap the access in `with {lock}:`",
                key=f"{getattr(fn, 'name', '<lambda>')}:{node.id}",
            )

    @staticmethod
    def _is_local(fn, name: str) -> bool:
        """True if ``name`` is a local binding inside ``fn`` (param or
        assignment target) with no ``global`` declaration — Python scoping
        makes every use a local then, not a touch of the guarded global."""
        for node in ast.walk(fn):
            if isinstance(node, ast.Global) and name in node.names:
                return False
        args = fn.args
        params = [*args.posonlyargs, *args.args, *args.kwonlyargs]
        if args.vararg:
            params.append(args.vararg)
        if args.kwarg:
            params.append(args.kwarg)
        if any(a.arg == name for a in params):
            return True
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id == name and isinstance(
                    node.ctx, ast.Store):
                return True
        return False

    @staticmethod
    def _under_lock(node: ast.AST, lock_chain: tuple[str, ...]) -> bool:
        want = list(lock_chain)
        for anc in core.ancestors(node):
            if not isinstance(anc, ast.With):
                continue
            for item in anc.items:
                if core.attr_chain(item.context_expr) == want:
                    return True
        return False
