"""CK-JIT: bodies handed to jit/shard_map/pallas_call must be trace-pure.

The classic JAX bug class: a host-side effect inside a traced function —
``time.perf_counter()``, ``random.random()``, a registry counter, a
``print`` — runs ONCE at trace time and never again, so the timing is
a constant, the "random" draw repeats forever, the counter undercounts
by a factor of the step count, and the print goes silent after the first
call. Nothing crashes; the numbers are just wrong.

This checker finds functions that flow into ``jax.jit`` / ``shard_map``
/ ``pl.pallas_call`` — as direct arguments, through ``partial(...)``,
through nested wrapping (``jax.jit(shard_map(f, ...))``), as lambdas, or
via decorators (``@jax.jit``, ``@partial(jax.jit, ...)``) — and flags
host-impure calls in their bodies:

- ``time.*`` and ``datetime.*`` (trace-time constants),
- ``random.*`` / ``np.random.*`` (``jax.random`` is fine — keyed and
  functional),
- ``print`` / ``logging`` / ``log.*`` (fires once; ``jax.debug.print``
  is the traced alternative and is allowed),
- metrics-registry calls (``obs_metrics.*``, instrument ``.inc()`` /
  ``.observe()`` / ``.set()`` on module-level ALL_CAPS instruments).

Resolution is one module deep (a Name argument resolves to a function
defined in the same file); helpers it calls are not recursed into — the
checker catches the direct-body class of bug, reviewers the rest.
"""

from __future__ import annotations

import ast

from cake_tpu.analysis import core

_TRACERS = {"jit", "shard_map", "pallas_call"}
_IMPURE_ROOTS = {"time", "random", "datetime", "logging"}
_LOGGER_NAMES = {"log", "logger"}
_METRIC_MODULES = {"obs_metrics", "_metrics", "metrics"}
_INSTRUMENT_METHODS = {"inc", "observe", "set"}


def _is_tracer_call(call: ast.Call) -> bool:
    chain = core.attr_chain(call.func)
    if not chain:
        return False
    last = chain[-1]
    if last not in _TRACERS:
        return False
    # jax.jit / jit / mesh.shard_map / pl.pallas_call / pallas_call —
    # but not e.g. somedict.jit; require a plausible root
    return len(chain) == 1 or chain[0] in ("jax", "pl", "pltpu", "self") \
        or "shard" in last or last == "pallas_call"


class TracePurityChecker(core.Checker):
    id = "CK-JIT"
    name = "trace-purity"
    description = ("functions traced by jax.jit/shard_map/pallas_call must "
                   "not call impure host APIs (time, random, print, "
                   "logging, metrics)")

    def check_module(self, mod: core.Module):
        defs = self._defs_by_name(mod.tree)
        targets: dict[int, tuple[ast.AST, str]] = {}  # id -> (fn node, via)

        def add(fn_node, via: str):
            if fn_node is not None:
                targets.setdefault(id(fn_node), (fn_node, via))

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and _is_tracer_call(node):
                if node.args:
                    add(self._resolve(node.args[0], defs),
                        core.call_name(node))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if self._decorator_traces(dec):
                        add(node, "decorator")
        for fn_node, via in targets.values():
            yield from self._check_body(mod, fn_node, via)

    # -- resolution --------------------------------------------------------
    @staticmethod
    def _defs_by_name(tree) -> dict[str, ast.AST]:
        return {
            n.name: n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }

    def _resolve(self, arg: ast.AST, defs) -> ast.AST | None:
        if isinstance(arg, ast.Lambda):
            return arg
        if isinstance(arg, ast.Name):
            return defs.get(arg.id)
        if isinstance(arg, ast.Call):
            name = core.call_name(arg)
            if "partial" in name and arg.args:
                return self._resolve(arg.args[0], defs)
            if _is_tracer_call(arg) and arg.args:  # jit(shard_map(f, ...))
                return self._resolve(arg.args[0], defs)
        return None

    @staticmethod
    def _decorator_traces(dec: ast.AST) -> bool:
        if isinstance(dec, ast.Call):
            name = core.call_name(dec)
            if "partial" in name and dec.args:
                inner = dec.args[0]
                return core.attr_chain(inner)[-1:] == ["jit"] or (
                    isinstance(inner, ast.Call) and _is_tracer_call(inner))
            return _is_tracer_call(dec)
        return core.attr_chain(dec)[-1:] == ["jit"] and (
            core.attr_chain(dec)[0] in ("jax",)
            or len(core.attr_chain(dec)) == 1)

    # -- purity walk -------------------------------------------------------
    def _check_body(self, mod, fn_node, via: str):
        fn_name = getattr(fn_node, "name", "<lambda>")
        for node in ast.walk(fn_node):
            if not isinstance(node, ast.Call):
                continue
            impure = self._impurity(node)
            if impure is None:
                continue
            yield self.finding(
                mod, node,
                f"impure host call '{impure}' inside '{fn_name}' which is "
                f"traced (via {via}) — it fires once at trace time, not "
                "per step",
                hint="hoist the effect to the host-side caller (record "
                     "around the dispatch), or use jax.debug.print / "
                     "jax.random for traced equivalents",
                key=f"{fn_name}:{impure}",
            )

    @staticmethod
    def _impurity(call: ast.Call) -> str | None:
        chain = core.attr_chain(call.func)
        if not chain:
            return None
        root, last = chain[0], chain[-1]
        dotted = ".".join(chain)
        if chain == ["print"]:
            return "print"
        if root in _IMPURE_ROOTS and len(chain) > 1:
            return dotted
        if root in _LOGGER_NAMES and len(chain) == 2 and last in (
                "debug", "info", "warning", "error", "exception", "critical",
                "log"):
            return dotted
        if root in ("np", "numpy") and len(chain) > 2 and chain[1] == "random":
            return dotted
        if root in _METRIC_MODULES and len(chain) > 1:
            return dotted
        if (last in _INSTRUMENT_METHODS and len(chain) == 2
                and root.isupper()):
            return dotted  # module-level instrument: REJECTED.inc()
        return None
