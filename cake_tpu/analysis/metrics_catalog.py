"""CK-METRIC: every metric series name must be declared in the catalog.

The registry is get-or-create by string key, so a typo'd name silently
forks a series (``wire.byte_out`` next to ``wire.bytes_out``, each
half-populated). This checker pins every series-name **literal** at an
instrument call site — ``counter("…")`` / ``gauge`` / ``histogram``
factories and the ``Counter``/``Gauge``/``Histogram`` constructors — to
an entry in :mod:`cake_tpu.obs.catalog`. F-string names (the per-segment
and per-worker families) are reduced to ``*`` patterns and must match a
declared ``DYNAMIC`` pattern verbatim. A series name the checker cannot
see through at all (a variable) is flagged too: an unverifiable name is
exactly how forks sneak in.
"""

from __future__ import annotations

import ast

from cake_tpu.analysis import core
from cake_tpu.obs import catalog

_FACTORIES = {"counter", "gauge", "histogram"}
_CONSTRUCTORS = {"Counter", "Gauge", "Histogram"}

# Files that legitimately handle series names as data, not as series:
# the registry itself (its factories take `name` as a parameter) and the
# catalog declarations.
_EXEMPT = {"cake_tpu/obs/metrics.py", "cake_tpu/obs/catalog.py"}


class MetricsCatalogChecker(core.Checker):
    id = "CK-METRIC"
    name = "metrics-catalog"
    description = ("every counter/gauge/histogram series name literal is "
                   "declared in cake_tpu/obs/catalog.py")

    def check_module(self, mod: core.Module):
        if mod.rel in _EXEMPT or mod.rel.startswith("cake_tpu/analysis/"):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = core.call_name(node)
            if fn in _CONSTRUCTORS:
                # constructors must look metrics-shaped: either imported
                # via a metrics module alias (obs_metrics.Histogram) or
                # called with a dotted series-name literal — bare
                # Counter() from collections etc. stays out of scope
                chain = core.attr_chain(node.func)
                rooted = len(chain) > 1 and "metric" in chain[0].lower()
                if not rooted and not self._dotted_literal(node):
                    continue
            elif fn not in _FACTORIES:
                continue
            arg = self._name_arg(node)
            if arg is None:
                continue  # name-less constructor (anonymous instrument)
            yield from self._check_name(mod, node, arg)

    @staticmethod
    def _name_arg(call: ast.Call):
        """The series-name argument: first positional, or the ``name=``
        keyword (a kwarg spelling must not bypass the gate)."""
        if call.args:
            return call.args[0]
        for kw in call.keywords:
            if kw.arg == "name":
                return kw.value
        return None

    @classmethod
    def _dotted_literal(cls, call: ast.Call) -> bool:
        arg = cls._name_arg(call)
        s = core.literal_str(arg) if arg is not None else None
        return bool(s and "." in s)

    def _check_name(self, mod, call, arg):
        lit = core.literal_str(arg)
        if lit is not None:
            if not catalog.is_declared(lit):
                yield self.finding(
                    mod, call,
                    f"metric series '{lit}' is not declared in "
                    "cake_tpu/obs/catalog.py",
                    hint="add it to catalog.SERIES (or fix the typo — a "
                         "near-miss name forks the series silently)",
                    key=lit,
                )
            return
        pat = core.fstring_pattern(arg)
        if pat is not None:
            if pat not in catalog.DYNAMIC:
                yield self.finding(
                    mod, call,
                    f"dynamic metric series pattern '{pat}' is not declared "
                    "in catalog.DYNAMIC",
                    hint="declare the family pattern (one '*' per "
                         "interpolated field) in cake_tpu/obs/catalog.py",
                    key=pat,
                )
            return
        fn = core.enclosing_function(call)
        where = getattr(fn, "name", "<module>") if fn is not None \
            else "<module>"
        yield self.finding(
            mod, call,
            "metric series name is not a literal — the catalog cannot "
            "verify it",
            hint="pass a string literal or f-string; route computed names "
                 "through a declared DYNAMIC family",
            key=f"non-literal:{where}",
        )
