"""CK-THREAD: declared thread domains — who may call into whom.

The Rust reference leans on ``Send``/``Sync``: the compiler knows which
values may cross threads. This tree's substitute is *declared thread
domains*, generalizing CK-ENGINE's single hard-coded rule ("only the
scheduler drives the engine") into annotations any class or module can
carry:

- ``_THREAD_DOMAIN = "engine"`` on a class (or module): its code runs
  on — and its methods may only be called from — that domain's thread.
  ``"any"`` documents a thread-safe type (internally locked) and imposes
  nothing.
- ``_THREAD_SAFE = ("submit", ...)`` on a domain-annotated class: the
  declared **crossing points** — methods callable from any domain
  because they hand work across the boundary safely (the scheduler's
  inbox + condition variable, a session's event queue, an internally
  locked read). Their bodies are checked AS "any"-domain code: a
  crossing point that itself pokes domain state is exactly the bug.
- ``_THREAD_OF = {"start": "engine"}``: per-method domain override for
  mixed classes (``Scheduler.start`` primes the engine happens-before
  the engine thread exists, so it counts as engine-domain code).
- ``_THREAD_ALIASES = ("engine",)``: conventional handle names
  instances travel under, beyond the constructor-taint pass (the
  scheduler's ``self.engine`` arrives as a parameter, not a
  construction).

A finding is a call from code lexically owned by domain A to a method of
a class owned by domain B (B not ``"any"``, A ≠ B) whose receiver is
recognizably such an instance (``self`` inside the class, a declared
alias, or a name/attr bound from the class's constructor anywhere in the
tree — scope-insensitive on purpose, same philosophy as CK-ENGINE), and
that is not a declared crossing: not in the callee's ``_THREAD_SAFE``,
and not made under ``with <lock>:`` for a lock named in the caller
class/module's ``_GUARDED_BY`` map. Unannotated caller code (examples,
bench, the CLI's single-threaded setup) is not checked — CK-ENGINE still
covers raw engine drives there.

The runtime twin (``CAKE_THREAD_STRICT=1``,
:mod:`cake_tpu.runtime.threadcheck`) stamps the engine thread at
scheduler start and asserts membership in the annotated mutators, so
this static model is validated against real execution by the
serve/kvpool/disagg suites.

Dunder methods are exempt in both directions (construction and protocol
hooks happen-before sharing, the same rule CK-LOCK applies to
``__init__``).
"""

from __future__ import annotations

import ast
import dataclasses

from cake_tpu.analysis import core

ANY = "any"


def _is_dunder(name: str) -> bool:
    return name.startswith("__") and name.endswith("__")


@dataclasses.dataclass
class _ClassInfo:
    name: str
    rel: str
    node: ast.ClassDef
    domain: str
    safe: frozenset
    of: dict
    aliases: tuple
    methods: frozenset
    guard_locks: frozenset

    def method_domain(self, meth: str) -> str:
        if meth in self.safe:
            return ANY
        return self.of.get(meth, self.domain)


def _tuple_of_strs(node) -> tuple[str, ...]:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            s = core.literal_str(e)
            if s is None:
                return ()
            out.append(s)
        return tuple(out)
    return ()


def _class_assigns(body) -> dict[str, ast.AST]:
    out = {}
    for stmt in body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            out[stmt.targets[0].id] = stmt.value
    return out


class ThreadDomainChecker(core.Checker):
    id = "CK-THREAD"
    name = "thread-domains"
    description = ("calls cross a declared _THREAD_DOMAIN boundary only "
                   "through _THREAD_SAFE crossing points or _GUARDED_BY "
                   "locks")

    # -- collection --------------------------------------------------------
    def _collect(self, mods):
        classes: dict[str, list[_ClassInfo]] = {}
        module_domain: dict[str, str] = {}
        module_locks: dict[str, frozenset] = {}
        for mod in mods:
            tops = _class_assigns(mod.tree.body)
            dom = core.literal_str(tops.get("_THREAD_DOMAIN", ast.Pass()))
            if dom:
                module_domain[mod.rel] = dom
            guard = core.const_dict(tops.get("_GUARDED_BY", ast.Pass()))
            module_locks[mod.rel] = frozenset((guard or {}).values())
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                a = _class_assigns(node.body)
                cdom = core.literal_str(a.get("_THREAD_DOMAIN", ast.Pass()))
                if not cdom:
                    continue
                cguard = core.const_dict(a.get("_GUARDED_BY", ast.Pass()))
                of_raw = core.const_dict(a.get("_THREAD_OF", ast.Pass()))
                info = _ClassInfo(
                    name=node.name, rel=mod.rel, node=node, domain=cdom,
                    safe=frozenset(_tuple_of_strs(
                        a.get("_THREAD_SAFE", ast.Pass()))),
                    of=of_raw or {},
                    aliases=_tuple_of_strs(a.get("_THREAD_ALIASES",
                                                 ast.Pass())),
                    methods=frozenset(
                        s.name for s in node.body
                        if isinstance(s, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))),
                    guard_locks=frozenset((cguard or {}).values()),
                )
                classes.setdefault(node.name, []).append(info)
        return classes, module_domain, module_locks

    @staticmethod
    def _handles(mods, classes):
        """Receiver names instances of annotated classes travel under:
        declared aliases + names/attrs bound from a constructor call
        anywhere in the tree (scope-insensitive on purpose — a shadowing
        false positive is cheap next to a missed cross-domain call)."""
        handles: dict[str, set[str]] = {}

        def add(name, cls):
            handles.setdefault(name, set()).add(cls)

        for infos in classes.values():
            for info in infos:
                for alias in info.aliases:
                    add(alias, info.name)
        for mod in mods:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Assign):
                    continue
                v = node.value
                if not (isinstance(v, ast.Call)
                        and core.call_name(v) in classes):
                    continue
                cls = core.call_name(v)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        add(t.id, cls)
                    elif isinstance(t, ast.Attribute):
                        add(t.attr, cls)
        return handles

    # -- caller resolution -------------------------------------------------
    @staticmethod
    def _caller_context(node, mod, classes, module_domain):
        """(domain, scope_name, caller_info|None) for the code lexically
        containing ``node``; domain None = unannotated (not checked)."""
        meth = None
        for anc in core.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                meth = anc
            elif isinstance(anc, ast.ClassDef):
                if meth is not None and _is_dunder(meth.name):
                    # dunder exemption regardless of class annotation:
                    # construction/protocol hooks happen-before sharing
                    # (real dunders only — a name-mangled __helper is a
                    # private method, not a protocol hook)
                    return None, meth.name, None
                infos = [i for i in classes.get(anc.name, ())
                         if i.rel == mod.rel and i.node is anc]
                if infos and meth is not None:
                    info = infos[0]
                    return (info.method_domain(meth.name),
                            f"{info.name}.{meth.name}", info)
                # unannotated class: keep walking (a nested handler class
                # inherits the enclosing module/function domain)
        dom = module_domain.get(mod.rel)
        name = getattr(meth, "name", "<module>") if meth is not None \
            else "<module>"
        return dom, name, None

    @staticmethod
    def _under_declared_lock(node, caller_info, module_locks, mod) -> bool:
        locks = set(module_locks.get(mod.rel, ()))
        if caller_info is not None:
            locks |= set(caller_info.guard_locks)
        if not locks:
            return False
        for anc in core.ancestors(node):
            if not isinstance(anc, ast.With):
                continue
            for item in anc.items:
                chain = core.attr_chain(item.context_expr)
                if chain and chain[-1] in locks:
                    return True
        return False

    # -- the pass ----------------------------------------------------------
    def finalize(self, mods):
        classes, module_domain, module_locks = self._collect(mods)
        if not classes:
            return
        handles = self._handles(mods, classes)
        # method name -> [(info, domain)] for non-any-domain methods
        callee: dict[str, list] = {}
        for infos in classes.values():
            for info in infos:
                for meth in info.methods:
                    if _is_dunder(meth):
                        continue
                    dom = info.method_domain(meth)
                    if dom != ANY:
                        callee.setdefault(meth, []).append((info, dom))
        for mod in mods:
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                meth = node.func.attr
                if meth not in callee:
                    continue
                chain = core.attr_chain(node.func.value)
                if not chain:
                    continue
                recv = chain[-1]
                caller_dom, scope, caller_info = self._caller_context(
                    node, mod, classes, module_domain)
                if caller_dom is None:
                    continue
                # resolve the callee: `self` binds to the enclosing class
                # only; any other receiver matches via handles/aliases
                if recv == "self" and len(chain) == 1:
                    cands = [(i, d) for i, d in callee[meth]
                             if caller_info is not None
                             and i.name == caller_info.name]
                else:
                    cands = [(i, d) for i, d in callee[meth]
                             if recv in handles and i.name in handles[recv]]
                if not cands:
                    continue
                doms = {d for _, d in cands}
                if caller_dom in doms:
                    continue  # same-domain (or ambiguous toward same)
                if self._under_declared_lock(node, caller_info,
                                             module_locks, mod):
                    continue
                info, dom = cands[0]
                yield self.finding(
                    mod, node,
                    f"call into thread domain '{dom}' "
                    f"('{'.'.join(chain)}.{meth}()' -> {info.name}) from "
                    f"'{caller_dom}' code in {scope}",
                    hint="cross domains only through declared crossing "
                         "points: a _THREAD_SAFE method on the owner "
                         "(inbox/queue hand-off), or a lock named in "
                         "_GUARDED_BY — or annotate the method "
                         "thread-safe if it truly is",
                    key=f"{info.name}.{meth}:{scope}",
                )
