"""CK-WIRE: wire/resource safety — deadlines, leaks, protocol arms.

Three arms, all encoding lessons this repo already paid for:

1. **recv deadlines** — the seed's ``settimeout(None)`` hole let one
   wedged peer pin a master forever; ISSUE 4 added per-op deadlines.
   Every ``Connection.recv(...)`` call must therefore pass ``timeout=``
   explicitly: a value, or a visible ``timeout=None`` that documents
   "block forever" as a decision instead of a default. (Raw
   ``socket.recv(n)`` byte reads — positional size arg — are out of
   scope; the framing layer bounds those.)

2. **resource leaks on error paths** — a socket/file acquired outside a
   ``with`` must be closed where an exception can't skip it. The checker
   flags an acquisition (``open``, ``socket.socket``,
   ``create_connection``, ``urlopen``, ``.accept()``, ``wire.connect``)
   bound to a local name when statements that can raise sit between the
   acquisition and its release (return/store/close), with no enclosing
   ``with`` and no ``try`` whose handler or ``finally`` closes it.
   Immediate hand-off (``self.x = open(...)``, ``return Conn(sock=s)``
   as the very next statement) is fine — ownership moved before
   anything could throw.

3. **MsgType arms** — a protocol member with a decode arm but no encode
   arm (or vice versa) is dead weight at best and a skew trap at worst.
   Cross-module pass: every ``MsgType`` member needs at least one send
   site (``conn.send(MsgType.X, ...)``) and one dispatch site
   (``t == MsgType.X`` / ``t in (MsgType.X, ...)``) across the tree.
"""

from __future__ import annotations

import ast

from cake_tpu.analysis import core

_ACQUIRE_LAST = {"create_connection", "urlopen", "accept"}

# Method names that store their argument in a longer-lived owner —
# passing a resource to one of these is an ownership hand-off, same as
# `self.x = var` (a bare helper call like `_set_keepalive(sock)` is NOT:
# helpers use, owners store).
_STORE_METHODS = {"append", "add", "put", "insert", "register", "push",
                  "setdefault"}


def _acquisition(call: ast.Call) -> str | None:
    """Short label if this call acquires a closable resource."""
    chain = core.attr_chain(call.func)
    if not chain:
        return None
    last = chain[-1]
    if chain == ["open"]:
        return "open"
    if len(chain) >= 2 and chain[-2:] == ["socket", "socket"]:
        return "socket.socket"
    if last in _ACQUIRE_LAST and len(chain) >= 2:
        return last
    if last == "connect" and any("wire" in p.lower() for p in chain[:-1]):
        return "wire.connect"
    return None


class WireSafetyChecker(core.Checker):
    id = "CK-WIRE"
    name = "wire-safety"
    description = ("Connection.recv passes an explicit timeout; sockets/"
                   "files are exception-safe; every MsgType has encode "
                   "and decode arms")

    # -- arm 1: recv deadlines --------------------------------------------
    def check_module(self, mod: core.Module):
        yield from self._check_recv(mod)
        yield from self._check_resources(mod)

    def _check_recv(self, mod):
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "recv"):
                continue
            if node.args:
                continue  # socket.recv(nbytes): framed layer bounds it
            if any(kw.arg == "timeout" for kw in node.keywords):
                continue
            recv_of = ".".join(core.attr_chain(node.func.value)) or "<expr>"
            yield self.finding(
                mod, node,
                f"'{recv_of}.recv()' without an explicit timeout — a "
                "wedged peer blocks this thread forever",
                hint="pass timeout=<seconds> (or an explicit timeout=None "
                     "to document block-forever as a decision)",
                key=f"recv:{recv_of}",
            )

    # -- arm 2: resource leaks --------------------------------------------
    def _check_resources(self, mod):
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _acquisition(node)
            if kind is None:
                continue
            stmt = core.statement_of(node)
            if stmt is None or self._inside_with(node):
                continue
            finding = self._classify(mod, node, stmt, kind)
            if finding is not None:
                yield finding

    @staticmethod
    def _inside_with(node) -> bool:
        """Acquisition used as (or inside) a `with` context expression."""
        for anc in core.ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    if node in ast.walk(item.context_expr):
                        return True
        return False

    def _classify(self, mod, call, stmt, kind):
        # baseline keys are qualified by the enclosing function so one
        # grandfathered leak can't silently cover a future same-named
        # variable elsewhere in the file
        fn = core.enclosing_function(call)
        where = getattr(fn, "name", "<module>") if fn is not None \
            else "<module>"
        # unbound acquisition: fine when the same expression closes it
        # (`wire.connect(...).close()`) or stores it in an owner
        # (`self.pool.append(open(p))`); otherwise it's simply dropped
        if isinstance(stmt, ast.Expr):
            p = core.parent(call)
            if (isinstance(p, ast.Attribute) and p.attr == "close"):
                return None
            if isinstance(stmt.value, ast.Call) and core.call_name(
                    stmt.value) == "close":
                return None
            for anc in core.ancestors(call):
                if (isinstance(anc, ast.Call)
                        and core.call_name(anc) in _STORE_METHODS):
                    return None
            return self.finding(
                mod, call,
                f"{kind}(...) result is dropped without close()",
                hint="bind it and close it, or chain .close()",
                key=f"res:{kind}:{where}:dropped",
            )
        if not isinstance(stmt, ast.Assign):
            return None  # return open(...) etc.: caller owns it
        # self.x = open(...) / handles[k] = ... : owner object manages it
        targets = []
        for t in stmt.targets:
            if isinstance(t, ast.Name):
                targets.append(t.id)
            elif isinstance(t, ast.Tuple):
                targets.extend(e.id for e in t.elts
                               if isinstance(e, ast.Name))
            else:
                return None  # attribute/subscript target: ownership moved
        if not targets:
            return None
        var = targets[0]
        fn = core.enclosing_function(stmt)
        body_root = fn if fn is not None else mod.tree
        release = self._first_release(body_root, stmt, var)
        if release is None:
            return self.finding(
                mod, call,
                f"{kind}(...) bound to '{var}' is never closed, stored, "
                "or returned in this function",
                hint=f"close '{var}' in a finally, or use `with`",
                key=f"res:{kind}:{where}:{var}",
            )
        if self._protected(body_root, stmt, var):
            return None
        if not self._risky_between(body_root, stmt, release):
            return None  # released immediately: nothing can raise first
        return self.finding(
            mod, call,
            f"'{var}' ({kind}) can leak: statements between the "
            f"acquisition (line {stmt.lineno}) and its release (line "
            f"{release.lineno}) may raise, and no try/finally closes it",
            hint=f"wrap the in-between work in try/except with "
                 f"`{var}.close()` on the error path (or move it under a "
                 "`with`)",
            key=f"res:{kind}:{where}:{var}",
        )

    @staticmethod
    def _hands_off(expr, var) -> bool:
        """True if ``expr`` passes ownership of ``var`` somewhere — the
        var appears as a VALUE (bare name, call argument, container
        element), not merely as the receiver of a method call:
        ``Connection(sock=sock)`` hands off, ``data = sock.recv(n)`` is
        just a read and the caller still owns the socket."""
        for n in ast.walk(expr):
            if (isinstance(n, ast.Name) and n.id == var
                    and not isinstance(core.parent(n), ast.Attribute)):
                return True
        return False

    @classmethod
    def _first_release(cls, root, acq_stmt, var):
        """First post-acquisition release node: return/yield handing the
        var off, an assignment whose RHS hands it off, or an explicit
        .close()."""
        acq_nodes = set(map(id, ast.walk(acq_stmt)))
        best = None
        for node in ast.walk(root):
            line = getattr(node, "lineno", None)
            if line is None or line < acq_stmt.lineno or id(node) in acq_nodes:
                continue
            released = False
            if isinstance(node, (ast.Return, ast.Yield)) and node.value \
                    is not None and cls._hands_off(node.value, var):
                released = True
            elif isinstance(node, ast.Assign) and cls._hands_off(
                    node.value, var):
                released = True
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr == "close"
                  and core.attr_chain(node.func.value) == [var]):
                released = True
            elif (isinstance(node, ast.Call)
                  and core.call_name(node) in _STORE_METHODS
                  and any(cls._hands_off(a, var) for a in node.args)):
                released = True  # conns.append(var): stored in an owner
            if released and (best is None or line < best.lineno):
                best = node
        return best

    @staticmethod
    def _next_stmt(stmt):
        """The statement executed after ``stmt`` on the fallthrough
        path: its next sibling, lifting through enclosing blocks (a
        statement that ends a try body continues at the try's
        successor)."""
        cur = stmt
        while cur is not None:
            p = core.parent(cur)
            for field in ("body", "orelse", "finalbody"):
                lst = getattr(p, field, None)
                if isinstance(lst, list) and cur in lst:
                    i = lst.index(cur)
                    if i + 1 < len(lst):
                        return lst[i + 1]
                    break
            cur = p if isinstance(p, ast.stmt) else (
                core.statement_of(p) if p is not None
                and not isinstance(p, ast.Module) else None)
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return None
        return None

    @classmethod
    def _protected(cls, root, acq_stmt, var) -> bool:
        """A try that actually covers the held-bare region and closes
        the var in a handler or finally: either it encloses the
        acquisition, or it is the very next statement after it (nothing
        can raise in between)."""
        def closes(nodes) -> bool:
            for n in nodes:
                for c in ast.walk(n):
                    if (isinstance(c, ast.Call)
                            and isinstance(c.func, ast.Attribute)
                            and c.func.attr == "close"
                            and core.attr_chain(c.func.value) == [var]):
                        return True
            return False

        def try_closes(t) -> bool:
            return isinstance(t, ast.Try) and (
                closes(t.finalbody) or closes(t.handlers))

        for anc in core.ancestors(acq_stmt):
            if try_closes(anc):
                return True
        nxt = cls._next_stmt(acq_stmt)
        return try_closes(nxt)

    @staticmethod
    def _risky_between(root, acq_stmt, release) -> bool:
        """Any call strictly between acquisition and release that can
        raise while the resource is held bare. Excluded: calls inside
        the release's own statement (`if cond: var.close()` — the test
        belongs to the release), and calls inside the handlers/orelse of
        the try wrapping the acquisition (the resource is unbound on
        those paths)."""
        lo = acq_stmt.end_lineno or acq_stmt.lineno
        release_stmt = core.statement_of(release)
        excluded = set(map(id, ast.walk(release_stmt))) if release_stmt \
            is not None else set()
        if release_stmt is not None:
            # the guard of a conditional release (`if stop: var.close()`)
            # is part of the release decision, not held-bare work
            for anc in core.ancestors(release_stmt):
                if isinstance(anc, (ast.If, ast.While)):
                    excluded.update(map(id, ast.walk(anc.test)))
        for anc in core.ancestors(acq_stmt):
            if isinstance(anc, ast.Try) and acq_stmt in anc.body:
                for part in (*anc.handlers, *anc.orelse):
                    excluded.update(map(id, ast.walk(part)))
                break
        for node in ast.walk(root):
            if isinstance(node, ast.Call) and id(node) not in excluded:
                line = getattr(node, "lineno", 0)
                if lo < line < release.lineno:
                    return True
        return False

    # -- arm 3: MsgType encode/decode arms --------------------------------
    def finalize(self, mods):
        enum_mod, enum_cls = self._find_enum(mods)
        if enum_cls is None:
            return
        members = [
            t.targets[0].id
            for t in enum_cls.body
            if isinstance(t, ast.Assign) and len(t.targets) == 1
            and isinstance(t.targets[0], ast.Name)
            and t.targets[0].id.isupper()
        ]
        sends: set[str] = set()
        dispatches: set[str] = set()
        for mod in mods:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Attribute):
                    continue
                chain = core.attr_chain(node)
                if len(chain) < 2 or "MsgType" not in chain[-2]:
                    continue
                member = chain[-1]
                if member not in members:
                    continue
                use = self._usage(node)
                if use == "send":
                    sends.add(member)
                elif use == "dispatch":
                    dispatches.add(member)
        for member in members:
            missing = [arm for arm, have in (("send", sends),
                                             ("dispatch", dispatches))
                       if member not in have]
            for arm in missing:
                verb = ("is never sent (no encode arm)" if arm == "send"
                        else "is never dispatched on (no decode arm)")
                yield self.finding(
                    enum_mod, enum_cls,
                    f"MsgType.{member} {verb} anywhere in the tree",
                    hint="wire both arms, or baseline a deliberate "
                         "one-sided member (e.g. reference-protocol "
                         "compat) with a justification",
                    key=f"MsgType.{member}:{arm}",
                )

    @staticmethod
    def _find_enum(mods):
        for mod in mods:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef) and node.name == "MsgType":
                    return mod, node
        return None, None

    @staticmethod
    def _usage(attr_node) -> str | None:
        """send (first arg of a .send call), dispatch (in a comparison),
        or neither (docs, aliasing)."""
        prev = attr_node
        for anc in core.ancestors(attr_node):
            if isinstance(anc, ast.Call):
                if (isinstance(anc.func, ast.Attribute)
                        and anc.func.attr == "send"
                        and anc.args and prev in (anc.args[0],
                                                  *ast.walk(anc.args[0]))):
                    return "send"
            if isinstance(anc, ast.Compare):
                return "dispatch"
            prev = anc
        return None
