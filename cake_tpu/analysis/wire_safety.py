"""CK-WIRE: wire-protocol safety — recv deadlines + protocol arms.

Two arms, both encoding lessons this repo already paid for (the third
original arm — socket/fd leak escape analysis — migrated into the
declarative CK-CLAIM framework, :mod:`cake_tpu.analysis.claims`, as its
``fd`` rule):

1. **recv deadlines** — the seed's ``settimeout(None)`` hole let one
   wedged peer pin a master forever; ISSUE 4 added per-op deadlines.
   Every ``Connection.recv(...)`` call must therefore pass ``timeout=``
   explicitly: a value, or a visible ``timeout=None`` that documents
   "block forever" as a decision instead of a default. (Raw
   ``socket.recv(n)`` byte reads — positional size arg — are out of
   scope; the framing layer bounds those.)

2. **protocol arms** — a protocol member with a decode arm but no encode
   arm (or vice versa) is dead weight at best and a skew trap at worst.
   Cross-module pass over BOTH protocol vocabularies in the tree: every
   ``MsgType`` enum member needs at least one send site
   (``conn.send(MsgType.X, ...)``) and one dispatch site
   (``t == MsgType.X`` / ``t in (MsgType.X, ...)``), and so does every
   frame constant in the declared :data:`FRAME_CONST_GROUPS` families —
   the disagg transfer channel's ``XFER_*`` ints ride the same wire
   framing without an enum, and skew hides there just as well.
"""

from __future__ import annotations

import ast

from cake_tpu.analysis import core

# Frame-constant protocol families: (module rel, constant-name prefix).
# Members are module-level ALL-CAPS ints; send/dispatch arms are judged
# tree-wide exactly like MsgType members.
FRAME_CONST_GROUPS = (
    ("cake_tpu/disagg/transfer.py", "XFER_"),
)


class WireSafetyChecker(core.Checker):
    id = "CK-WIRE"
    name = "wire-safety"
    description = ("Connection.recv passes an explicit timeout; every "
                   "MsgType member and declared frame constant has "
                   "encode and decode arms")

    # -- arm 1: recv deadlines --------------------------------------------
    def check_module(self, mod: core.Module):
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "recv"):
                continue
            if node.args:
                continue  # socket.recv(nbytes): framed layer bounds it
            if any(kw.arg == "timeout" for kw in node.keywords):
                continue
            recv_of = ".".join(core.attr_chain(node.func.value)) or "<expr>"
            yield self.finding(
                mod, node,
                f"'{recv_of}.recv()' without an explicit timeout — a "
                "wedged peer blocks this thread forever",
                hint="pass timeout=<seconds> (or an explicit timeout=None "
                     "to document block-forever as a decision)",
                key=f"recv:{recv_of}",
            )

    # -- arm 2: protocol encode/decode arms --------------------------------
    def finalize(self, mods):
        yield from self._check_msgtype(mods)
        yield from self._check_frame_consts(mods)

    def _check_msgtype(self, mods):
        enum_mod, enum_cls = self._find_enum(mods)
        if enum_cls is None:
            return
        members = [
            t.targets[0].id
            for t in enum_cls.body
            if isinstance(t, ast.Assign) and len(t.targets) == 1
            and isinstance(t.targets[0], ast.Name)
            and t.targets[0].id.isupper()
        ]
        sends: set[str] = set()
        dispatches: set[str] = set()
        for mod in mods:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Attribute):
                    continue
                chain = core.attr_chain(node)
                if len(chain) < 2 or "MsgType" not in chain[-2]:
                    continue
                member = chain[-1]
                if member not in members:
                    continue
                use = self._usage(node)
                if use == "send":
                    sends.add(member)
                elif use == "dispatch":
                    dispatches.add(member)
        yield from self._missing_arms(enum_mod, enum_cls, "MsgType.",
                                      members, sends, dispatches,
                                      key_fmt="MsgType.{member}:{arm}")

    def _check_frame_consts(self, mods):
        by_rel = {m.rel: m for m in mods}
        for rel, prefix in FRAME_CONST_GROUPS:
            mod = by_rel.get(rel)
            if mod is None:
                continue  # family module not in this (full) scan surface
            anchors: dict[str, ast.AST] = {}
            for stmt in mod.tree.body:
                if (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and stmt.targets[0].id.startswith(prefix)
                        and stmt.targets[0].id.isupper()
                        and isinstance(stmt.value, ast.Constant)
                        and isinstance(stmt.value.value, int)):
                    anchors[stmt.targets[0].id] = stmt
            sends: set[str] = set()
            dispatches: set[str] = set()
            for m in mods:
                for node in ast.walk(m.tree):
                    name = None
                    if isinstance(node, ast.Name) and node.id in anchors:
                        name = node.id
                    elif isinstance(node, ast.Attribute) \
                            and node.attr in anchors:
                        name = node.attr  # re-exported: transfer.XFER_ACK
                    if name is None:
                        continue
                    use = self._usage(node)
                    if use == "send":
                        sends.add(name)
                    elif use == "dispatch":
                        dispatches.add(name)
            for member, anchor in anchors.items():
                yield from self._missing_arms(
                    mod, anchor, "frame constant ", [member],
                    sends, dispatches, key_fmt="frame:{member}:{arm}")

    def _missing_arms(self, mod, anchor, label, members, sends,
                      dispatches, key_fmt):
        for member in members:
            missing = [arm for arm, have in (("send", sends),
                                             ("dispatch", dispatches))
                       if member not in have]
            for arm in missing:
                verb = ("is never sent (no encode arm)" if arm == "send"
                        else "is never dispatched on (no decode arm)")
                yield self.finding(
                    mod, anchor,
                    f"{label}{member} {verb} anywhere in the tree",
                    hint="wire both arms, or baseline a deliberate "
                         "one-sided member (e.g. reference-protocol "
                         "compat) with a justification",
                    key=key_fmt.format(member=member, arm=arm),
                )

    @staticmethod
    def _find_enum(mods):
        for mod in mods:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef) and node.name == "MsgType":
                    return mod, node
        return None, None

    @staticmethod
    def _usage(attr_node) -> str | None:
        """send (first arg of a .send call), dispatch (in a comparison),
        or neither (docs, aliasing)."""
        prev = attr_node
        for anc in core.ancestors(attr_node):
            if isinstance(anc, ast.Call):
                if (isinstance(anc.func, ast.Attribute)
                        and anc.func.attr == "send"
                        and anc.args and prev in (anc.args[0],
                                                  *ast.walk(anc.args[0]))):
                    return "send"
            if isinstance(anc, ast.Compare):
                return "dispatch"
            prev = anc
        return None
