"""CK-CLAIM: declarative acquire/release pairing — leaks on any path.

The Rust reference gets this from the borrow checker: a value that owns
a resource either reaches its drop or moves into something that will.
This checker is the Python tree's substitute, generalizing CK-WIRE's
original socket/fd escape analysis into a *declared* rule table: each
:class:`ClaimRule` names a paired API — acquire calls, the release that
balances them, and the module that implements the pair (excluded from
analysis: ``pin`` may call ``ref`` inside ``kvpool/table.py``) — and
every acquisition must provably reach its release on all paths,
exception edges included, or hand ownership to something longer-lived.

Rules in force:

- **fd** (migrated from CK-WIRE arm 2): ``open`` / ``socket.socket`` /
  ``create_connection`` / ``urlopen`` / ``.accept()`` / ``wire.connect``
  must be closed, ``with``-owned, returned, or stored.
- **kvpool page claims**: ``pool.alloc()`` (and the engine's
  ``_alloc_page`` wrapper) must reach ``unref`` or hand the page id into
  a table/list an owner releases; ``pool.ref``/``pool.pin`` taken in a
  loop must be balanced by ``unref``/``unpin`` or the page list must be
  handed off (``rec["pages"] = pages``) *before* anything between can
  raise — a ``pin()`` whose hand-off sits after a device dispatch leaks
  pinned pages forever the day that dispatch throws.
- **disagg transfer ids**: an ``import_begin`` registration must flow
  into ``import_attach``/``import_abort`` or be stored for the resume
  handler; an orphaned one pins pool pages until the TTL sweep.
- **spill-store reservations** (ISSUE 20): a ``spill_begin`` claim must
  reach ``spill_commit`` or ``spill_abort`` on every path — a leaked
  reservation shrinks the bounded host-RAM store for every later
  preemption.

What counts as a release (per rule): an explicit release call
(``x.close()``; ``unref(pid)``/``unpin(pid)`` — including a loop
``for p in pages: pool.unpin(p)`` over the claimed list), a hand-off
(``return``/``yield`` the token, use it as an assignment RHS, pass it
to a container store like ``append``/``register``), or a protecting
``try`` whose handler/finally releases it (enclosing the acquisition,
or the very next statement after it). Release calls and effect-style
claim calls (``ref``/``unref``/``pin``/``unpin``) are never "risky"
statements — they are part of the protocol being checked — but a
binding acquisition between a held claim and its release IS risky: a
second ``create_connection`` that raises strands the first socket.

Effect-style claims (``pool.pin(pid)`` — no bound result) track the
claim through one container hop: a pin inside a loop whose tokens are
appended to a local list transfers the claim to that list, which must
then be released or handed off like a bound resource.
"""

from __future__ import annotations

import ast
import dataclasses

from cake_tpu.analysis import core


@dataclasses.dataclass(frozen=True)
class ClaimRule:
    """One declared acquire/release pair.

    ``patterns`` match the acquiring call's attribute chain:

    - ``"open"`` — exact bare name;
    - ``".accept"`` — method call (any receiver);
    - ``"socket.socket"`` — last two chain segments;
    - ``"pool*.pin"`` — method whose receiver chain mentions ``pool``.

    ``release_methods`` are released as ``token.close()``;
    ``release_funcs`` as ``unref(token)`` (any receiver), including the
    loop form over a claimed list. ``style`` is ``"binding"`` (the
    acquire's result is the token: ``s = open(p)``) or ``"effect"``
    (the acquire's first argument is: ``pool.pin(pid)``). ``exclude``
    lists the modules that *implement* the pair.
    """

    rule: str
    style: str  # "binding" | "effect"
    patterns: tuple[str, ...]
    release_methods: tuple[str, ...] = ()
    release_funcs: tuple[str, ...] = ()
    exclude: tuple[str, ...] = ()
    hint: str = ""


CLAIM_RULES = (
    ClaimRule(
        rule="fd",
        style="binding",
        patterns=("open", "socket.socket", ".create_connection",
                  ".urlopen", ".accept", "wire*.connect"),
        release_methods=("close",),
        hint="close it in a finally, or use `with`",
    ),
    ClaimRule(
        rule="kvpool.page",
        style="binding",
        patterns=("pool*.alloc", "._alloc_page"),
        release_funcs=("unref",),
        exclude=("cake_tpu/kvpool/table.py", "cake_tpu/kvpool/prefix.py"),
        hint="unref it on the error path, or hand it to the stream "
             "table / prefix tree before anything can raise",
    ),
    ClaimRule(
        rule="kvpool.ref",
        style="effect",
        patterns=("pool*.ref",),
        release_funcs=("unref",),
        exclude=("cake_tpu/kvpool/table.py", "cake_tpu/kvpool/prefix.py"),
        hint="balance with unref, or hand the page list to its owner "
             "before anything can raise",
    ),
    ClaimRule(
        rule="kvpool.pin",
        style="effect",
        patterns=("pool*.pin",),
        release_funcs=("unpin",),
        exclude=("cake_tpu/kvpool/table.py", "cake_tpu/kvpool/prefix.py"),
        hint="unpin in a finally, or hand the pinned list to the import "
             "record/owner BEFORE any statement that can raise",
    ),
    ClaimRule(
        rule="disagg.import",
        style="binding",
        patterns=(".import_begin",),
        release_funcs=("import_attach", "import_abort"),
        exclude=("cake_tpu/runtime/batch_generator.py",),
        hint="attach or abort the transfer, or store its meta for the "
             "resume handler",
    ),
    ClaimRule(
        rule="gateway.lease",
        style="binding",
        patterns=("._lease_acquire",),
        release_funcs=("_lease_release",),
        hint="append the member to the fleet list (the monitor owns its "
             "lease from there) or release it before anything can raise",
    ),
    ClaimRule(
        rule="gateway.admit",
        style="binding",
        patterns=("._admit_enter",),
        release_funcs=("_admit_exit",),
        hint="release the admission-queue slot in a finally — a leaked "
             "slot shrinks the queue for every later request",
    ),
    ClaimRule(
        rule="serve.spill",
        style="binding",
        patterns=(".spill_begin",),
        release_funcs=("spill_commit", "spill_abort"),
        exclude=("cake_tpu/serve/spill.py",),
        hint="commit the spilled payload or abort the claim in an "
             "except/finally — a leaked reservation shrinks the store "
             "for every later preemption",
    ),
)

# Calls that are never "risky statements" between an acquisition and
# its release: declared releases, and effect-style claim calls (pin/ref
# take a claim on an EXISTING token — part of the protocol under check,
# the `alloc; pin; unref; append` loop idiom). Binding-style acquires
# (open/connect/alloc/import_begin) stay risky on purpose: a second
# dial that raises strands the first socket — the classic double-
# acquisition leak.
_NONRISKY_NAMES = frozenset(
    {p.rsplit(".", 1)[-1] for r in CLAIM_RULES if r.style == "effect"
     for p in r.patterns}
    | {m for r in CLAIM_RULES for m in r.release_methods}
    | {f for r in CLAIM_RULES for f in r.release_funcs}
)

# Method names that store their argument in a longer-lived owner —
# passing a resource to one of these is an ownership hand-off, same as
# `self.x = var` (a bare helper call like `_set_keepalive(sock)` is NOT:
# helpers use, owners store).
_STORE_METHODS = {"append", "add", "put", "insert", "register", "push",
                  "setdefault"}


def _match_pattern(chain: list[str], pattern: str) -> bool:
    if not chain:
        return False
    if "." not in pattern:
        return chain == [pattern]
    head, name = pattern.rsplit(".", 1)
    if chain[-1] != name:
        return False
    if head == "":  # ".accept": any method receiver
        return len(chain) >= 2
    if head.endswith("*"):  # "pool*.pin": receiver mentions the stem
        stem = head[:-1].lower()
        return any(stem in part.lower() for part in chain[:-1])
    return len(chain) >= 2 and chain[-2] == head


def _acquisition(call: ast.Call, rule: ClaimRule) -> str | None:
    """Short label if this call acquires under ``rule``."""
    chain = core.attr_chain(call.func)
    for pat in rule.patterns:
        if _match_pattern(chain, pat):
            return pat.rsplit(".", 1)[-1].lstrip("*") or pat
    return None


class ClaimChecker(core.Checker):
    id = "CK-CLAIM"
    name = "claim-lifecycle"
    description = ("declared acquire/release pairs (fds, kvpool page "
                   "claims, transfer ids) reach their release or a "
                   "hand-off on every path, exception edges included")

    def check_module(self, mod: core.Module):
        rules = [r for r in CLAIM_RULES if mod.rel not in r.exclude]
        if not rules:
            return
        # one walk per module, every rule matched per call (not one
        # walk per rule): the rule table grows, the tree traversals
        # shouldn't
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            for rule in rules:
                kind = _acquisition(node, rule)
                if kind is None:
                    continue
                stmt = core.statement_of(node)
                if stmt is None or self._inside_with(node):
                    continue
                if rule.style == "binding":
                    finding = self._classify_binding(mod, node, stmt, kind,
                                                     rule)
                else:
                    finding = self._classify_effect(mod, node, stmt, kind,
                                                    rule)
                if finding is not None:
                    yield finding

    # -- shared machinery -------------------------------------------------
    @staticmethod
    def _inside_with(node) -> bool:
        """Acquisition used as (or inside) a `with` context expression."""
        for anc in core.ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    if node in ast.walk(item.context_expr):
                        return True
        return False

    @staticmethod
    def _where(node) -> str:
        fn = core.enclosing_function(node)
        return getattr(fn, "name", "<module>") if fn is not None \
            else "<module>"

    @staticmethod
    def _hands_off(expr, var) -> bool:
        """True if ``expr`` passes ownership of ``var`` somewhere — the
        var appears as a VALUE (bare name, call argument, container
        element), not merely as the receiver of a method call:
        ``Connection(sock=sock)`` hands off, ``data = sock.recv(n)`` is
        just a read and the caller still owns the socket."""
        for n in ast.walk(expr):
            if (isinstance(n, ast.Name) and n.id == var
                    and not isinstance(core.parent(n), ast.Attribute)):
                return True
        return False

    @classmethod
    def _release_call(cls, node, var, rule: ClaimRule) -> bool:
        """An explicit release of ``var`` under ``rule``: ``var.close()``,
        ``unref(var)``, or the loop form ``for p in var: unref(p)``."""
        if not isinstance(node, ast.Call):
            return False
        if (rule.release_methods
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in rule.release_methods
                and core.attr_chain(node.func.value) == [var]):
            return True
        if rule.release_funcs and core.call_name(node) in rule.release_funcs:
            for a in node.args:
                # the arg derives from the token: unref(var), or
                # import_abort(var["xfer_id"]) — releasing through a
                # projection of the claim releases the claim
                if any(isinstance(n, ast.Name) and n.id == var
                       for n in ast.walk(a)):
                    return True
                if isinstance(a, ast.Name):
                    # loop release: unref(p) inside `for p in var:`
                    for anc in core.ancestors(node):
                        if (isinstance(anc, ast.For)
                                and isinstance(anc.target, ast.Name)
                                and anc.target.id == a.id
                                and isinstance(anc.iter, ast.Name)
                                and anc.iter.id == var):
                            return True
        return False

    @classmethod
    def _releases(cls, node, var, rule: ClaimRule) -> bool:
        """Release OR hand-off of ``var`` at ``node``."""
        if isinstance(node, (ast.Return, ast.Yield)) and node.value \
                is not None and cls._hands_off(node.value, var):
            return True
        if isinstance(node, ast.Assign) and cls._hands_off(node.value, var):
            return True
        if cls._release_call(node, var, rule):
            return True
        if (isinstance(node, ast.Call)
                and core.call_name(node) in _STORE_METHODS
                and any(cls._hands_off(a, var) for a in node.args)):
            return True  # conns.append(var): stored in an owner
        return False

    @classmethod
    def _first_release(cls, root, acq_stmt, var, rule: ClaimRule):
        """First post-acquisition release/hand-off node."""
        acq_nodes = set(map(id, ast.walk(acq_stmt)))
        best = None
        for node in ast.walk(root):
            line = getattr(node, "lineno", None)
            if line is None or line < acq_stmt.lineno \
                    or id(node) in acq_nodes:
                continue
            if cls._releases(node, var, rule) and (
                    best is None or line < best.lineno):
                best = node
        return best

    @staticmethod
    def _next_stmt(stmt):
        """The statement executed after ``stmt`` on the fallthrough
        path: its next sibling, lifting through enclosing blocks (a
        statement that ends a try body continues at the try's
        successor)."""
        cur = stmt
        while cur is not None:
            p = core.parent(cur)
            for field in ("body", "orelse", "finalbody"):
                lst = getattr(p, field, None)
                if isinstance(lst, list) and cur in lst:
                    i = lst.index(cur)
                    if i + 1 < len(lst):
                        return lst[i + 1]
                    break
            cur = p if isinstance(p, ast.stmt) else (
                core.statement_of(p) if p is not None
                and not isinstance(p, ast.Module) else None)
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return None
        return None

    @classmethod
    def _protected(cls, acq_stmt, var, rule: ClaimRule) -> bool:
        """A try that actually covers the held-bare region and releases
        the var in a handler or finally: either it encloses the
        acquisition, or it is the very next statement after it (nothing
        can raise in between)."""
        def closes(nodes) -> bool:
            for n in nodes:
                for c in ast.walk(n):
                    if cls._release_call(c, var, rule):
                        return True
            return False

        def try_closes(t) -> bool:
            return isinstance(t, ast.Try) and (
                closes(t.finalbody) or closes(t.handlers))

        for anc in core.ancestors(acq_stmt):
            if try_closes(anc):
                return True
        nxt = cls._next_stmt(acq_stmt)
        return try_closes(nxt)

    @staticmethod
    def _risky_between(root, acq_stmt, release) -> bool:
        """Any call strictly between acquisition and release that can
        raise while the claim is held bare. Excluded: release calls and
        effect-style claim calls (part of the protocol under check —
        binding acquires are NOT excluded, a second dial can strand the
        first), calls inside the release's own statement (`if cond:
        var.close()` — the test belongs to the release), and calls
        inside the handlers/orelse of the try wrapping the acquisition
        (the claim is unheld on those paths)."""
        lo = acq_stmt.end_lineno or acq_stmt.lineno
        release_stmt = core.statement_of(release)
        excluded = set(map(id, ast.walk(release_stmt))) if release_stmt \
            is not None else set()
        if release_stmt is not None:
            # the guard of a conditional release (`if stop: var.close()`)
            # is part of the release decision, not held-bare work
            for anc in core.ancestors(release_stmt):
                if isinstance(anc, (ast.If, ast.While)):
                    excluded.update(map(id, ast.walk(anc.test)))
        for anc in core.ancestors(acq_stmt):
            if isinstance(anc, ast.Try) and acq_stmt in anc.body:
                for part in (*anc.handlers, *anc.orelse):
                    excluded.update(map(id, ast.walk(part)))
                break
        for node in ast.walk(root):
            if isinstance(node, ast.Call) and id(node) not in excluded:
                if core.call_name(node) in _NONRISKY_NAMES:
                    continue
                line = getattr(node, "lineno", 0)
                if lo < line < release.lineno:
                    return True
        return False

    # -- binding style: token = acquire(...) -------------------------------
    def _classify_binding(self, mod, call, stmt, kind, rule: ClaimRule):
        # baseline keys are qualified by the enclosing function so one
        # grandfathered leak can't silently cover a future same-named
        # variable elsewhere in the file
        where = self._where(call)
        # unbound acquisition: fine when the same expression releases it
        # (`wire.connect(...).close()`) or stores it in an owner
        # (`self.pool.append(open(p))`); otherwise it's simply dropped
        if isinstance(stmt, ast.Expr):
            p = core.parent(call)
            if (isinstance(p, ast.Attribute)
                    and p.attr in (rule.release_methods or ("close",))):
                return None
            for anc in core.ancestors(call):
                if isinstance(anc, ast.Call) and (
                        core.call_name(anc) in _STORE_METHODS
                        or core.call_name(anc) in rule.release_funcs):
                    return None
            return self.finding(
                mod, call,
                f"{kind}(...) result is dropped without a release "
                f"[{rule.rule}]",
                hint=rule.hint or "bind it and release it",
                key=f"res:{kind}:{where}:dropped",
            )
        if not isinstance(stmt, ast.Assign):
            return None  # return open(...) etc.: caller owns it
        # self.x = open(...) / handles[k] = ... : owner object manages it
        targets = []
        for t in stmt.targets:
            if isinstance(t, ast.Name):
                targets.append(t.id)
            elif isinstance(t, ast.Tuple):
                targets.extend(e.id for e in t.elts
                               if isinstance(e, ast.Name))
            else:
                return None  # attribute/subscript target: ownership moved
        if not targets:
            return None
        var = targets[0]
        fn = core.enclosing_function(stmt)
        body_root = fn if fn is not None else mod.tree
        return self._track(mod, call, stmt, body_root, var, kind, rule)

    # -- effect style: pool.pin(token) -------------------------------------
    def _classify_effect(self, mod, call, stmt, kind, rule: ClaimRule):
        where = self._where(call)
        fn = core.enclosing_function(call)
        body_root = fn if fn is not None else mod.tree
        tok = call.args[0] if call.args else None
        tok_name = tok.id if isinstance(tok, ast.Name) else None
        loop = next((a for a in core.ancestors(stmt)
                     if isinstance(a, ast.For)), None)
        carrier, claim_stmt = None, stmt
        if loop is not None and tok_name is not None:
            claim_stmt = loop
            if (isinstance(loop.target, ast.Name)
                    and loop.target.id == tok_name
                    and isinstance(loop.iter, ast.Name)):
                # `for pid in table: pool.pin(pid)` — the claim is on the
                # iterated list
                carrier = loop.iter.id
            else:
                # `for _ in range(n): pid = alloc(); pin(pid);
                #  pages.append(pid)` — the claim transfers to the list
                # collecting the tokens
                for n in ast.walk(loop):
                    if (isinstance(n, ast.Call)
                            and isinstance(n.func, ast.Attribute)
                            and n.func.attr in _STORE_METHODS
                            and isinstance(n.func.value, ast.Name)
                            and any(isinstance(a, ast.Name)
                                    and a.id == tok_name
                                    for a in n.args)):
                        carrier = n.func.value.id
                        break
            if carrier is None:
                # per-iteration claim on a plain name (`pid = s.pid;
                # pin(pid); ...; unpin(pid)`): no collecting list, so
                # track the name itself within the iteration instead of
                # giving up as untrackable
                carrier, claim_stmt = tok_name, stmt
        elif tok_name is not None:
            carrier = tok_name
        if carrier is None:
            # untrackable token (subscript/expression arg — a plain
            # name always resolves a carrier above): accept only a
            # protecting try with a wildcard release
            if self._wildcard_protected(claim_stmt, rule):
                return None
            # qualify the key by the token EXPRESSION so one
            # grandfathered untracked claim cannot silently baseline a
            # different one later added to the same function
            tok_src = ast.unparse(tok) if tok is not None else "<no-arg>"
            return self.finding(
                mod, call,
                f"{kind}(...) claim cannot be tracked to a release "
                f"[{rule.rule}]: its token is neither a name nor "
                "collected into a list",
                hint=rule.hint,
                key=f"claim:{rule.rule}:{where}:untracked:{tok_src}",
            )
        return self._track(mod, call, claim_stmt, body_root, carrier,
                           kind, rule, key_prefix=f"claim:{rule.rule}")

    @classmethod
    def _wildcard_protected(cls, acq_stmt, rule: ClaimRule) -> bool:
        """A protecting try whose handler/finally makes ANY release_funcs
        call — the escape hatch for tokens the tracker cannot name."""
        def closes(nodes) -> bool:
            for n in nodes:
                for c in ast.walk(n):
                    if (isinstance(c, ast.Call)
                            and core.call_name(c) in rule.release_funcs):
                        return True
            return False

        for anc in core.ancestors(acq_stmt):
            if isinstance(anc, ast.Try) and (
                    closes(anc.finalbody) or closes(anc.handlers)):
                return True
        nxt = cls._next_stmt(acq_stmt)
        return isinstance(nxt, ast.Try) and (
            closes(nxt.finalbody) or closes(nxt.handlers))

    def _track(self, mod, call, claim_stmt, body_root, var, kind,
               rule: ClaimRule, key_prefix: str = "res"):
        where = self._where(call)
        release = self._first_release(body_root, claim_stmt, var, rule)
        if release is None:
            if self._protected(claim_stmt, var, rule):
                return None
            return self.finding(
                mod, call,
                f"{kind}(...) claim on '{var}' is never released, "
                f"stored, or returned in this function [{rule.rule}]",
                hint=rule.hint,
                key=f"{key_prefix}:{kind}:{where}:{var}",
            )
        if self._protected(claim_stmt, var, rule):
            return None
        if not self._risky_between(body_root, claim_stmt, release):
            return None  # released immediately: nothing can raise first
        return self.finding(
            mod, call,
            f"'{var}' ({kind} claim) can leak: statements between the "
            f"acquisition (line {claim_stmt.lineno}) and its release "
            f"(line {release.lineno}) may raise, and no try/finally "
            f"releases it [{rule.rule}]",
            hint=rule.hint or f"wrap the in-between work in try/except "
                 f"releasing '{var}' on the error path",
            key=f"{key_prefix}:{kind}:{where}:{var}",
        )
