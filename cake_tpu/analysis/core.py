"""cakelint core: findings, checker protocol, AST driver.

The repo's load-bearing invariants — one engine owner, declared metric
series, lock discipline, trace-pure jitted bodies, deadline-bounded wire
reads — live in CHANGES.md prose and reviewer memory. This package turns
them into AST checks that gate CI (``make lint``), the same role Clang's
thread-safety annotations and TSan play for C++ servers.

Architecture: one driver parses every file once into a :class:`Module`
(AST with parent links + source lines), then hands each module to every
registered :class:`Checker`. Checkers are per-module visitors with an
optional :meth:`Checker.finalize` pass over the whole module set for
cross-file invariants (e.g. "every MsgType has a send arm somewhere").
Findings carry ``file:line:col``, a checker id, a message, a fix hint,
and a stable ``key`` so baselines survive unrelated line drift.

Suppression: a finding whose source line (or the line above it) carries
``cakelint: ignore[CK-ID]`` (or a bare ``cakelint: ignore``) is dropped —
the escape hatch for a justified one-off that doesn't warrant a
baseline entry.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import tokenize
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[2]

# Scan surface: the package, the runnable examples, and the bench driver.
# Tests are deliberately out — they exercise invariant-breaking paths on
# purpose (chaos faults, lock races, raw engine drives).
DEFAULT_ROOTS = ("cake_tpu", "examples", "bench.py", "__graft_entry__.py")

_SKIP_DIRS = {"__pycache__", ".git", "native"}

# sentinel for "no suppression comment on this line" (a bare ignore
# comment parses to None-ids, so None cannot also mean absence)
_NO_IGNORE = object()


@dataclasses.dataclass(frozen=True)
class Finding:
    """One invariant violation at a source location."""

    checker: str  # checker id, e.g. "CK-METRIC"
    path: str  # repo-relative posix path
    line: int
    col: int
    message: str
    hint: str = ""
    # Stable identity for baselines: (checker, path, key) — key defaults
    # to the message, but checkers set something line-independent (a
    # series name, "BatchGenerator.step", "MsgType.X:send") so a baseline
    # entry survives edits elsewhere in the file.
    key: str = ""

    @property
    def baseline_key(self) -> tuple[str, str, str]:
        return (self.checker, self.path, self.key or self.message)

    def to_dict(self) -> dict:
        return {
            "checker": self.checker,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "hint": self.hint,
            "key": self.key or self.message,
        }

    def render(self) -> str:
        s = f"{self.path}:{self.line}:{self.col}: {self.checker} {self.message}"
        if self.hint:
            s += f"\n    hint: {self.hint}"
        return s

    def sort_key(self):
        return (self.path, self.line, self.col, self.checker)


class Module:
    """One parsed source file: AST with parent links + raw lines."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        add_parents(self.tree)
        self._comments: dict[int, str] | None = None

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def comment_at(self, lineno: int) -> str:
        """The REAL comment token on ``lineno`` ('' if none), from one
        lazy tokenize pass — so a ``#`` inside a string literal can
        neither suppress nor read as a suppression comment."""
        if self._comments is None:
            comments: dict[int, str] = {}
            try:
                for tok in tokenize.generate_tokens(
                        io.StringIO(self.source).readline):
                    if tok.type == tokenize.COMMENT:
                        comments[tok.start[0]] = tok.string
            except tokenize.TokenError:
                pass  # already ast-parsed; truncated trailer at worst
            self._comments = comments
        return self._comments.get(lineno, "")

    def suppression_line(self, finding: Finding) -> int | None:
        """Line number of the ``cakelint: ignore[ID]`` comment covering
        this finding (its own line or the line above — the
        comment-only-line idiom), or None."""
        for ln in (finding.line, finding.line - 1):
            ids = self.ignore_at(ln)
            if ids is _NO_IGNORE:
                continue
            if ids is None or finding.checker in ids:
                return ln
        return None

    def suppressed(self, finding: Finding) -> bool:
        return self.suppression_line(finding) is not None

    def ignore_at(self, lineno: int):
        """Parse a suppression comment on ``lineno``: returns the
        ``_NO_IGNORE`` sentinel when there is none, else the listed
        checker ids (or None for a bare id-less ignore). The marker must
        sit inside the line's actual comment token — prose mentions in
        docstrings or string literals don't suppress."""
        text = self.comment_at(lineno)
        if "cakelint: ignore" not in text:
            return _NO_IGNORE
        mark = text.split("cakelint: ignore", 1)[1]
        if not mark.startswith("["):  # bare ignore: every checker
            return None
        return [i.strip() for i in mark[1:].split("]", 1)[0].split(",")]

    def ignore_comments(self):
        """Every suppression comment in the file: ``[(line, ids|None)]``
        (ids None = bare ignore)."""
        out = []
        for ln, text in enumerate(self.lines, start=1):
            parsed = self.ignore_at(ln)
            if parsed is not _NO_IGNORE:
                out.append((ln, parsed))
        return out


class Checker:
    """Base checker. Subclasses set ``id``/``name``/``description`` and
    implement :meth:`check_module` (per-file) and/or :meth:`finalize`
    (after every module has been seen — cross-file invariants)."""

    id = "CK-BASE"
    name = "base"
    description = ""

    def check_module(self, mod: Module):
        return ()

    def finalize(self, mods: list[Module]):
        return ()

    # -- convenience for subclasses --------------------------------------
    def finding(self, mod: Module, node: ast.AST, message: str,
                hint: str = "", key: str = "") -> Finding:
        return Finding(
            checker=self.id, path=mod.rel,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            message=message, hint=hint, key=key,
        )


# -- AST helpers (shared by every checker) -------------------------------

def add_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.cakelint_parent = node  # type: ignore[attr-defined]


def parent(node: ast.AST):
    return getattr(node, "cakelint_parent", None)


def ancestors(node: ast.AST):
    n = parent(node)
    while n is not None:
        yield n
        n = parent(n)


def attr_chain(node: ast.AST) -> list[str]:
    """``self._cond.notify`` -> ["self", "_cond", "notify"]; empty list
    for anything that isn't a pure Name/Attribute chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def call_name(call: ast.Call) -> str:
    """Last name of the called thing ("" if unresolvable)."""
    chain = attr_chain(call.func)
    return chain[-1] if chain else ""


def literal_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def fstring_pattern(node: ast.AST) -> str | None:
    """Reduce an f-string to a catalog pattern: every interpolated field
    becomes ``*`` (``f"seg{i}.ms"`` -> ``"seg*.ms"``)."""
    if not isinstance(node, ast.JoinedStr):
        return None
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            parts.append(str(v.value))
        elif isinstance(v, ast.FormattedValue):
            parts.append("*")
        else:
            return None
    return "".join(parts)


def const_dict(node: ast.AST) -> dict[str, str] | None:
    """A ``{"attr": "lock"}`` literal as a plain dict (None otherwise)."""
    if not isinstance(node, ast.Dict):
        return None
    out: dict[str, str] = {}
    for k, v in zip(node.keys, node.values):
        ks, vs = literal_str(k) if k else None, literal_str(v)
        if ks is None or vs is None:
            return None
        out[ks] = vs
    return out


def enclosing_function(node: ast.AST):
    for a in ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return a
    return None


def statement_of(node: ast.AST) -> ast.stmt | None:
    """The nearest enclosing statement node."""
    cur: ast.AST | None = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = parent(cur)
    return cur  # type: ignore[return-value]


def contains_name(node: ast.AST, name: str) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id == name for n in ast.walk(node)
    )


# -- driver --------------------------------------------------------------

def iter_py_files(roots, repo_root: Path):
    for root in roots:
        p = Path(root)
        if not p.is_absolute():
            p = repo_root / p
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not any(part in _SKIP_DIRS for part in f.parts):
                    yield f


def load_modules(roots=None, repo_root: Path | None = None):
    """Parse the scan surface. Returns (modules, parse_findings) — a
    syntactically broken file is itself a finding, not a crash."""
    repo_root = repo_root or REPO_ROOT
    roots = roots or DEFAULT_ROOTS
    mods: list[Module] = []
    findings: list[Finding] = []
    for f in iter_py_files(roots, repo_root):
        try:
            rel = f.resolve().relative_to(repo_root).as_posix()
        except ValueError:
            rel = f.as_posix()
        try:
            mods.append(Module(f, rel, f.read_text()))
        except (SyntaxError, UnicodeDecodeError) as e:
            findings.append(Finding(
                checker="CK-PARSE", path=rel,
                line=getattr(e, "lineno", 1) or 1, col=0,
                message=f"file does not parse: {e.__class__.__name__}: {e}",
                key="parse",
            ))
    return mods, findings


def is_full_scan(roots, repo_root: Path | None = None) -> bool:
    """Cross-file (finalize) checks need the whole tree in view:
    'MsgType.X is never sent anywhere' is meaningless when 'anywhere'
    is one file or one subpackage. Full = the default surface (no
    explicit roots) or a root that IS the repo root. Partial scans also
    skip stale-baseline judgement — they cannot tell 'fixed' from
    'not re-checked'."""
    if roots is None:
        return True
    repo_root = (repo_root or REPO_ROOT).resolve()
    for r in roots:
        p = Path(r)
        if not p.is_absolute():
            p = repo_root / p
        try:
            if p.resolve() == repo_root:
                return True
        except OSError:
            continue
    return False


def check_modules(mods, checkers, full: bool = True, parse_findings=(),
                  unused_out: list | None = None):
    """Run ``checkers`` over an already-parsed module list (one walk of
    the tree feeds both the checkers and any caller that needs the
    scanned-path set). ``full=False`` skips cross-file ``finalize``
    passes. Returns sorted findings with suppressions applied.

    With ``unused_out`` (a list), suppression comments that suppressed
    NOTHING this run are appended as ``{"path", "line", "ids"}`` dicts —
    the in-source twin of a stale baseline entry. Callers pass it only
    on full scans with every checker enabled: a subset run cannot tell
    "nothing to suppress" from "the suppressing checker didn't run"."""
    findings = list(parse_findings)
    by_rel = {m.rel: m for m in mods}
    for checker in checkers:
        for mod in mods:
            findings.extend(checker.check_module(mod))
        if full:
            findings.extend(checker.finalize(mods))
    kept = []
    hits: set[tuple[str, int]] = set()
    for f in findings:
        mod = by_rel.get(f.path)
        if mod is not None:
            ln = mod.suppression_line(f)
            if ln is not None:
                hits.add((f.path, ln))
                continue
        kept.append(f)
    if unused_out is not None:
        for mod in mods:
            for ln, ids in mod.ignore_comments():
                if (mod.rel, ln) not in hits:
                    unused_out.append(
                        {"path": mod.rel, "line": ln, "ids": ids})
    return sorted(kept, key=Finding.sort_key)


def run_checkers(checkers, roots=None, repo_root: Path | None = None):
    """Parse the scan surface and run ``checkers`` over it. Cross-file
    ``finalize`` passes are skipped on file-scoped scans (see
    :func:`is_full_scan`)."""
    mods, parse_findings = load_modules(roots, repo_root)
    return check_modules(mods, checkers, is_full_scan(roots, repo_root),
                         parse_findings)
