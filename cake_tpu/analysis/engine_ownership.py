"""CK-ENGINE: the scheduler is the only caller of the engine. Ever.

``BatchGenerator`` mutates device state on every ``step()``; the serving
plane is safe only because exactly one thread — the scheduler's engine
thread — ever calls its mutating surface, while HTTP handlers talk to
sessions. That ownership line is stated in serve/scheduler.py's docstring
and nowhere else; this checker enforces it: outside the allowed owners,
no code may call a mutating engine method (``step``/``enqueue``/
``finish``/``set_prompts``/``drain``/``warm_admission``) on anything that
is an engine — a variable bound from a ``BatchGenerator``/
``SingleStreamEngine`` construction, or any ``.engine`` attribute (the
conventional name the scheduler and CLI use for the handle).

Deliberate direct drives (the examples exist to demonstrate the raw
engine API; bench.py times it without a serving plane) are grandfathered
in the committed baseline with a justification each.
"""

from __future__ import annotations

import ast

from cake_tpu.analysis import core

MUTATING = {"step", "enqueue", "finish", "set_prompts", "drain",
            "warm_admission"}

ENGINE_CONSTRUCTORS = {"BatchGenerator", "SingleStreamEngine"}

# The owners: the scheduler (the one runtime caller), the engine
# implementations themselves (internal self-calls), and the facade.
ALLOWED = {
    "cake_tpu/serve/scheduler.py",
    "cake_tpu/runtime/batch_generator.py",
    "cake_tpu/serve/engine.py",
}


class EngineOwnershipChecker(core.Checker):
    id = "CK-ENGINE"
    name = "engine-ownership"
    description = ("only serve/scheduler.py (and the engine modules "
                   "themselves) may call mutating BatchGenerator methods")

    def check_module(self, mod: core.Module):
        if mod.rel in ALLOWED:
            return
        tainted = self._engine_names(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            meth = node.func.attr
            if meth not in MUTATING:
                continue
            recv = node.func.value
            chain = core.attr_chain(recv)
            is_engine = bool(chain) and (
                chain[-1] == "engine" or chain[-1] in tainted
                or (len(chain) == 1 and chain[0] in tainted)
            )
            if not is_engine:
                continue
            yield self.finding(
                mod, node,
                f"mutating engine call '.{meth}()' outside the scheduler "
                f"(receiver '{'.'.join(chain)}')",
                hint="the engine has ONE owner — route work through "
                     "serve.scheduler.Scheduler (submit/cancel), or "
                     "baseline a deliberate direct drive with a "
                     "justification",
                key=f"BatchGenerator.{meth}",
            )

    @staticmethod
    def _engine_names(mod: core.Module) -> set[str]:
        """Names bound (anywhere in the module) from an engine
        construction: ``gen = BatchGenerator(...)`` and rebindings of the
        same name. Scope-insensitive on purpose — a shadowing false
        positive is cheap next to a missed engine drive."""
        tainted: set[str] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if not (isinstance(value, ast.Call)
                    and core.call_name(value) in ENGINE_CONSTRUCTORS):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    tainted.add(tgt.id)
        return tainted
