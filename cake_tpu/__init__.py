"""cake-tpu: a TPU-native distributed LLM inference framework.

A ground-up rebuild of the capabilities of b0xtch/cake (distributed
single-stream Llama-3 inference, layer-sharded across devices by a YAML
topology) designed for TPU pods: JAX/XLA/pjit compute, shard_map + ICI
collectives for multi-chip, Pallas kernels for the hot ops, and C++ for the
native runtime components. See SURVEY.md for the reference blueprint.
"""

__version__ = "0.1.0"

from cake_tpu.models.config import (  # noqa: F401
    LlamaConfig,
    gemma_7b,
    llama2_7b,
    llama3_8b,
    llama3_70b,
    mistral_7b,
    mixtral_8x7b,
    qwen2_7b,
)
