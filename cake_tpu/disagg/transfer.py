"""The KV transfer channel between serve replicas.

One frame kind rides the existing wire plane (:mod:`cake_tpu.runtime.
wire`: magic + type + length-prefixed payload + CRC32 trailer, native or
pure-Python transport, chaos-proxy parseable):

- ``XFER_SNAPSHOT`` — a whole :mod:`cake_tpu.disagg.snapshot` payload,
  prefill replica -> decode replica;
- ``XFER_ACK`` — the receiver parsed and accepted it (the stream is now
  resumable there);
- ``XFER_REJECT`` — deterministic refusal (fingerprint mismatch, not a
  paged engine, malformed snapshot). Carries the reason; NEVER retried —
  the same bytes would be refused again, exactly the transport-vs-config
  split :func:`cake_tpu.runtime.retry.retry_call` draws for the worker
  handshake.

Transport failures (connect refused, CRC mismatch from a corrupted
frame, a truncated/killed connection, a recv deadline on a stalled one)
retry with full-jitter backoff under a deadline budget
(:class:`~cake_tpu.runtime.retry.RetryPolicy`); each retry reconnects
and resends the whole snapshot. Resends are idempotent at the receiver:
snapshots dedup by transfer id, so an ACK lost to a mid-reply fault
costs one duplicate send, never a duplicate import.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time

from cake_tpu.obs import metrics as obs_metrics
from cake_tpu.runtime import wire
from cake_tpu.runtime.retry import RetryPolicy, retry_call

log = logging.getLogger("cake_tpu.disagg.transfer")

# Thread domain (cakelint CK-THREAD): sends run on serve handler
# threads, receives on the TransferServer's per-connection threads —
# neither may touch the engine; inbound snapshots cross into the engine
# domain only through the scheduler's submit_import/abort_import
# crossing points (its condition-locked import inbox).
_THREAD_DOMAIN = "transfer"

# frame types, clear of the worker protocol's MsgType range (1..9): the
# transfer channel is its own listener/port, but distinct ids keep a
# misrouted frame an obvious error instead of a confusing decode
XFER_SNAPSHOT = 32
XFER_ACK = 33
XFER_REJECT = 34

TRANSFER_MS = obs_metrics.histogram("disagg.transfer_ms")
TRANSFER_BYTES = obs_metrics.histogram("disagg.transfer_bytes",
                                       buckets=obs_metrics.BYTES_BUCKETS)
TRANSFER_FAILURES = obs_metrics.counter("disagg.transfer_failures")


class TransferError(RuntimeError):
    """The transfer could not be completed inside the retry budget."""


class TransferRejected(TransferError):
    """The receiver refused the snapshot deterministically (fingerprint
    mismatch, malformed payload) — retrying would refuse again."""


def send_snapshot(host: str, port: int, payload: bytes,
                  deadline_s: float = 15.0, connect_timeout_s: float = 2.0,
                  ack_timeout_s: float = 10.0, rng=None,
                  sleep=time.sleep, trace=None) -> None:
    """Ship one snapshot and wait for the receiver's verdict.

    Retries transport failures (reconnect + resend) with full jitter
    until ``deadline_s`` runs out — raising :class:`TransferError` with
    the last transport error chained — and raises
    :class:`TransferRejected` immediately on an ``XFER_REJECT``.
    With ``trace`` (an ``obs.reqtrace.ReqTrace``), every attempt —
    including the failed ones a retry follows — records its own
    ``disagg.transfer`` span, so a chaos-hit transfer shows its retries.
    """
    t0 = time.perf_counter()
    n_attempt = [0]

    def attempt() -> None:
        n_attempt[0] += 1
        span = (trace.span("disagg.transfer", attempt=n_attempt[0],
                           target=f"{host}:{port}")
                if trace is not None else contextlib.nullcontext())
        with span:
            conn = wire.connect(host, port,
                                timeout_ms=int(connect_timeout_s * 1000))
            try:
                conn.send(XFER_SNAPSHOT, payload)
                # the ACK waits on the receiver's parse only
                # (pool-pressure deferral happens after the ACK, inside
                # the engine FIFO), so one generous quiescence deadline
                # covers it
                mtype, body = conn.recv(timeout=ack_timeout_s)
            finally:
                conn.close()
            if mtype == XFER_ACK:
                return
            if mtype == XFER_REJECT:
                raise TransferRejected(
                    body.decode(errors="replace") or "snapshot rejected")
            raise wire.WireError(
                f"unexpected transfer reply frame type {mtype}")

    policy = RetryPolicy(deadline_s=deadline_s, base_s=0.05, cap_s=1.0)
    try:
        retry_call(attempt, policy,
                   retry_on=(OSError, wire.WireError),
                   describe=f"kv transfer to {host}:{port}", rng=rng,
                   sleep=sleep)
    except TransferRejected:
        TRANSFER_FAILURES.inc()
        raise
    except (OSError, wire.WireError) as e:
        TRANSFER_FAILURES.inc()
        raise TransferError(
            f"kv transfer to {host}:{port} failed after "
            f"{time.perf_counter() - t0:.1f}s: {e}") from e
    TRANSFER_MS.observe((time.perf_counter() - t0) * 1e3)
    TRANSFER_BYTES.observe(len(payload))


class TransferServer:
    """Framed snapshot receiver in front of one serve scheduler.

    Accepts connections on its own port (``--transfer-port``), reads
    ``XFER_SNAPSHOT`` frames, hands each payload to the scheduler's
    import inbox (parsed + registered ON the engine thread — the only
    thread allowed to touch the engine/pool), and answers ``XFER_ACK``
    or ``XFER_REJECT``. A connection serves any number of snapshots
    (prefill replicas keep theirs open across handoffs).
    """

    def __init__(self, scheduler, bind: str = "127.0.0.1", port: int = 0,
                 accept_timeout_s: float = 30.0):
        self.scheduler = scheduler
        self.accept_timeout_s = accept_timeout_s
        self._listener = wire.Listener(bind, port)
        self.port = self._listener.port
        self.bind = bind
        self._stop = threading.Event()
        self._conns: list[wire.Connection] = []  # live, for stop()
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="cake-disagg-transfer")

    def start(self) -> "TransferServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._listener.close()
        for conn in list(self._conns):  # unblock parked handlers
            conn.close()
        self._thread.join(timeout=5.0)

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn = self._listener.accept()
            except (OSError, wire.WireError):
                return  # listener closed (stop) or unusable
            self._conns.append(conn)  # owner; handler removes on exit
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: wire.Connection) -> None:
        try:
            while not self._stop.is_set():
                # a replica legitimately idles between handoffs; the
                # accept-side deadline only bounds a WEDGED peer (the
                # same SO_RCVTIMEO quiescence semantics as the worker)
                try:
                    mtype, payload = conn.recv(
                        timeout=self.accept_timeout_s)
                except wire.WireTimeout:
                    return  # idle/wedged peer: drop; senders reconnect
                if mtype != XFER_SNAPSHOT:
                    conn.send(XFER_REJECT,
                              f"unexpected frame type {mtype}".encode())
                    return
                try:
                    self.scheduler.submit_import(payload)
                except TimeoutError as e:
                    # TRANSIENT: the engine thread is busy/wedged, not a
                    # verdict on the bytes — close the connection so the
                    # sender's transport retry (idempotent resend, deduped
                    # by transfer id) gets another shot, instead of a
                    # never-retried XFER_REJECT
                    log.warning("transfer import timed out: %s", e)
                    return
                except ValueError as e:
                    # deterministic refusal (mismatch/malformed/engine
                    # cannot import): tell the sender NOT to retry
                    log.warning("transfer import refused: %s", e)
                    conn.send(XFER_REJECT, str(e).encode())
                    continue
                conn.send(XFER_ACK)
        except (OSError, wire.WireError):
            pass  # peer went away mid-exchange; it owns the retry
        finally:
            conn.close()
            try:
                self._conns.remove(conn)
            except ValueError:
                pass  # stop() raced the removal
