"""Versioned, self-describing snapshot of one stream's KV pages + state.

Layout (little-endian)::

    magic "CKXF" | u16 version | u32 header_len | header JSON | page blobs

The JSON header carries everything host-sided: the transfer id, a model
**fingerprint** (layer/head/dtype/page geometry — an import refuses a
snapshot whose geometry does not match its own pool, the same
max_seq-mismatch rule the worker handshake enforces), the stream state
(prompt, generated tokens, KV frontier ``pos``, absolute token ``index``,
the raw per-stream sampling key, repeat-penalty ring + slot, feedback
token), the constrained-decoding cursor (the ``response_format`` spec +
DFA state, so the importer recompiles the cached DFA and resumes
mid-grammar), and the byte length of every page blob that follows.

Page blobs are the stream's physical KV pages in logical order, each
tensor serialized through :func:`cake_tpu.runtime.protocol.
encode_activation` — the SAME ``--wire-codec`` path the distributed
decode plane ships activations through (``none``/``bf16``/``int8``,
self-describing, counted in ``wire.codec_bytes_*``). Quantization
*scales* of an int8 KV pool always ride ``none``: compressing the scale
of a quantization would corrupt the cache it scales. Bit-identity
contract: the round trip is bit-identical whenever the codec is lossless
for the page dtype — ``none`` always, ``bf16`` on a bf16 cache (2-byte
floats ship verbatim), ``int8`` on an int8-quantized pool (integer
payloads pass through, scales ride ``none``).
"""

from __future__ import annotations

import json
import struct

import numpy as np

from cake_tpu.runtime.protocol import (
    check_codec,
    decode_activation,
    encode_activation,
)

MAGIC = b"CKXF"
SNAPSHOT_VERSION = 1
_HEAD = struct.Struct("<4sHI")  # magic, version, header_len


class SnapshotError(ValueError):
    """Malformed snapshot bytes (bad magic/version/layout)."""


class SnapshotMismatch(SnapshotError):
    """A well-formed snapshot whose model fingerprint does not match the
    importing engine — deterministic, never retried (the same bytes
    would mismatch again)."""


def _codec_for(name: str, codec: str) -> str:
    """Per-tensor codec choice: quantization scales (the ``ks``/``vs``
    halves of an int8 pool page) always ship lossless — see module
    docstring."""
    if name in ("ks", "vs"):
        return "none"
    return codec


class Snapshot:
    """Parsed snapshot: header fields + per-page tensor dicts.

    ``pages`` is a list of ``{"k": arr, "v": arr}`` (plain KV) or
    ``{"kq", "ks", "vq", "vs"}`` (int8-quantized pool) in logical page
    order; each array is ``[L, KH, page_size(, D)]``.
    """

    def __init__(self, xfer_id: str, fingerprint: dict, codec: str,
                 stream_id: int, prompt: list[int], generated: list[int],
                 pos: int, index: int, last_token: int, key: np.ndarray,
                 history: np.ndarray, hist_slot: int,
                 guide_spec: dict | None, guide_state: int,
                 pages: list[dict], trace: dict | None = None):
        self.xfer_id = xfer_id
        self.stream_id = int(stream_id)
        self.fingerprint = fingerprint
        self.codec = codec
        self.prompt = list(prompt)
        self.generated = list(generated)
        self.pos = int(pos)
        self.index = int(index)
        self.last_token = int(last_token)
        self.key = np.asarray(key, np.uint32)
        self.history = np.asarray(history, np.int32)
        self.hist_slot = int(hist_slot)
        self.guide_spec = guide_spec
        self.guide_state = int(guide_state)
        self.pages = pages
        # request-scoped trace context riding the frame metadata
        # (obs/reqtrace: {"id", "parent", "request"}); optional — absent
        # on snapshots from untraced exporters
        self.trace = trace

    @property
    def n_pages(self) -> int:
        return len(self.pages)

    def check_fingerprint(self, fp: dict) -> None:
        if self.fingerprint != fp:
            theirs = {k: v for k, v in self.fingerprint.items()
                      if fp.get(k) != v}
            ours = {k: fp.get(k) for k in theirs}
            raise SnapshotMismatch(
                f"snapshot fingerprint mismatch: snapshot has {theirs}, "
                f"this engine has {ours}")


# fixed per-page tensor order inside the blob stream
_PLAIN_KEYS = ("k", "v")
_QUANT_KEYS = ("kq", "ks", "vq", "vs")


def encode_snapshot(xfer_id: str, fingerprint: dict, codec: str,
                    stream_id: int, prompt: list[int],
                    generated: list[int], pos: int, index: int,
                    last_token: int, key, history, hist_slot: int,
                    guide_spec: dict | None, guide_state: int,
                    pages: list[dict],
                    trace: dict | None = None) -> bytes:
    """Serialize one stream's state + pages (see module docstring)."""
    check_codec(codec)
    keys = _QUANT_KEYS if pages and "kq" in pages[0] else _PLAIN_KEYS
    blobs: list[bytes] = []
    for page in pages:
        for k in keys:
            arr = np.asarray(page[k])
            blobs.append(encode_activation(arr, _codec_for(k, codec)))
    header = {
        "v": SNAPSHOT_VERSION,
        "id": xfer_id,
        "fp": fingerprint,
        "codec": codec,
        "quant": keys is _QUANT_KEYS,
        "stream": {
            "sid": int(stream_id),
            "prompt": list(map(int, prompt)),
            "generated": list(map(int, generated)),
            "pos": int(pos),
            "index": int(index),
            "last": int(last_token),
            "key": [int(x) for x in np.asarray(key, np.uint32).ravel()],
            "history": [int(x) for x in np.asarray(history, np.int64)],
            "hist_slot": int(hist_slot),
        },
        "guide": ({"spec": guide_spec, "state": int(guide_state)}
                  if guide_spec is not None else None),
        "blobs": [len(b) for b in blobs],
        "tensors_per_page": len(keys),
    }
    if trace:
        # optional key: old decoders ignore it, new ones .get it — no
        # version bump needed for a metadata-only addition
        header["trace"] = trace
    hj = json.dumps(header).encode()
    return b"".join([_HEAD.pack(MAGIC, SNAPSHOT_VERSION, len(hj)), hj,
                     *blobs])


def _header_of(data) -> tuple[dict, int]:
    buf = memoryview(data)
    if len(buf) < _HEAD.size:
        raise SnapshotError("snapshot truncated before header")
    magic, ver, hlen = _HEAD.unpack_from(buf, 0)
    if magic != MAGIC:
        raise SnapshotError(f"bad snapshot magic {bytes(magic)!r}")
    if ver != SNAPSHOT_VERSION:
        raise SnapshotError(f"unsupported snapshot version {ver} "
                            f"(this build speaks {SNAPSHOT_VERSION})")
    end = _HEAD.size + hlen
    if len(buf) < end:
        raise SnapshotError("snapshot truncated inside header")
    try:
        header = json.loads(bytes(buf[_HEAD.size:end]).decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise SnapshotError(f"bad snapshot header JSON: {e}")
    return header, end


def peek_xfer_id(data) -> str:
    """Transfer id without decoding page payloads — the idempotency key
    a receiver dedups resent snapshots by (a retry after a lost ACK
    delivers the same bytes twice)."""
    header, _ = _header_of(data)
    return str(header["id"])


def decode_snapshot(data) -> Snapshot:
    """Parse snapshot bytes into a :class:`Snapshot` (pages decoded to
    host numpy in their pre-codec dtype)."""
    header, off = _header_of(data)
    buf = memoryview(data)
    st = header["stream"]
    keys = _QUANT_KEYS if header.get("quant") else _PLAIN_KEYS
    per = header.get("tensors_per_page", len(keys))
    if per != len(keys):
        raise SnapshotError(
            f"snapshot carries {per} tensors per page, expected "
            f"{len(keys)}")
    lens = header["blobs"]
    if len(lens) % per:
        raise SnapshotError(
            f"{len(lens)} page blobs do not divide into {per}-tensor "
            "pages")
    pages: list[dict] = []
    cursor = off
    vals: list[np.ndarray] = []
    for n in lens:
        end = cursor + int(n)
        if end > len(buf):
            raise SnapshotError("snapshot truncated inside page blobs")
        arr, _codec = decode_activation(buf[cursor:end])
        vals.append(arr)
        cursor = end
        if len(vals) == per:
            pages.append(dict(zip(keys, vals)))
            vals = []
    if cursor != len(buf):
        raise SnapshotError(
            f"{len(buf) - cursor} trailing bytes after page blobs")
    guide = header.get("guide")
    return Snapshot(
        xfer_id=str(header["id"]),
        fingerprint=dict(header["fp"]),
        codec=str(header["codec"]),
        stream_id=st.get("sid", 0),
        prompt=st["prompt"],
        generated=st["generated"],
        pos=st["pos"],
        index=st["index"],
        last_token=st["last"],
        key=np.asarray(st["key"], np.uint32),
        history=np.asarray(st["history"], np.int32),
        hist_slot=st["hist_slot"],
        guide_spec=guide["spec"] if guide else None,
        guide_state=guide["state"] if guide else 0,
        pages=pages,
        trace=header.get("trace"),
    )
