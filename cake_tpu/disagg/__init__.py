"""Disaggregated prefill/decode serving: KV-page export/import + transfer.

The paper's design — and this repo's serve plane until ISSUE 13 — walks
every request through one engine: a long prefill dispatch on a mixed
replica inflates every decoding neighbor's TPOT, and TTFT p95 is hostage
to batch composition. This package splits the two phases across replica
tiers:

- :mod:`cake_tpu.disagg.snapshot` — a versioned, self-describing
  snapshot of one stream's KV pages + sampler/cursor state
  (``BatchGenerator.export_stream`` / ``import_stream``), serialized
  per-page through the existing wire activation codec
  (``--wire-codec none|bf16|int8``). Round-trips are bit-identical to an
  uninterrupted stream whenever the codec is lossless for the cache
  dtype (``none`` always; ``bf16`` on a bf16 cache; ``int8`` on an
  int8-quantized pool) — which alone buys session suspend/resume and
  multi-turn reconnection;
- :mod:`cake_tpu.disagg.transfer` — the length-prefixed transfer channel
  between replicas: :mod:`cake_tpu.runtime.wire` framing (magic + type +
  length + CRC trailer) with retry/backoff on
  :class:`cake_tpu.runtime.retry.RetryPolicy`, so the chaos proxy and
  every recovery lesson of the worker wire plane apply verbatim.

The serve plane grows ``--role prefill|decode|mixed`` on top
(``serve/scheduler.py``): prefill replicas run bucketed prefill only and
hand the finished pages to a decode replica; decode replicas import
pages straight into the pool (page-table edits, no cache-tensor
splices) and run only the steady-state batched step. The gateway
(``gateway/api.py``) learns the two-stage route — prefill tier by queue
depth, decode tier by p2c + prefix affinity — with fallback to mixed
replicas and transparent re-prefill on a transfer failure.
"""

from cake_tpu.disagg.snapshot import (  # noqa: F401
    SNAPSHOT_VERSION,
    SnapshotError,
    SnapshotMismatch,
    decode_snapshot,
    encode_snapshot,
    peek_xfer_id,
)
from cake_tpu.disagg.transfer import (  # noqa: F401
    TransferError,
    TransferRejected,
    TransferServer,
    send_snapshot,
)
