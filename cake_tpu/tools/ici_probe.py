"""ici_probe: per-hop inter-stage latency/bandwidth over the mesh ring.

BASELINE.json names "inter-layer ICI latency" as a metric of record; the
reference's analogue is its per-connection handshake RTT + per-op TCP
timing (`client.rs:76-84`, `worker.rs:226-254`) — here the inter-stage
link is the compiler-scheduled `lax.ppermute` the pipeline rides
(`parallel/pipeline.py`), so the probe times exactly that collective over
the same ``stage`` ring the decoder uses.

Method: one jitted shard_map program scans R back-to-back ppermutes of a
[payload] activation-shaped buffer (scan amortizes dispatch, the data
dependency serializes hops), timed over the mesh's ``stage`` axis. Per
hop: ``dt / R``; bandwidth: ``payload_bytes / hop``. Run on a real pod
slice for ICI numbers; on the CPU test mesh it proves the machinery (the
numbers are host-memcpy, labeled as such).

Usage:  python -m cake_tpu.tools.ici_probe [--stages N] [--reps R]
            [--json-out PATH]
Prints one JSON line per payload size:
  {"payload_bytes", "hops", "per_hop_us", "gbps", "device", "n_stages"}
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from cake_tpu.parallel.mesh import STAGE, make_mesh, shard_map


def _build_ring(mesh, n: int, reps: int):
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(x):
        def step(c, _):
            return jax.lax.ppermute(c, STAGE, perm), None

        out, _ = jax.lax.scan(step, x, None, length=reps)
        return out

    return jax.jit(shard_map(
        body, mesh=mesh, in_specs=P(STAGE), out_specs=P(STAGE),
        check_vma=False,
    ))


def probe(stages: int | None = None, reps: int = 64,
          json_out: str | None = None) -> list:
    devices = jax.devices()
    n = stages or len(devices)
    if n < 2:
        sys.stderr.write(
            "ici_probe needs >= 2 devices to form a ring (a single chip "
            "has no inter-stage link to measure)\n"
        )
        return []
    mesh = make_mesh(num_stages=n, devices=devices[:n])
    dev = devices[0]
    results = []
    for payload in (1 << 12, 1 << 16, 1 << 20, 1 << 24):
        elems = payload // 2  # bf16 activation-shaped payload
        per_shard = max(1, elems // n)
        x = jnp.zeros((per_shard * n,), jnp.bfloat16)
        fn = _build_ring(mesh, n, reps)
        out = fn(x)
        np.asarray(out.addressable_shards[0].data.ravel()[:1])  # compile+sync
        t0 = time.perf_counter()
        out = fn(x)
        np.asarray(out.addressable_shards[0].data.ravel()[:1])
        dt = time.perf_counter() - t0
        hop = dt / reps
        rec = {
            "payload_bytes": per_shard * 2,
            "hops": reps,
            "per_hop_us": round(hop * 1e6, 2),
            "gbps": round(per_shard * 2 / hop / 1e9, 3),
            "device": getattr(dev, "device_kind", "cpu"),
            "n_stages": n,
        }
        results.append(rec)
        print(json.dumps(rec), flush=True)
    if json_out:
        with open(json_out, "w") as f:
            json.dump(results, f, indent=1)
    return results


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--stages", type=int, default=None)
    ap.add_argument("--reps", type=int, default=64)
    ap.add_argument("--json-out", default=None)
    args = ap.parse_args()
    probe(args.stages, args.reps, args.json_out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
