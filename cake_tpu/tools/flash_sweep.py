"""Flash-attention crossover sweep: pallas vs XLA across context lengths.

Measures the compiled flash kernels against the reference-math XLA oracle
(`cake-core/src/model/attention.rs:62-77` f32-scores convention) over a grid
of (T, S) shapes at Llama-3-8B attention geometry, to pick the context-length
crossover used by :func:`cake_tpu.ops.attention.attend`'s ``impl="auto"``
dispatch — the same measured-crossover treatment ``quant_matmul`` got for its
M>=16 gate (`ops/quant.py`).

Usage:  python -m cake_tpu.tools.flash_sweep [--json-out PATH]

Prints one JSON line per shape:
  {"path": "prefill"|"decode", "t", "s", "pallas_ms", "xla_ms", "speedup"}
"""

from __future__ import annotations

import argparse
import json
import sys
from functools import partial

import jax
import jax.numpy as jnp

from cake_tpu.tools.kernel_check import _time_ms


def _audit(rec: dict) -> dict:
    """Annotate a sweep record with what ``impl='auto'`` dispatches at this
    shape and the resulting speedup over always-XLA (>= 1.0 everywhere is
    the dispatch-policy contract)."""
    from cake_tpu.ops.attention import PREFILL_FLASH_MIN_S

    auto = ("flash" if rec["path"] == "prefill"
            and rec["s"] >= PREFILL_FLASH_MIN_S else "xla")
    rec["auto_impl"] = auto
    rec["auto_speedup"] = rec["speedup"] if auto == "flash" else 1.0
    return rec


def sweep(json_out: str | None = None) -> list:
    from cake_tpu.ops.attention import _attend_xla
    from cake_tpu.ops.pallas import flash_attention, flash_decode, interpret_default

    compiled = not interpret_default()
    dev = jax.devices()[0]
    sys.stderr.write(f"device={dev.device_kind} compiled={compiled}\n")
    b, h, kvh, d = 1, 32, 8, 128
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 3)

    class _Flushed(list):
        """append() also rewrites json_out — a mid-sweep crash (r4w2:
        flash_sweep died on a Mosaic lowering rule mid-run and the
        committed artifact lost every landed row) keeps its evidence."""

        def append(self, rec) -> None:
            super().append(rec)
            if json_out:
                with open(json_out, "w") as f:
                    json.dump(list(self), f, indent=1)

    results = _Flushed()

    f_pal = jax.jit(partial(flash_attention, interpret=not compiled))
    fd_pal = jax.jit(partial(flash_decode, interpret=not compiled))
    f_xla = jax.jit(_attend_xla)

    # Decode: T=1 against a KV buffer of S. Frontier-near-the-end rows are
    # the worst case (XLA must sweep ~everything either way); the EARLY-
    # frontier rows in a long window are the one regime where flash decode
    # has a structural edge — it reads KV blocks only up to the frontier
    # while XLA's fused gemv sweeps the whole buffer. The early rows are
    # the measurement `ops/attention.py` used to claim without evidence
    # (r3 verdict item 8); they decide whether `auto` gets a
    # frontier-aware dispatch or the claim dies.
    for s, p in ((512, 488), (1024, 1000), (2048, 2024), (4096, 4072),
                 (8192, 8168),  # late frontier (s - 24)
                 (4096, 512), (8192, 512), (8192, 2048), (16384, 1024)):
        kv_k = jax.random.normal(ks[0], (b, kvh, s, d), jnp.bfloat16)
        kv_v = jax.random.normal(ks[1], (b, kvh, s, d), jnp.bfloat16)
        q = jax.random.normal(ks[2], (b, h, 1, d), jnp.bfloat16)
        pos = jnp.int32(p)
        p_ms = _time_ms(fd_pal, q, kv_k, kv_v, pos)
        x_ms = _time_ms(f_xla, q, kv_k, kv_v, pos)
        rec = _audit({"path": "decode", "t": 1, "s": s, "pos": p,
                      "pallas_ms": round(p_ms, 4), "xla_ms": round(x_ms, 4),
                      "speedup": round(x_ms / p_ms, 3)})
        results.append(rec)
        print(json.dumps(rec), flush=True)

    # Batched (serving) decode: per-row frontiers, the BatchGenerator shape
    for bb, s in ((8, 1024), (8, 4096), (32, 1024), (32, 4096)):
        kv_k = jax.random.normal(ks[0], (bb, kvh, s, d), jnp.bfloat16)
        kv_v = jax.random.normal(ks[1], (bb, kvh, s, d), jnp.bfloat16)
        q = jax.random.normal(ks[2], (bb, h, 1, d), jnp.bfloat16)
        pos = jnp.clip(
            jnp.arange(1, bb + 1, dtype=jnp.int32) * (s // (bb + 1)),
            16, s - 2,
        )
        p_ms = _time_ms(fd_pal, q, kv_k, kv_v, pos)
        x_ms = _time_ms(f_xla, q, kv_k, kv_v, pos)
        rec = _audit({"path": "decode", "t": 1, "s": s, "batch": bb,
                      "pallas_ms": round(p_ms, 4), "xla_ms": round(x_ms, 4),
                      "speedup": round(x_ms / p_ms, 3)})
        results.append(rec)
        print(json.dumps(rec), flush=True)

    # Prefill: chunk of T tokens against a window of S (T <= S); both the
    # full-prompt case (T = S/2, frontier mid-buffer) and the chunked case
    # (small T against a large populated window) appear in real runs.
    for t, s in ((256, 512), (512, 1024), (512, 2048), (1024, 2048),
                 (512, 4096), (2048, 4096), (2048, 8192), (512, 8192)):
        kv_k = jax.random.normal(ks[0], (b, kvh, s, d), jnp.bfloat16)
        kv_v = jax.random.normal(ks[1], (b, kvh, s, d), jnp.bfloat16)
        q = jax.random.normal(ks[2], (b, h, t, d), jnp.bfloat16)
        pos = jnp.int32(s - t - 8)  # frontier near the end: max valid keys
        inner = max(2, min(32, (2048 * 4096) // (t * s) * 4))
        p_ms = _time_ms(f_pal, q, kv_k, kv_v, pos, inner=inner)
        x_ms = _time_ms(f_xla, q, kv_k, kv_v, pos, inner=inner)
        rec = _audit({"path": "prefill", "t": t, "s": s,
                      "pallas_ms": round(p_ms, 4), "xla_ms": round(x_ms, 4),
                      "speedup": round(x_ms / p_ms, 3)})
        results.append(rec)
        print(json.dumps(rec), flush=True)

    # Windowed decode (Mistral sliding window): the kernel reads ~W of KV
    # bytes where XLA sweeps+masks the whole buffer — the structural case
    # grows with S/W. auto currently stays XLA (measured-crossover rule);
    # a winning row here is what flips it.
    @jax.jit
    def fd_pal_w(q, kk_, vv_, pos):
        return flash_decode(q, kk_, vv_, pos, window=4096,
                            interpret=not compiled)

    @jax.jit
    def fd_xla_w(q, kk_, vv_, pos):
        return _attend_xla(q, kk_, vv_, pos, window=4096)

    for s in (8192, 16384):
        kv_k = jax.random.normal(ks[0], (b, kvh, s, d), jnp.bfloat16)
        kv_v = jax.random.normal(ks[1], (b, kvh, s, d), jnp.bfloat16)
        q1 = jax.random.normal(ks[2], (b, h, 1, d), jnp.bfloat16)
        pos = jnp.int32(s - 8)
        p_ms = _time_ms(fd_pal_w, q1, kv_k, kv_v, pos)
        x_ms = _time_ms(fd_xla_w, q1, kv_k, kv_v, pos)
        rec = {"path": "decode_win4096", "t": 1, "s": s,
               "pallas_ms": round(p_ms, 4), "xla_ms": round(x_ms, 4),
               "speedup": round(x_ms / p_ms, 3),
               "auto_impl": "xla", "auto_speedup": 1.0}
        results.append(rec)
        print(json.dumps(rec), flush=True)

    # Windowed prefill (Mistral sliding window): the kernel's block sweep
    # is window-proportional (out-of-window KV blocks never fetched) vs
    # the XLA path's full-history sweep+mask. Window 4096 at an 8K/16K
    # frontier is the Mistral-7B geometry of record.
    @jax.jit
    def f_pal_w(q, kk_, vv_, pos):
        return flash_attention(q, kk_, vv_, pos, window=4096,
                               interpret=not compiled)

    @jax.jit
    def f_xla_w(q, kk_, vv_, pos):
        return _attend_xla(q, kk_, vv_, pos, window=4096)

    for t, s in ((2048, 8192), (512, 8192), (2048, 16384)):
        kv_k = jax.random.normal(ks[0], (b, kvh, s, d), jnp.bfloat16)
        kv_v = jax.random.normal(ks[1], (b, kvh, s, d), jnp.bfloat16)
        q = jax.random.normal(ks[2], (b, h, t, d), jnp.bfloat16)
        pos = jnp.int32(s - t - 8)
        inner = max(2, min(32, (2048 * 4096) // (t * s) * 4))
        p_ms = _time_ms(f_pal_w, q, kv_k, kv_v, pos, inner=inner)
        x_ms = _time_ms(f_xla_w, q, kv_k, kv_v, pos, inner=inner)
        full_ms = _time_ms(f_pal, q, kv_k, kv_v, pos, inner=inner)
        rec = {"path": "prefill_win4096", "t": t, "s": s,
               "pallas_ms": round(p_ms, 4), "xla_ms": round(x_ms, 4),
               "full_flash_ms": round(full_ms, 4),
               "speedup": round(x_ms / p_ms, 3),
               "auto_impl": "flash", "auto_speedup": round(x_ms / p_ms, 3)}
        results.append(rec)
        print(json.dumps(rec), flush=True)

    # Int8-KV prefill: the quantization-aware flash kernel vs the XLA path
    # over trace-level-dequantized buffers (what the dispatch uses below
    # the crossover) — the long-context plane of the quantized cache.
    from cake_tpu.ops.kvcache import dequant_kv, quant_kv
    from cake_tpu.ops.pallas import flash_attention_q8

    fq8 = jax.jit(partial(flash_attention_q8, interpret=not compiled))

    @jax.jit
    def xla_deq(q, kq, ksc, vq, vsc, pos):
        from cake_tpu.ops.kvcache import QuantizedKV

        return _attend_xla(q,
                           dequant_kv(QuantizedKV(q=kq, scale=ksc), q.dtype),
                           dequant_kv(QuantizedKV(q=vq, scale=vsc), q.dtype),
                           pos)

    for t, s in ((512, 2048), (2048, 4096), (2048, 8192)):
        kv_k = quant_kv(jax.random.normal(ks[0], (b, kvh, s, d), jnp.bfloat16))
        kv_v = quant_kv(jax.random.normal(ks[1], (b, kvh, s, d), jnp.bfloat16))
        q = jax.random.normal(ks[2], (b, h, t, d), jnp.bfloat16)
        pos = jnp.int32(s - t - 8)
        inner = max(2, min(32, (2048 * 4096) // (t * s) * 4))
        p_ms = _time_ms(fq8, q, kv_k.q, kv_k.scale, kv_v.q, kv_v.scale, pos,
                        inner=inner)
        x_ms = _time_ms(xla_deq, q, kv_k.q, kv_k.scale, kv_v.q, kv_v.scale,
                        pos, inner=inner)
        rec = {"path": "prefill_q8kv", "t": t, "s": s,
               "pallas_ms": round(p_ms, 4), "xla_ms": round(x_ms, 4),
               "speedup": round(x_ms / p_ms, 3),
               "auto_impl": "flash_q8", "auto_speedup": round(x_ms / p_ms, 3)}
        results.append(rec)
        print(json.dumps(rec), flush=True)

    return list(results)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json-out", default=None)
    args = ap.parse_args()
    sweep(args.json_out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
