"""split-model: pre-split a checkpoint into per-worker bundles.

Equivalent of the reference's `cake-split-model` crate
(cake-split-model/src/main.rs): for each worker in the topology (or one via
--worker), filter the safetensors weight_map by layer ownership
(main.rs:80-106, topology.rs:25-32), copy the matching tensors into
``<name>-node/model/{reduced.safetensors, model.safetensors.index.json}``
(main.rs:108-142,176-200), **verify by re-loading the written file**
(main.rs:202-208), and write a single-worker ``topology.yml``
(main.rs:210-223). Config/tokenizer files are copied alongside so a bundle
is a self-sufficient worker checkpoint.

Usage:
  python -m cake_tpu.tools.split_model \\
      --model-path /path/to/llama --topology topology.yml --output ./bundles
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
from pathlib import Path

import numpy as np

from cake_tpu.parallel.topology import Topology
from cake_tpu.utils.weights import load_safetensors_index


def reduce_for_worker(weight_map: dict[str, str], node) -> dict[str, str]:
    """Filter tensor name -> shard file to the worker's layers
    (main.rs:80-106)."""
    return {
        name: fname
        for name, fname in weight_map.items()
        if node.is_layer_owner(name)
    }


def split_for_worker(model_dir: Path, out_root: Path, topology: Topology,
                     node) -> Path:
    from safetensors import safe_open
    from safetensors.numpy import save_file

    name_to_file = load_safetensors_index(model_dir)
    weight_map = {n: str(f.name) for n, f in name_to_file.items()}
    reduced = reduce_for_worker(weight_map, node)
    if not reduced:
        raise ValueError(f"worker '{node.name}' owns no tensors")

    out_dir = out_root / f"{node.name}-node" / "model"
    out_dir.mkdir(parents=True, exist_ok=True)

    # copy matching tensors out of the mmap'd shards (main.rs:108-142)
    tensors: dict[str, np.ndarray] = {}
    handles: dict[Path, object] = {}
    try:
        for tname in sorted(reduced):
            f = name_to_file[tname]
            if f not in handles:
                handles[f] = safe_open(f, framework="np")
            tensors[tname] = handles[f].get_tensor(tname)
    finally:
        for h in handles.values():
            if hasattr(h, "close"):
                h.close()

    out_file = out_dir / "reduced.safetensors"
    save_file(tensors, out_file)
    index = {
        "metadata": {
            "total_size": int(sum(t.nbytes for t in tensors.values()))
        },
        "weight_map": {n: "reduced.safetensors" for n in tensors},
    }
    (out_dir / "model.safetensors.index.json").write_text(json.dumps(index))

    # self-check: re-open the written file and verify every tensor resolves
    # to exactly one shard (main.rs:202-208)
    with safe_open(out_file, framework="np") as sf:
        written = set(sf.keys())
    if written != set(tensors):
        raise RuntimeError(
            f"verification failed for '{node.name}': wrote {len(tensors)} "
            f"tensors, file has {len(written)}"
        )

    # config/tokenizer travel with the bundle
    for aux in ("config.json", "tokenizer.json", "tokenizer_config.json"):
        src = model_dir / aux
        if src.exists():
            shutil.copy(src, out_dir / aux)

    # single-worker topology (main.rs:210-223)
    single = Topology.from_dict({node.name: {
        "host": node.host, "description": node.description,
        "layers": list(node.layers),
    }})
    single.save(out_root / f"{node.name}-node" / "topology.yml")
    return out_dir


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cake-split-model")
    p.add_argument("--model-path", required=True)
    p.add_argument("--topology", required=True)
    p.add_argument("--output", required=True)
    p.add_argument("--worker", default=None,
                   help="split only this worker (default: all)")
    args = p.parse_args(argv)

    model_dir = Path(args.model_path)
    topology = Topology.from_path(args.topology)
    out_root = Path(args.output)

    nodes = list(topology)
    if args.worker:
        if args.worker not in topology:
            sys.exit(f"error: worker '{args.worker}' not in topology")
        nodes = [topology[args.worker]]

    for node in nodes:
        out = split_for_worker(model_dir, out_root, topology, node)
        total = sum(
            f.stat().st_size for f in out.glob("reduced.safetensors")
        )
        print(f"{node.name}: {out} ({total / 1e6:.1f} MB)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
