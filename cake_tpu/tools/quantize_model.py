"""quantize-model: write a pre-quantized int8 or int4 checkpoint.

Quantize-on-load (``--quantize int8``/``int4``) re-runs per-channel
quantization on every start — minutes of host work for 70B-class
checkpoints, on every host. This tool pays that cost ONCE, offline (the
same role the reference's `cake-split-model` plays for layer filtering,
main.rs:144-223): each linear is quantized per-output-channel (the one
convention, ops/quant.py) and stored as two tensors

    <hf_name>.q8     int8, HF [out, in] orientation        (--bits 8)
    <hf_name>.q4     int8 packed two-per-byte, [out, in/2]  (--bits 4)
    <hf_name>.scale  f32 [out]

alongside the untouched norms/embedding. Loaders (utils/weights.py,
utils/sharded_load.py) detect the ``.q8``/``.q4`` names and read the
quantized bytes directly — startup reads a fraction of the bytes and does
zero quantize compute, and sharded loads slice the stored scales instead
of reading full weights. Like the reference splitter, the written file is
verified by re-loading it.

Usage:
  python -m cake_tpu.tools.quantize_model \\
      --model-path /path/to/llama --output /path/to/llama-int8 [--bits 4]
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
from pathlib import Path

import numpy as np

from cake_tpu.ops.quant import (LAYER_LINEARS, quantize_linear4_np,
                                quantize_linear_np)
from cake_tpu.utils.weights import (_LAYER_MAP, _MOE_EXPERT_MAP,
                                    load_safetensors_index)

# HF names of quantizable linears (torch [out, in] orientation), DERIVED
# from the single source of truth (weights._LAYER_MAP filtered by
# quant.LAYER_LINEARS) so a future linear cannot drift out of sync between
# this tool and the loaders; everything else (norms, embedding) passes
# through unchanged
# Mixtral expert linears are int8-quantizable like any [out, in] linear
# (router/norms pass through); their suffixes are DERIVED from
# weights._MOE_EXPERT_MAP, same single-source rule as the dense list.
_LINEAR_SUFFIXES = tuple(_LAYER_MAP[k][0] for k in LAYER_LINEARS) + tuple(
    p.split("{e}.")[-1] for p in _MOE_EXPERT_MAP.values()
)


def _is_linear(name: str) -> bool:
    return (name == "lm_head.weight"
            or any(name.endswith(s) for s in _LINEAR_SUFFIXES))


def quantize_checkpoint(model_path: str | Path, output: str | Path,
                        shard_bytes: int = 4 << 30, bits: int = 8,
                        group_size: int | None = None) -> Path:
    """Quantize every linear of the checkpoint at ``model_path`` into
    ``output`` (config/tokenizer copied alongside); returns ``output``.
    ``bits`` selects the tier: 8 (``.q8``) or 4 (packed ``.q4``);
    ``group_size`` (int4 only) writes group-wise scales — int4's accuracy
    tier for real checkpoints, detected by the loaders from the stored
    scale's shape.

    Output is written incrementally in ~``shard_bytes`` safetensors shards
    — host RAM is bounded by one shard, not the checkpoint (a 70B-class
    model never materializes in memory)."""
    from safetensors import safe_open
    from safetensors.numpy import save_file

    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    if group_size is not None and bits != 4:
        raise ValueError("--group-size applies to --bits 4 only")
    qsuffix = ".q8" if bits == 8 else ".q4"
    if bits == 8:
        np_qfn = quantize_linear_np
    else:
        import functools

        np_qfn = functools.partial(quantize_linear4_np,
                                   group_size=group_size)
    model_path, output = Path(model_path), Path(output)
    output.mkdir(parents=True, exist_ok=True)
    name_to_file = load_safetensors_index(model_path)
    from cake_tpu.utils.weights import detect_family, is_prequantized

    if is_prequantized(name_to_file):
        raise ValueError(
            f"{model_path} is already pre-quantized (.q8/.scale tensors); "
            "re-quantizing it would only copy bytes"
        )
    if detect_family(name_to_file)[0] and bits == 4:
        # don't burn the offline pass producing an artifact the loaders
        # would reject
        from cake_tpu.ops.quant import reject_int4_moe

        reject_int4_moe()

    handles: dict[Path, object] = {}

    def get(name: str) -> np.ndarray:
        f = name_to_file[name]
        if f not in handles:
            handles[f] = safe_open(f, framework="np")
        return handles[f].get_tensor(name)

    n_q = 0
    total = 0
    weight_map: dict[str, str] = {}
    pending: dict[str, np.ndarray] = {}
    pending_bytes = 0
    shard_idx = 0

    def flush():
        nonlocal pending, pending_bytes, shard_idx
        if not pending:
            return
        fname = f"model-{shard_idx:05d}.safetensors"
        save_file(pending, output / fname)
        for k in pending:
            weight_map[k] = fname
        shard_idx += 1
        pending = {}
        pending_bytes = 0

    def emit(name: str, arr: np.ndarray):
        nonlocal pending_bytes, total
        # belt-and-braces: safetensors serializes the raw buffer, so a
        # strided/F-ordered array would be scrambled on disk
        arr = np.ascontiguousarray(arr)
        pending[name] = arr
        pending_bytes += arr.nbytes
        total += arr.nbytes
        if pending_bytes >= shard_bytes:
            flush()

    for name in sorted(name_to_file):
        w = get(name)
        if _is_linear(name):
            # stored [out, in]; scale is per out channel, computed over the
            # in axis — quantize in the logical [in, out] layout and store
            # back transposed so the file keeps the HF orientation (int4:
            # [out, in/2], packed along the in axis)
            q, scale = np_qfn(w.T)
            emit(f"{name}{qsuffix}", np.ascontiguousarray(q.T))
            emit(f"{name}.scale", scale)
            n_q += 1
        else:
            emit(name, np.ascontiguousarray(w))
    flush()
    for h in handles.values():
        if hasattr(h, "close"):
            h.close()

    index = {
        "metadata": {"total_size": int(total),
                     "cake_quant": ("int8" if bits == 8 else
                                    f"int4:g{group_size}" if group_size
                                    else "int4")},
        "weight_map": weight_map,
    }
    (output / "model.safetensors.index.json").write_text(json.dumps(index))
    for extra in ("config.json", "tokenizer.json", "tokenizer_config.json"):
        src = model_path / extra
        if src.exists():
            shutil.copy2(src, output / extra)

    # self-check: re-open every written shard and verify all tensors
    # resolve (the reference splitter's reload verification,
    # main.rs:202-208)
    seen: set[str] = set()
    for fname in sorted(set(weight_map.values())):
        with safe_open(output / fname, framework="np") as sf:
            names = set(sf.keys())
            seen |= names
            probe = next(
                (n for n in names if n.endswith(qsuffix)), None)
            if probe and sf.get_tensor(probe).dtype != np.int8:
                raise RuntimeError(
                    f"self-check failed: {qsuffix} tensor not int8 storage")
    missing = set(weight_map) - seen
    if missing:
        raise RuntimeError(f"self-check failed: missing {missing}")
    print(f"quantized {n_q} linears -> {output} "
          f"({len(set(weight_map.values()))} shard(s), {total / 1e9:.2f} GB)")
    return output


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model-path", required=True)
    ap.add_argument("--output", required=True)
    ap.add_argument("--bits", type=int, choices=[4, 8], default=8)
    ap.add_argument("--group-size", type=int, default=None,
                    help="int4 group-wise scale rows (accuracy tier)")
    args = ap.parse_args()
    try:
        quantize_checkpoint(args.model_path, args.output, bits=args.bits,
                            group_size=args.group_size)
    except Exception as e:
        sys.exit(f"error: {e}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
