"""Int4 decode-gemv sweep: find why (and fix how) m=1 int4 runs under its
roofline.

The r4 on-chip record: single-stream int4 decode measured 51 tok/s against
a 170 tok/s weights-bound roofline, while int8 (twice the bytes) hits 84.8
— so the m=1 int4 kernel is the bottleneck, not HBM. Working hypothesis
(ops/pallas/quant.py:_kernel4): the per-byte nibble unpack (widen + shifts
+ converts over a [BK2, BN] block) is VPU-bound and its widened
temporaries pressure VMEM; both effects are block-size- and
width-dependent. This tool measures, per decode-critical 8B shape, the
kernel across {block_n} x {block_k} x {int32, int16} unpack variants plus
the XLA fallback and the int8 kernel (the byte-rate ceiling to beat),
reporting achieved packed-GB/s so the gap to the ~819 GB/s v5e HBM peak is
explicit.

Usage:  python -m cake_tpu.tools.int4_sweep [--json-out PATH] [--m M]

One JSON line per row:
  {"k", "n", "variant", "block_n", "block_k", "ms", "gbps", "speedup_vs_xla"}

The winning (block, unpack) per shape is the measured config the kernel's
defaults should adopt (the same measured-crossover discipline as
quant_matmul's m>=16 gate and flash's PREFILL_FLASH_MIN_S).
"""

from __future__ import annotations

import argparse
import json
import sys
from functools import partial

import jax
import jax.numpy as jnp

from cake_tpu.tools.kernel_check import _time_ms


# Llama-3-8B decode linears (in, out): the per-token weight sweep.
SHAPES_8B = [
    (4096, 4096),    # wq / wo
    (4096, 14336),   # w_gate / w_up (the big pair)
    (14336, 4096),   # w_down
]


def sweep(json_out: str | None = None, m: int = 1) -> list:
    # probe the failure-prone setup BEFORE truncating the ledger: a bad
    # pallas import or a wedged device grant must not zero out the
    # previous run's rows (the modules stay cached for _sweep)
    import jax

    from cake_tpu.ops.pallas.quant import quant4_matmul_pallas  # noqa: F401
    from cake_tpu.ops.quant import quant4_matmul_xla  # noqa: F401

    jax.devices()
    # `with` owns the ledger file: a sweep dying mid-shape (OOM, ctrl-C)
    # must not lose buffered rows or leak the fd (cakelint CK-WIRE)
    if json_out:
        with open(json_out, "w") as out_f:
            return _sweep(out_f, m)
    return _sweep(None, m)


def _sweep(out_f, m: int = 1) -> list:
    from cake_tpu.ops.pallas import interpret_default
    from cake_tpu.ops.pallas.quant import (
        quant4_matmul_pallas,
        quant_matmul_pallas,
    )
    from cake_tpu.ops.quant import (
        quant4_matmul_xla,
        quantize_linear,
        quantize_linear4,
    )

    from cake_tpu.ops.pallas.quant import _pick_block

    compiled = not interpret_default()
    dev = jax.devices()[0]
    sys.stderr.write(f"device={dev.device_kind} compiled={compiled} m={m}\n")
    key = jax.random.PRNGKey(0)
    results = []

    def emit(rec):
        results.append(rec)
        print(json.dumps(rec), flush=True)
        if out_f:
            out_f.write(json.dumps(rec) + "\n")
            out_f.flush()

    # The timed loop's data-dependence fold must be shape-agnostic: the
    # default chain adds the (m, n) output into the (m, k) activation,
    # which only broadcasts when n == k — a scalar fold works everywhere.
    def chain(out, a0):
        return a0 + (out.ravel()[0] * 1e-30).astype(a0.dtype)

    # bf16 is the decode activation dtype of record; interpret mode (the
    # CPU smoke path) hits an interpreter bf16-in-scan limitation, so it
    # smokes in f32 — the real measurement is compiled-on-TPU either way.
    act_dt = jnp.bfloat16 if compiled else jnp.float32
    for k, n in SHAPES_8B:
        kx, kw = jax.random.split(jax.random.fold_in(key, k * n))
        x = jax.random.normal(kx, (m, k), act_dt)
        w = jax.random.normal(kw, (k, n), jnp.float32) / jnp.sqrt(k)
        q4 = quantize_linear4(w)
        q8 = quantize_linear(w)
        packed_mb = q4.qp.size / 1e6  # int8 bytes holding two nibbles each

        # baselines: the XLA unpack fallback and the int8 kernel byte rate
        try:
            xla_ms = _time_ms(jax.jit(quant4_matmul_xla), x, q4.qp,
                              q4.scale, chain=chain)
            emit(dict(k=k, n=n, variant="xla", block_n=0, block_k=0,
                      ms=xla_ms, gbps=packed_mb / xla_ms,
                      speedup_vs_xla=1.0))
        except Exception as e:
            sys.stderr.write(f"  k={k} n={n} xla baseline: "
                             f"{type(e).__name__}: {str(e)[:120]}\n")
            xla_ms = None
        try:
            int8_ms = _time_ms(
                jax.jit(partial(quant_matmul_pallas,
                                interpret=not compiled)),
                x, q8.q, q8.scale, chain=chain,
            )
            emit(dict(k=k, n=n, variant="int8_kernel", block_n=0,
                      block_k=0, ms=int8_ms,
                      gbps=2 * packed_mb / int8_ms,  # int8 bytes
                      speedup_vs_xla=(xla_ms / int8_ms) if xla_ms else None))
        except Exception as e:
            sys.stderr.write(f"  k={k} n={n} int8 baseline: "
                             f"{type(e).__name__}: {str(e)[:120]}\n")
            int8_ms = None

        # XLA-native s4: store the quantized values as a jnp.int4 array and
        # let XLA's own int4 support handle the unpack (TPU XLA carries
        # hardware-assisted s4 conversion; if it streams packed bytes this
        # beats any hand-written unpack). Same math as the kernel:
        # y = (x @ w4) * scale with the convert fused into the dot operand.
        try:
            from cake_tpu.ops.quant import unpack_int4

            w4 = jnp.asarray(unpack_int4(q4.qp), jnp.int8).astype(jnp.int4)

            def s4_matmul(x, w4, scale):
                y = jnp.einsum("mk,kn->mn", x, w4.astype(x.dtype),
                               preferred_element_type=jnp.float32)
                return (y * scale).astype(x.dtype)

            s4_ms = _time_ms(jax.jit(s4_matmul), x, w4, q4.scale,
                             chain=chain)
            emit(dict(k=k, n=n, variant="xla_s4", block_n=0, block_k=0,
                      ms=s4_ms, gbps=packed_mb / s4_ms,
                      speedup_vs_xla=(xla_ms / s4_ms) if xla_ms else None))
        except Exception as e:
            sys.stderr.write(f"  k={k} n={n} xla_s4: "
                             f"{type(e).__name__}: {str(e)[:160]}\n")

        # report configs by the blocks that actually EXECUTE: the grid
        # clamps to power-of-2 divisors (_pick_block), so distinct
        # requests can collapse; dedupe on the effective pair and disable
        # the skinny-M widening that would override sub-1024 requests.
        seen = set()
        for unpack in ("int32", "int16"):
            for bn in (512, 1024, 2048):
                for bk in (512, 1024, 2048):
                    if bn > n or bk > k // 2:
                        continue
                    bn_eff = _pick_block(n, bn)
                    bk_eff = _pick_block(k // 2, bk)
                    if (unpack, bn_eff, bk_eff) in seen:
                        continue
                    seen.add((unpack, bn_eff, bk_eff))
                    fn = jax.jit(partial(
                        quant4_matmul_pallas, block_n=bn_eff,
                        block_k=bk_eff, unpack=unpack, skinny_widen=False,
                        interpret=not compiled,
                    ))
                    try:
                        ms = _time_ms(fn, x, q4.qp, q4.scale, chain=chain)
                    except Exception as e:  # Mosaic lowering edge: record
                        sys.stderr.write(
                            f"  k={k} n={n} {unpack} bn={bn_eff} "
                            f"bk={bk_eff}: "
                            f"{type(e).__name__}: {str(e)[:120]}\n")
                        continue
                    emit(dict(k=k, n=n, variant=unpack, block_n=bn_eff,
                              block_k=bk_eff, ms=ms, gbps=packed_mb / ms,
                              speedup_vs_xla=(xla_ms / ms) if xla_ms
                              else None))

        best = max((r for r in results if r["k"] == k and r["n"] == n
                    and r["variant"] in ("int32", "int16")),
                   key=lambda r: r["gbps"], default=None)
        if best:
            sys.stderr.write(
                f"shape {k}x{n}: best {best['variant']} "
                f"bn={best['block_n']} bk={best['block_k']} "
                f"{best['gbps']:.0f} GB/s"
                + (f" (xla {packed_mb / xla_ms:.0f}" if xla_ms else " (")
                + (f", int8 kernel {2 * packed_mb / int8_ms:.0f} int8-GB/s)"
                   if int8_ms else ")")
                + "\n")

    return results


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json-out", default=None)
    ap.add_argument("--m", type=int, default=1)
    args = ap.parse_args()
    sweep(args.json_out, m=args.m)
    return 0


if __name__ == "__main__":
    sys.exit(main())
