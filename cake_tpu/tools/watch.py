"""Live cluster watch: render every worker's status page as a refreshing
terminal table.

The reference's worker is an iOS APP with a live GUI — device name,
assigned layers, connection state, throughput ticking over
(`/root/reference/cake-ios-worker-app/Cake Worker/ContentView.swift:28-56`).
TPU fleets are headless, so cake-tpu workers expose the same information
as a JSON page (`--status-port`, runtime/worker.py ``status()``); this
tool is the interactive view over it — one row per worker, refreshed in
place, with per-interval ops/s and byte rates derived from the counter
deltas (the GUI's ticking numbers).

Usage:
    python -m cake_tpu.tools.watch host1:8090 host2:8090
    python -m cake_tpu.tools.watch --topology topology.yml --port 8090
    ... --interval 2       # refresh period (s)
    ... --once             # one snapshot, no screen control (scripts/CI)
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request


def fetch_status(host: str, timeout: float = 2.0) -> dict:
    """One worker's status dict, or an ``{"error": ...}`` marker row —
    a dead worker must show as DOWN in the table, not kill the watch."""
    url = f"http://{host}/"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except Exception as e:  # connection refused / timeout / bad JSON
        return {"error": str(e)[:80]}


def _human(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0:
            return f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}TiB"


def render(hosts: list[str], snaps: list[dict], prev: dict,
           dt: float) -> str:
    """One table frame. ``prev`` maps host -> last snapshot (for counter
    deltas); mutated in place so the caller just re-calls."""
    hdr = (f"{'worker':<22} {'device':<12} {'layers':<12} {'conns':>5} "
           f"{'ops/s':>8} {'in/s':>10} {'out/s':>10} {'rss':>9} "
           f"{'uptime':>8}")
    lines = [hdr, "-" * len(hdr)]
    for host, s in zip(hosts, snaps):
        if "error" in s:
            # drop the stale snapshot: on recovery the counter delta would
            # span every missed interval but be divided by one dt,
            # inflating the displayed rates N-fold for a frame
            prev.pop(host, None)
            lines.append(f"{host:<22} DOWN: {s['error']}")
            continue
        p = prev.get(host)
        if p and dt > 0:
            ops_s = max(0.0, (s["ops_total"] - p["ops_total"]) / dt)
            in_s = max(0.0, (s["bytes_in"] - p["bytes_in"]) / dt)
            out_s = max(0.0, (s["bytes_out"] - p["bytes_out"]) / dt)
        else:
            ops_s = in_s = out_s = 0.0
        prev[host] = s
        runs = ",".join(f"{a}-{b - 1}" for a, b in s["layer_runs"])
        name = f"{s['name']}@{host}"
        lines.append(
            f"{name:<22.22} {s['device']:<12.12} {runs:<12.12} "
            f"{s['connections_live']:>5} {ops_s:>8.1f} "
            f"{_human(in_s):>10} {_human(out_s):>10} "
            f"{_human(s['rss_bytes']):>9} {s['uptime_s']:>7.0f}s"
        )
    return "\n".join(lines)


def hosts_from_topology(path: str, port: int) -> list[str]:
    """Status hosts from the same topology YAML the cluster runs on: the
    worker's serving address's host + the shared status port."""
    from cake_tpu.parallel.topology import Topology

    topo = Topology.from_path(path)
    return [f"{n.host.rsplit(':', 1)[0]}:{port}"
            for n in topo.nodes.values() if n.host]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("hosts", nargs="*",
                    help="worker status pages as host:port")
    ap.add_argument("--topology", default=None,
                    help="derive hosts from a topology YAML instead")
    ap.add_argument("--port", type=int, default=8090,
                    help="status port for --topology hosts")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (no screen control)")
    args = ap.parse_args(argv)

    hosts = list(args.hosts)
    if args.topology:
        hosts += hosts_from_topology(args.topology, args.port)
    if not hosts:
        ap.error("no hosts: pass host:port arguments or --topology")

    from concurrent.futures import ThreadPoolExecutor

    prev: dict = {}
    last_t = time.monotonic()
    # concurrent fetches bound a frame at max(one fetch) instead of the
    # sum — a few firewalled/hung hosts must not freeze the live table
    pool = ThreadPoolExecutor(max_workers=min(32, len(hosts)))
    while True:
        snaps = list(pool.map(fetch_status, hosts))
        now = time.monotonic()
        frame = render(hosts, snaps, prev, now - last_t)
        last_t = now
        if args.once:
            print(frame)
            return 0 if all("error" not in s for s in snaps) else 1
        # in-place refresh: clear screen + home (plain ANSI, no curses —
        # works over ssh and in dumb terminals with --once as the out)
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
