"""HTTP load generator for the serving plane (stdlib-only).

Drives ``--mode serve``'s ``POST /v1/completions`` with N concurrent
clients, either closed-loop (each client fires its next request the moment
the previous completes — the saturation view) or open-loop (Poisson
arrivals at ``--rate`` req/s regardless of completions — the latency-
under-load view; open loop is the honest one for tail latencies, since a
closed loop self-throttles when the server slows down). Prompts draw from
a ``--prompt-len`` mix of random in-vocab token ids (``prompt_ids`` path:
no tokenizer needed on either side), or from ``--prompt`` literals.

``--workload json`` (ISSUE 8) sends schema-constrained requests
(``response_format: json_schema`` against :data:`JSON_WORKLOAD_SCHEMA`)
and asserts every response's assembled text ``json.loads``-parses —
the end-to-end proof that grammar-constrained decoding produced valid
JSON through the whole HTTP plane. Needs a server-side tokenizer.
Invalid responses land in ``json_invalid`` (nonzero exit).

``--workload churn`` (ISSUE 11) is the admission/retirement regime the
paged KV pool (cake_tpu/kvpool) exists for: Poisson arrivals, a
short/long prompt-length mix, and every Nth client disconnecting
mid-stream (``--disconnect-every``), so slot churn is drivable over
HTTP instead of only in-process.

``--workload mixed-prefill`` (ISSUE 13) is the interference regime the
disaggregated prefill/decode tiers (cake_tpu/disagg) exist for: Poisson
arrivals with a BIMODAL prompt-length mix (``--prompt-len 8,512`` —
chatty short prompts sharing a fleet with long-document ones), every
request streaming. On a mixed fleet the long prefills inflate every
decoding neighbor's TPOT and TTFT p95 is hostage to batch composition;
a tiered fleet isolates them. The report splits TTFT p50/p95 by prompt
bucket (``ttft_ms_by_prompt_len``) so the short-prompt tail is visible
next to the long one.

``--workload mixed-class`` (ISSUE 20) is the SLO-scheduling regime: an
interactive trickle (every 4th request, ``"class": "interactive"``)
under a batch flood (the rest, ``"class": "batch"``), Poisson arrivals,
every request streaming. Under FIFO the interactive TTFT tail is
hostage to however many batch requests queued first; the class-aware
scheduler jumps them (and preempts batch victims to host-RAM spill when
slots are full). The report splits TTFT p50/p95 by class
(``ttft_ms_by_class``) — the ``CAKE_BENCH_SLO=1`` acceptance signal.

``--retry-429`` makes a 429 honor its ``Retry-After`` and resubmit
(bounded) instead of counting a hard rejection — the realistic open-loop
client against a saturated server or gateway. ``--spawn-backends N``
(ISSUE 10) spawns N tiny in-process serve replicas plus a routing
gateway (``cake_tpu/gateway``) and drives the gateway, so one command
smokes the whole loopback fleet.

Prints TTFT / TPOT / end-to-end percentiles and aggregate token
throughput; used by ``make serve-smoke`` / ``make constrain-smoke`` /
``make gateway-smoke`` and the ``CAKE_BENCH_SERVE=1`` /
``CAKE_BENCH_CONSTRAIN=1`` / ``CAKE_BENCH_GATEWAY=1`` bench rows.

Usage:
  python -m cake_tpu.tools.loadgen http://127.0.0.1:8080 \\
      -n 32 -c 4 --max-tokens 64 --prompt-len 8,32,128
  python -m cake_tpu.tools.loadgen http://127.0.0.1:8080 \\
      -n 64 --rate 8 --max-tokens 32        # open loop, 8 req/s Poisson
  python -m cake_tpu.tools.loadgen http://127.0.0.1:8080 \\
      -n 16 --workload json --max-tokens 48  # constrained JSON workload
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time
import urllib.error
import urllib.request


# the --workload json constraint: small, fully bounded (the lowered
# automaton is acyclic, so every constrained stream terminates within
# its token budget), exercises object/integer/boolean paths
JSON_WORKLOAD_SCHEMA = {
    "type": "object",
    "properties": {
        "a": {"type": "integer"},
        "ok": {"type": "boolean"},
    },
    "required": ["a", "ok"],
}


def _percentile(xs: list[float], q: float) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    i = min(len(s) - 1, max(0, int(q * (len(s) - 1) + 0.5)))
    return s[i]


def _one_request(url: str, body: dict, timeout: float,
                 abort_after: int | None = None) -> dict:
    """Fire one streaming completions request; measure TTFT (first SSE
    token event), per-token gaps, and end-to-end wall. Returns a result
    dict ({"error"/"status": ...} on failure). ``abort_after``: walk away
    after that many tokens — the early-disconnect client the churn
    workload injects (the server must reap the slot/KV, not the
    client)."""
    req = urllib.request.Request(
        url.rstrip("/") + "/v1/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    t0 = time.perf_counter()
    out: dict = {"tokens": 0, "ttft_s": None, "gaps_s": [], "ids": [],
                 "text": ""}
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            if not body.get("stream"):
                payload = json.loads(resp.read())
                out["tokens"] = payload["usage"]["completion_tokens"]
                out["ids"] = payload.get("token_ids", [])
                out["text"] = payload.get("text", "")
                out["finish_reason"] = payload.get("finish_reason")
                out["ttft_s"] = (payload["usage"].get("ttft_ms", 0)
                                 or 0) / 1e3
                out["wall_s"] = time.perf_counter() - t0
                return out
            t_last = None
            for raw in resp:
                raw = raw.strip()
                if not raw.startswith(b"data: "):
                    continue
                data = raw[len(b"data: "):]
                if data == b"[DONE]":
                    break
                ev = json.loads(data)
                if "token" in ev:
                    now = time.perf_counter()
                    if t_last is None:
                        out["ttft_s"] = now - t0
                    else:
                        out["gaps_s"].append(now - t_last)
                    t_last = now
                    out["tokens"] += 1
                    out["ids"].append(ev["token"])
                    if ev.get("text"):
                        out["text"] += ev["text"]
                    if abort_after and out["tokens"] >= abort_after:
                        # early disconnect: close mid-stream (the with
                        # block tears the connection down) and leave the
                        # server to cancel + reap the slot
                        out["disconnected"] = True
                        break
                elif "error" in ev:
                    out["error"] = ev["error"]
                    break
                elif ev.get("done"):
                    if ev.get("text"):
                        out["text"] += ev["text"]  # detok tail
                    out["finish_reason"] = ev.get("finish_reason")
            out["wall_s"] = time.perf_counter() - t0
            return out
    except urllib.error.HTTPError as e:
        return {"status": e.code,
                "retry_after": e.headers.get("Retry-After"),
                "wall_s": time.perf_counter() - t0}
    except Exception as e:  # connection refused/reset, timeout, ...
        return {"error": str(e), "wall_s": time.perf_counter() - t0}


def _make_prompts(n: int, lens: list[int], vocab: int, seed: int,
                  literals: list[str]) -> list[dict]:
    """One request-body fragment per planned request: a literal text
    prompt round-robin, or random in-vocab ids from the length mix."""
    rng = random.Random(seed)
    frags = []
    for i in range(n):
        if literals:
            frags.append({"prompt": literals[i % len(literals)]})
        else:
            ln = lens[i % len(lens)]
            frags.append({"prompt_ids": [rng.randrange(1, max(2, vocab))
                                         for _ in range(ln)]})
    return frags


def run_load(url: str, n: int, concurrency: int = 4, max_tokens: int = 32,
             prompt_lens: list[int] | None = None, vocab: int = 256,
             rate: float | None = None, seed: int = 0,
             prompts: list[str] | None = None, stream: bool = True,
             timeout: float = 300.0, workload: str = "text",
             retry_429: bool = False,
             disconnect_every: int | None = None,
             slo_ttft_ms: float | None = None,
             slo_tpot_ms: float | None = None) -> dict:
    """Run the load; returns aggregate stats (also the in-process entry
    the bench row and tests use). ``workload="json"`` attaches the
    schema constraint to every request and json-validates every
    response's text. ``workload="churn"`` is the admission/retirement
    regime (ISSUE 11): Poisson arrivals (defaults ``rate`` to ~2x the
    concurrency when unset), a short/long prompt-length mix (defaults
    the mix to 8,64), and every ``disconnect_every``-th client walking
    away mid-stream (defaults to 4) — the slot-churn traffic shape the
    paged KV pool exists for, drivable over HTTP instead of only
    in-process. ``workload="mixed-prefill"`` is the disagg interference
    regime (ISSUE 13): Poisson arrivals with a bimodal prompt mix
    (defaults to 8,512) — the result gains ``ttft_ms_by_prompt_len``
    so the short-prompt TTFT tail is visible next to the long one.
    ``retry_429`` makes a 429 response honor its ``Retry-After`` and
    resubmit (bounded) instead of counting a hard rejection — the
    honest open-loop behavior against a saturated server or gateway (a
    real client backs off; it does not give up). ``slo_ttft_ms``/
    ``slo_tpot_ms`` (ISSUE 16) judge every completed request against
    per-request latency targets (TPOT as the mean inter-token gap) and
    add an ``slo`` block with **goodput** — the fraction of completed
    requests meeting BOTH set targets — next to the percentile view:
    percentiles say how slow the tail was, goodput says how many users
    got what the SLO promised."""
    if workload not in ("text", "json", "churn", "mixed-prefill",
                        "mixed-class"):
        raise ValueError(f"workload must be 'text', 'json', 'churn', "
                         f"'mixed-prefill' or 'mixed-class', "
                         f"got {workload!r}")
    if workload == "mixed-class":
        # the SLO-scheduling regime (ISSUE 20): an interactive trickle
        # under a batch flood, open loop — the per-class TTFT split is
        # the whole point
        if rate is None:
            rate = max(2.0, 2.0 * concurrency)
        if not stream:
            raise ValueError("workload='mixed-class' measures per-class "
                             "TTFT tails; it needs streaming responses")
    if workload == "mixed-prefill":
        # the disagg interference regime: bimodal prompt lengths under
        # Poisson arrivals (open loop — the honest view of the tail the
        # tier split exists to fix)
        if prompt_lens is None:
            prompt_lens = [8, 512]
        if rate is None:
            rate = max(2.0, 2.0 * concurrency)
        if not stream:
            raise ValueError("workload='mixed-prefill' measures TTFT/"
                             "TPOT tails; it needs streaming responses")
    if workload == "churn":
        # churn shape unless the caller pinned its own knobs (None is the
        # unset sentinel — an explicit 0 really means "never disconnect")
        if prompt_lens is None:
            prompt_lens = [8, 64]
        if rate is None:
            rate = max(2.0, 2.0 * concurrency)
        if disconnect_every is None:
            disconnect_every = 4
        if not stream:
            raise ValueError("workload='churn' needs streaming responses "
                             "(early disconnects abort an SSE stream)")
    disconnect_every = disconnect_every or 0
    frags = _make_prompts(n, prompt_lens or [8], vocab, seed, prompts or [])
    results: list[dict] = [None] * n  # type: ignore[list-item]
    t_start = time.perf_counter()

    def _class_of(i: int) -> str:
        # every 4th request is the interactive trickle; the rest are
        # the batch flood it must cut through
        return "interactive" if i % 4 == 0 else "batch"

    def fire(i: int) -> None:
        body = dict(frags[i], max_tokens=max_tokens, stream=stream)
        if workload == "json":
            body["response_format"] = {"type": "json_schema",
                                       "schema": JSON_WORKLOAD_SCHEMA}
        if workload == "mixed-class":
            body["class"] = _class_of(i)
        abort_after = (2 if disconnect_every
                       and i % disconnect_every == disconnect_every - 1
                       else None)
        r = _one_request(url, body, timeout, abort_after=abort_after)
        tries = 0
        while retry_429 and r.get("status") == 429 and tries < 8:
            try:
                delay = float(r.get("retry_after") or 1.0)
            except ValueError:
                delay = 1.0
            time.sleep(min(max(delay, 0.0), 30.0))
            tries += 1
            r = _one_request(url, body, timeout, abort_after=abort_after)
        if tries:
            r["retries_429"] = tries
        results[i] = r

    if rate:
        # open loop: Poisson arrivals, one thread per in-flight request
        rng = random.Random(seed + 1)
        threads = []
        t_next = time.perf_counter()
        for i in range(n):
            t_next += rng.expovariate(rate)
            delay = t_next - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            th = threading.Thread(target=fire, args=(i,), daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=timeout)
    else:
        # closed loop: `concurrency` clients, each back-to-back
        it = iter(range(n))
        lock = threading.Lock()

        def worker() -> None:
            while True:
                with lock:
                    i = next(it, None)
                if i is None:
                    return
                fire(i)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(concurrency)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=timeout)
    wall = time.perf_counter() - t_start

    done = [r for r in results if r and r.get("tokens")]
    rejected = [r for r in results if r and r.get("status") == 429]
    errors = [r for r in results if r and (
        "error" in r or ("status" in r and r["status"] != 429))]
    disconnected = sum(1 for r in results if r and r.get("disconnected"))
    json_invalid = 0
    if workload == "json":
        for r in done:
            try:
                json.loads(r.get("text") or "")
            except ValueError:
                json_invalid += 1
                r["json_invalid"] = True
    ttfts = [r["ttft_s"] for r in done if r.get("ttft_s") is not None]
    gaps = [g for r in done for g in r.get("gaps_s", ())]
    total_tokens = sum(r["tokens"] for r in done)
    # TTFT split by prompt bucket: with a bimodal mix, the aggregate p95
    # is just the long bucket's p50 — the split is what shows whether
    # short prompts kept their latency next to long ones (the
    # mixed-prefill acceptance signal)
    by_len: dict[int, list[float]] = {}
    for i, r in enumerate(results):
        if r and r.get("tokens") and r.get("ttft_s") is not None:
            ln = len(frags[i].get("prompt_ids")
                     or frags[i].get("prompt", ""))
            by_len.setdefault(ln, []).append(r["ttft_s"])
    ttft_by_len = {
        str(ln): {"p50": round(_percentile(xs, 0.5) * 1e3, 1),
                  "p95": round(_percentile(xs, 0.95) * 1e3, 1),
                  "n": len(xs)}
        for ln, xs in sorted(by_len.items())}
    # TTFT split by class (mixed-class): under FIFO the aggregate hides
    # the interactive tail inside the batch flood's — the split is the
    # CAKE_BENCH_SLO acceptance signal
    ttft_by_class: dict[str, dict] = {}
    if workload == "mixed-class":
        by_cls: dict[str, list[float]] = {}
        for i, r in enumerate(results):
            if r and r.get("tokens") and r.get("ttft_s") is not None:
                by_cls.setdefault(_class_of(i), []).append(r["ttft_s"])
        ttft_by_class = {
            cls: {"p50": round(_percentile(xs, 0.5) * 1e3, 1),
                  "p95": round(_percentile(xs, 0.95) * 1e3, 1),
                  "n": len(xs)}
            for cls, xs in sorted(by_cls.items())}
    slo = None
    if slo_ttft_ms is not None or slo_tpot_ms is not None:
        good = 0
        for r in done:
            ok = True
            if slo_ttft_ms is not None:
                ok &= (r.get("ttft_s") is not None
                       and r["ttft_s"] * 1e3 <= slo_ttft_ms)
            if slo_tpot_ms is not None and r.get("gaps_s"):
                tpot = sum(r["gaps_s"]) / len(r["gaps_s"]) * 1e3
                ok &= tpot <= slo_tpot_ms
            if ok:
                good += 1
            else:
                r["slo_bad"] = True
        slo = {
            **({"ttft_target_ms": slo_ttft_ms}
               if slo_ttft_ms is not None else {}),
            **({"tpot_target_ms": slo_tpot_ms}
               if slo_tpot_ms is not None else {}),
            "good": good,
            # goodput = fraction of ATTEMPTED requests that completed
            # AND met every set target: a 429/error miss is an SLO miss,
            # not a statistical exclusion
            "goodput": round(good / n, 4) if n else 0.0,
        }
    return {
        "requests": n,
        "completed": len(done),
        "rejected_429": len(rejected),
        "retried_429": sum(r.get("retries_429", 0)
                           for r in results if r),
        "errors": len(errors),
        "disconnected": disconnected,
        "json_invalid": json_invalid,
        "wall_s": round(wall, 3),
        "tokens": total_tokens,
        "tok_s": round(total_tokens / wall, 2) if wall > 0 else 0.0,
        "ttft_ms": {
            "p50": round(_percentile(ttfts, 0.5) * 1e3, 1),
            "p95": round(_percentile(ttfts, 0.95) * 1e3, 1),
        },
        "tpot_ms": {
            "p50": round(_percentile(gaps, 0.5) * 1e3, 2),
            "p95": round(_percentile(gaps, 0.95) * 1e3, 2),
        },
        **({"ttft_ms_by_prompt_len": ttft_by_len}
           if len(ttft_by_len) > 1 else {}),
        **({"ttft_ms_by_class": ttft_by_class} if ttft_by_class else {}),
        **({"slo": slo} if slo is not None else {}),
        "results": results,
    }


def _spawn_replica(cfg, params, role: str = "mixed",
                   max_concurrent: int = 2, queue_depth: int = 16,
                   paged: bool = False, transfer: bool = False):
    """One tiny in-process serve replica. Returns ``(server, scheduler,
    transfer_server|None)``. ``paged`` runs the paged-KV engine (needed
    for any KV movement); ``transfer`` opens the import listener."""
    from cake_tpu.ops.sampling import SamplerSettings
    from cake_tpu.runtime.batch_generator import BatchGenerator
    from cake_tpu.serve.api import start_api_server
    from cake_tpu.serve.scheduler import Scheduler

    kw = {"kv_layout": "paged", "kv_page_size": 16} if paged else {}
    gen = BatchGenerator(
        cfg, params,
        settings=SamplerSettings(temperature=0.0, repeat_penalty=1.0),
        **kw)
    sched = Scheduler(gen, queue_depth=queue_depth, role=role)
    sched.start(max_concurrent=max_concurrent, warm_prompt_len=8)
    ts = None
    if transfer:
        from cake_tpu.disagg import TransferServer

        ts = TransferServer(sched).start()
        sched.transfer_port = ts.port
    return start_api_server(sched), sched, ts


class FleetHandle:
    """A dynamically-registered loopback fleet with live resize (ISSUE
    19). Replicas join by POSTing the gateway's ``/v1/fleet/register``
    (no static seeds), :meth:`resize` grows by spawn+register and
    shrinks through the gateway's ``/v1/fleet/drain/<addr>`` rolling-
    restart flow — live sessions migrate to a sibling over the
    KV-transfer plane, so a shrink under load fails zero requests."""

    def __init__(self, gateway, monitor, build_replica):
        self.gateway = gateway
        self.monitor = monitor
        self.url = f"http://127.0.0.1:{gateway.port}"
        self._build = build_replica
        self._stacks: list[tuple] = []  # (server, scheduler, xfer)
        self.events: list[str] = []

    def _post(self, path: str, body: dict) -> dict:
        req = urllib.request.Request(
            self.url + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            return json.loads(resp.read() or b"{}")

    def size(self) -> int:
        return len(self._stacks)

    def grow(self, k: int) -> None:
        for _ in range(k):
            srv, sched, ts = self._build()
            self._stacks.append((srv, sched, ts))
            ack = self._post("/v1/fleet/register", {
                "addr": f"127.0.0.1:{srv.port}",
                **({"transfer_port": sched.transfer_port}
                   if sched.transfer_port else {}),
            })
            self.events.append(f"grow 127.0.0.1:{srv.port} "
                               f"-> {ack.get('name')}")
        # the welcome probe is decisive; give the last joiner a beat
        deadline = time.monotonic() + 10.0
        while (len(self.monitor.routable()) < len(self._stacks)
               and time.monotonic() < deadline):
            time.sleep(0.05)

    def shrink(self, k: int) -> None:
        for _ in range(k):
            if len(self._stacks) <= 1:
                return  # never drain the last replica out from under load
            srv, sched, ts = self._stacks.pop()
            addr = f"127.0.0.1:{srv.port}"
            ack = self._post(f"/v1/fleet/drain/{addr}", {})
            self.events.append(
                f"drain {addr} -> migrate_to {ack.get('migrate_to')}")
            # wait for the replica to run dry (sessions migrated or
            # finished), then tear it down like a clean process exit
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                st = sched.stats()
                if st["queued"] == 0 and st["running"] == 0:
                    break
                time.sleep(0.05)
            srv.drain(timeout_s=15.0)
            if ts is not None:
                ts.stop()
            sched.close()

    def resize(self, m: int) -> None:
        """Grow or drain to ``m`` replicas, live."""
        m = max(1, m)
        if m > len(self._stacks):
            self.grow(m - len(self._stacks))
        elif m < len(self._stacks):
            self.shrink(len(self._stacks) - m)

    def cleanup(self) -> None:
        self.gateway.close()
        self.monitor.stop()
        for srv, sched, ts in self._stacks:
            srv.close()
            if ts is not None:
                ts.stop()
            sched.close()
        self._stacks.clear()


def spawn_elastic_fleet(n: int, max_concurrent: int = 2,
                        queue_depth: int = 16, policy: str = "p2c",
                        max_seq: int = 128) -> FleetHandle:
    """The live-resize demo fleet (ISSUE 19): a gateway with ZERO static
    backends plus ``n`` replicas that join by self-registration. Every
    replica runs the paged engine with a transfer listener, so a shrink
    migrates live sessions to a sibling instead of failing them.
    Returns a :class:`FleetHandle`; call ``.cleanup()`` when done."""
    import jax

    from cake_tpu.gateway.api import start_gateway
    from cake_tpu.gateway.health import HealthMonitor
    from cake_tpu.gateway.policy import make_policy
    from cake_tpu.models import llama
    from cake_tpu.models.config import tiny

    cfg = tiny(max_seq_len=max_seq, eos_token_id=-1)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))

    def build_replica():
        return _spawn_replica(cfg, params, max_concurrent=max_concurrent,
                              queue_depth=queue_depth, paged=True,
                              transfer=True)

    monitor = HealthMonitor([], probe_interval=0.5, lease_ttl_s=3.0,
                            allow_empty=True).start()
    gateway = start_gateway(monitor, make_policy(policy))
    handle = FleetHandle(gateway, monitor, build_replica)
    try:
        handle.grow(n)
    except BaseException:
        handle.cleanup()
        raise
    return handle


def spawn_fleet(n: int, max_concurrent: int = 2, queue_depth: int = 16,
                policy: str = "p2c", roles: list[str] | None = None,
                max_seq: int = 128):
    """Smoke support for the gateway plane: build ``n`` tiny
    random-weight serve replicas IN PROCESS plus a routing gateway in
    front, so one command (``--spawn-backends N``) drives a whole
    loopback fleet with zero setup. Returns ``(gateway, cleanup)`` —
    call ``cleanup()`` when done. ``roles`` (ISSUE 13, aligned with the
    replicas) builds a TIERED fleet: every engine goes paged (KV moves
    between replicas as pool pages), decode replicas get a transfer
    listener, and the gateway's two-stage route engages by itself once
    its prober discovers the tiers — e.g. ``roles=["prefill",
    "decode"]`` is the minimal disagg deployment. Deliberately
    heavyweight imports live here, not at module top: plain loadgen
    against a remote URL stays stdlib-only."""
    import jax

    from cake_tpu.gateway.api import start_gateway
    from cake_tpu.gateway.health import Backend, HealthMonitor
    from cake_tpu.gateway.policy import make_policy
    from cake_tpu.models import llama
    from cake_tpu.models.config import tiny

    if roles is not None:
        if len(roles) != n:
            raise ValueError(f"{len(roles)} roles for {n} replicas")
        bad = [r for r in roles if r not in ("mixed", "prefill", "decode")]
        if bad:
            raise ValueError(f"unknown role(s) {bad}")
    cfg = tiny(max_seq_len=max_seq, eos_token_id=-1)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    stacks = []
    xfer_servers = []
    for i in range(n):
        role = roles[i] if roles is not None else "mixed"
        # tiered fleets run paged engines everywhere (the A/B against a
        # mixed fleet must compare the tier split, not the KV layout)
        srv, sched, ts = _spawn_replica(
            cfg, params, role=role, max_concurrent=max_concurrent,
            queue_depth=queue_depth, paged=roles is not None,
            transfer=role == "decode")
        if ts is not None:
            xfer_servers.append(ts)
        stacks.append((srv, sched))
    backends = [Backend(f"b{i}", f"127.0.0.1:{srv.port}")
                for i, (srv, _) in enumerate(stacks)]
    monitor = HealthMonitor(backends, probe_interval=0.5).start()
    gateway = start_gateway(monitor, make_policy(policy))
    if roles is not None and any(r != "mixed" for r in roles):
        # the two-stage route needs the prober's tier map before the
        # first request (an undiscovered decode tier would silently
        # route classically — and 400 off the prefill replicas)
        deadline = time.monotonic() + 10.0
        want = {r for r in roles if r != "mixed"}
        while time.monotonic() < deadline:
            seen = {b.role for b in monitor.routable()}
            if want <= seen:
                break
            time.sleep(0.05)

    def cleanup() -> None:
        gateway.close()
        monitor.stop()
        for ts in xfer_servers:
            ts.stop()
        for srv, sched in stacks:
            srv.close()
            sched.close()

    return gateway, cleanup


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="cake-loadgen",
        description="closed/open-loop HTTP load generator for --mode serve",
    )
    p.add_argument("url", nargs="?", default=None,
                   help="server base URL, e.g. http://127.0.0.1:8080 "
                        "(omitted with --spawn-backends: the spawned "
                        "gateway is driven instead)")
    p.add_argument("-n", "--requests", type=int, default=16)
    p.add_argument("-c", "--concurrency", type=int, default=4,
                   help="closed-loop client count (ignored with --rate)")
    p.add_argument("--rate", type=float, default=None,
                   help="open-loop Poisson arrival rate (req/s); omit for "
                        "closed loop")
    p.add_argument("--max-tokens", type=int, default=32, dest="max_tokens")
    p.add_argument("--prompt-len", default=None, dest="prompt_len",
                   help="comma-separated prompt-length mix for random "
                        "prompt_ids requests (cycled per request; "
                        "default 8, or 8,64 for --workload churn)")
    p.add_argument("--vocab", type=int, default=256,
                   help="vocab bound for the random prompt ids")
    p.add_argument("--prompt", action="append", default=[],
                   help="literal text prompt (repeatable; needs a "
                        "server-side tokenizer; overrides --prompt-len)")
    p.add_argument("--no-stream", action="store_true",
                   help="unary JSON responses instead of SSE")
    p.add_argument("--workload", choices=["text", "json", "churn",
                                          "mixed-prefill", "mixed-class"],
                   default="text",
                   help="json: schema-constrained requests "
                        "(response_format json_schema), responses "
                        "asserted json.loads-parseable. churn: the "
                        "admission/retirement regime — Poisson arrivals "
                        "(--rate defaults to 2x concurrency), a "
                        "short/long prompt mix (--prompt-len defaults "
                        "to 8,64), every 4th client disconnecting "
                        "mid-stream (--disconnect-every). "
                        "mixed-prefill: the disagg interference regime "
                        "— Poisson arrivals with a bimodal prompt mix "
                        "(--prompt-len defaults to 8,512); the report "
                        "splits TTFT by prompt bucket. mixed-class: the "
                        "SLO-scheduling regime — an interactive trickle "
                        "(every 4th request) under a batch flood, "
                        "Poisson arrivals; the report splits TTFT by "
                        "class (ttft_ms_by_class)")
    p.add_argument("--disconnect-every", type=int, default=None,
                   dest="disconnect_every", metavar="N",
                   help="every Nth request walks away after 2 tokens "
                        "(0 = never; churn workload defaults to 4) — "
                        "the server must reap the slot and its KV")
    p.add_argument("--retry-429", action="store_true", dest="retry_429",
                   help="honor Retry-After on a 429 and resubmit "
                        "(bounded) instead of counting a hard rejection "
                        "— the honest open-loop client behavior")
    p.add_argument("--spawn-backends", type=int, default=None,
                   dest="spawn_backends", metavar="N",
                   help="smoke mode: spawn N tiny in-process serve "
                        "replicas plus a routing gateway and drive the "
                        "gateway (no url needed) — one command exercises "
                        "the whole loopback fleet")
    p.add_argument("--resize-to", type=int, default=None, dest="resize_to",
                   metavar="M",
                   help="with --spawn-backends N: the live-resize demo — "
                        "grow the fleet to M replicas mid-load (dynamic "
                        "self-registration, no static seeds) and drain "
                        "back to N, migrating live sessions to siblings; "
                        "the run must complete with zero failed requests")
    p.add_argument("--spawn-roles", default=None, dest="spawn_roles",
                   metavar="ROLE,...",
                   help="with --spawn-backends: per-replica roles "
                        "(mixed|prefill|decode, comma-separated, count "
                        "must match) — 'prefill,decode' spawns the "
                        "minimal tiered fleet and the gateway's "
                        "two-stage route engages by itself")
    p.add_argument("--slo-ttft-ms", type=float, default=None,
                   dest="slo_ttft_ms", metavar="MS",
                   help="per-request TTFT target: the report gains an "
                        "slo block with goodput (fraction of requests "
                        "completing AND meeting every set target)")
    p.add_argument("--slo-tpot-ms", type=float, default=None,
                   dest="slo_tpot_ms", metavar="MS",
                   help="per-request mean TPOT target (judged with "
                        "--slo-ttft-ms: a request must meet both)")
    p.add_argument("--slo-goodput-min", type=float, default=None,
                   dest="slo_goodput_min", metavar="FRAC",
                   help="CI gate: exit nonzero when goodput falls below "
                        "this fraction (needs an --slo-* target)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--timeout", type=float, default=300.0)
    args = p.parse_args(argv)
    if args.spawn_backends is not None and args.spawn_backends < 1:
        p.error("--spawn-backends must be >= 1")
    if args.slo_goodput_min is not None and (args.slo_ttft_ms is None
                                             and args.slo_tpot_ms is None):
        p.error("--slo-goodput-min needs --slo-ttft-ms and/or "
                "--slo-tpot-ms (there is no goodput without a target)")
    if args.url is None and args.spawn_backends is None:
        p.error("a server url is required (or --spawn-backends N)")
    if args.resize_to is not None:
        if args.spawn_backends is None:
            p.error("--resize-to needs --spawn-backends")
        if args.resize_to < 1:
            p.error("--resize-to must be >= 1")
        if args.spawn_roles is not None:
            p.error("--resize-to drives role-less (mixed) replicas; it "
                    "is mutually exclusive with --spawn-roles")
    roles = None
    if args.spawn_roles is not None:
        if args.spawn_backends is None:
            p.error("--spawn-roles needs --spawn-backends")
        roles = [r.strip() for r in args.spawn_roles.split(",")
                 if r.strip()]
        if len(roles) != args.spawn_backends:
            p.error(f"--spawn-roles lists {len(roles)} roles for "
                    f"--spawn-backends {args.spawn_backends}")
    lens = ([int(x) for x in args.prompt_len.split(",") if x.strip()]
            if args.prompt_len else None)
    url, cleanup, handle, resizer = args.url, None, None, None
    if args.spawn_backends:
        if args.resize_to is not None:
            handle = spawn_elastic_fleet(args.spawn_backends)
            cleanup = handle.cleanup
            url = args.url or handle.url

            def _resize_cycle() -> None:
                # resize up mid-load, then drain back down, still under
                # load — the zero-failed-requests rolling cycle
                time.sleep(1.0)
                handle.resize(args.resize_to)
                time.sleep(2.0)
                handle.resize(args.spawn_backends)

            resizer = threading.Thread(target=_resize_cycle, daemon=True)
            resizer.start()
        else:
            gateway, cleanup = spawn_fleet(args.spawn_backends, roles=roles)
            url = args.url or f"http://127.0.0.1:{gateway.port}"
    try:
        stats = run_load(
            url, args.requests, concurrency=args.concurrency,
            max_tokens=args.max_tokens, prompt_lens=lens, vocab=args.vocab,
            rate=args.rate, seed=args.seed, prompts=args.prompt,
            stream=not args.no_stream, timeout=args.timeout,
            workload=args.workload, retry_429=args.retry_429,
            disconnect_every=args.disconnect_every,
            slo_ttft_ms=args.slo_ttft_ms, slo_tpot_ms=args.slo_tpot_ms,
        )
    finally:
        if resizer is not None:
            resizer.join(timeout=60)
        if cleanup is not None:
            cleanup()
    stats = dict(stats)
    stats.pop("results")
    if handle is not None:
        stats["fleet_events"] = handle.events
    print(json.dumps(stats, indent=1))
    if (args.slo_goodput_min is not None
            and stats.get("slo", {}).get("goodput", 0.0)
            < args.slo_goodput_min):
        print(f"SLO gate failed: goodput {stats['slo']['goodput']} < "
              f"{args.slo_goodput_min}", file=sys.stderr)
        return 1
    return 0 if stats["errors"] == 0 and stats["json_invalid"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
